"""Shared host-side predicate evaluation over dictionary-coded sources.

One implementation of the tag-predicate semantics used by every raw
(row-retrieval) path — measure._raw_rows, stream scans — so the code
conventions (-1 = literal not in dictionary, -2 = column absent from the
source) cannot drift between engines.  The device aggregate path encodes
the same semantics in measure_exec's kernel lowering.
"""

from __future__ import annotations

import numpy as np

from banyandb_tpu.api.model import Condition
from banyandb_tpu.api.schema import TagType
from banyandb_tpu.storage.part import ColumnData


def tag_value_bytes(v) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode()
    if isinstance(v, int):
        return v.to_bytes(8, "little", signed=True)
    raise TypeError(f"unsupported tag literal {type(v)}")


def decode_tag_value(raw: bytes, tag_type: TagType):
    if tag_type == TagType.INT:
        return int.from_bytes(raw, "little", signed=True) if raw else 0
    if tag_type == TagType.STRING:
        return raw.decode(errors="replace")
    return raw


_RANGE_OPS = {"lt", "le", "gt", "ge"}

_WORD_RE = __import__("re").compile(r"[0-9A-Za-z]+")


def analyze(analyzer: str, text: str) -> set[str]:
    """Tokenize per the reference's index-rule analyzers (bluge analogs,
    pkg/index/analyzer): url/simple/standard split on non-alphanumerics
    and lowercase; keyword keeps the whole string as one term."""
    if analyzer == "keyword":
        return {text}
    return {t.lower() for t in _WORD_RE.findall(text)}


def range_lut(op: str, literal, values: list, tag_type=None) -> np.ndarray:
    """bool LUT over DISTINCT dictionary values for a range predicate:
    numeric compare for int literals (INT tags store int64 LE; a numeric
    literal against a non-INT tag is a schema error), bytes-lexicographic
    for strings.  Shared by the host row path and the device kernel's
    LUT lowering so the two cannot drift."""
    import operator

    opf = {
        "lt": operator.lt, "le": operator.le,
        "gt": operator.gt, "ge": operator.ge,
    }[op]
    if isinstance(literal, int) and not isinstance(literal, bool):
        if tag_type is not None and tag_type != TagType.INT:
            raise TypeError(f"numeric range op {op} on non-INT tag")
        dec: list = [
            int.from_bytes(v, "little", signed=True) if v else 0
            for v in values
        ]
        lit = literal
    else:
        dec = values
        lit = tag_value_bytes(literal)
    return np.fromiter(
        (opf(x, lit) for x in dec), dtype=bool, count=len(dec)
    )


def match_lut(c: Condition, analyzers, values: list) -> np.ndarray:
    """bool LUT over DISTINCT dictionary values for a MATCH predicate.

    An index rule with an analyzer is mandatory (ref
    pkg/index/inverted/query.go:371); match_option.analyzer only
    OVERRIDES the rule's analyzer, it cannot substitute for the rule."""
    if not isinstance(c.value, str):
        raise TypeError("MATCH requires a string literal")
    rule_analyzer = (analyzers or {}).get(c.name)
    if rule_analyzer is None:
        raise ValueError(
            f"an index rule with an analyzer is mandatory for MATCH on "
            f"tag {c.name!r}"
        )
    analyzer = getattr(c, "match_analyzer", "") or rule_analyzer
    q = analyze(analyzer, c.value)
    want_all = getattr(c, "match_op", "or") == "and"
    return np.fromiter(
        (
            (
                q <= analyze(analyzer, v.decode(errors="replace"))
                if want_all
                else bool(q & analyze(analyzer, v.decode(errors="replace")))
            )
            for v in values
        ),
        dtype=bool,
        count=len(values),
    )


def _code_lut_mask(col: np.ndarray, lut: np.ndarray) -> np.ndarray:
    """bool mask from a per-dict-code LUT; sentinel codes (-1/-2) miss."""
    n = len(lut)
    if n == 0:
        return np.zeros(col.shape, dtype=bool)
    ok = (col >= 0) & (col < n)
    return np.where(ok, lut[np.clip(col, 0, n - 1)], False)


def _cond_mask(
    src: ColumnData, c: Condition, analyzers=None, tag_types=None
) -> np.ndarray:
    """bool[n] mask for one condition over dictionary codes.

    `analyzers`: tag -> analyzer name from the measure's bound index
    rules — mandatory context for MATCH (the reference errors on MATCH
    without an index rule, pkg/index/inverted/query.go:371).
    `tag_types`: tag -> TagType for schema checks on range literals."""
    col = src.tags.get(c.name)
    if col is None:
        # Source predates the tag: the "absent" sentinel (-2) misses
        # both real codes and the -1 "literal unknown" code.
        col = np.full(src.ts.shape, -2, dtype=np.int32)
    d = src.dicts.get(c.name, [])
    lut = {v: i for i, v in enumerate(d)}
    if c.op == "eq":
        return col == lut.get(tag_value_bytes(c.value), -1)
    if c.op == "ne":
        return col != lut.get(tag_value_bytes(c.value), -1)
    if c.op in ("in", "not_in"):
        codes = {lut.get(tag_value_bytes(v), -1) for v in c.value}
        inmask = np.isin(col, list(codes))
        return inmask if c.op == "in" else ~inmask
    if c.op in _RANGE_OPS:
        return _code_lut_mask(
            col,
            range_lut(c.op, c.value, list(d), (tag_types or {}).get(c.name)),
        )
    if c.op == "match":
        return _code_lut_mask(col, match_lut(c, analyzers, list(d)))
    raise NotImplementedError(f"raw-path op {c.op}")


def row_mask(
    src: ColumnData,
    conds: list[Condition],
    begin_millis: int,
    end_millis: int,
    analyzers=None,
    tag_types=None,
) -> np.ndarray:
    """bool[n] time-range + AND'ed tag-predicate mask over one source."""
    mask = (src.ts >= begin_millis) & (src.ts < end_millis)
    for c in conds:
        mask &= _cond_mask(src, c, analyzers, tag_types)
    return mask


def criteria_mask(
    src: ColumnData,
    criteria,
    begin_millis: int,
    end_millis: int,
    analyzers=None,
    tag_types=None,
) -> np.ndarray:
    """bool[n] time-range + FULL criteria-tree mask (AND/OR) — the host
    twin of the device expr lowering (measure_exec._lower_criteria)."""
    from banyandb_tpu.api.model import LogicalExpression

    mask = (src.ts >= begin_millis) & (src.ts < end_millis)
    if criteria is None:
        return mask

    def walk(node) -> np.ndarray:
        if isinstance(node, Condition):
            return _cond_mask(src, node, analyzers, tag_types)
        assert isinstance(node, LogicalExpression), node
        left, right = walk(node.left), walk(node.right)
        return (left & right) if node.op == "and" else (left | right)

    return mask & walk(criteria)
