"""Shared host-side predicate evaluation over dictionary-coded sources.

One implementation of the tag-predicate semantics used by every raw
(row-retrieval) path — measure._raw_rows, stream scans — so the code
conventions (-1 = literal not in dictionary, -2 = column absent from the
source) cannot drift between engines.  The device aggregate path encodes
the same semantics in measure_exec's kernel lowering.
"""

from __future__ import annotations

import numpy as np

from banyandb_tpu.api.model import Condition
from banyandb_tpu.api.schema import TagType
from banyandb_tpu.storage.part import ColumnData


def tag_value_bytes(v) -> bytes:
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode()
    if isinstance(v, int):
        return v.to_bytes(8, "little", signed=True)
    raise TypeError(f"unsupported tag literal {type(v)}")


def decode_tag_value(raw: bytes, tag_type: TagType):
    if tag_type == TagType.INT:
        return int.from_bytes(raw, "little", signed=True) if raw else 0
    if tag_type == TagType.STRING:
        return raw.decode(errors="replace")
    return raw


def _cond_mask(src: ColumnData, c: Condition) -> np.ndarray:
    """bool[n] mask for one condition over dictionary codes."""
    col = src.tags.get(c.name)
    if col is None:
        # Source predates the tag: the "absent" sentinel (-2) misses
        # both real codes and the -1 "literal unknown" code.
        col = np.full(src.ts.shape, -2, dtype=np.int32)
    d = src.dicts.get(c.name, [])
    lut = {v: i for i, v in enumerate(d)}
    if c.op == "eq":
        return col == lut.get(tag_value_bytes(c.value), -1)
    if c.op == "ne":
        return col != lut.get(tag_value_bytes(c.value), -1)
    if c.op in ("in", "not_in"):
        codes = {lut.get(tag_value_bytes(v), -1) for v in c.value}
        inmask = np.isin(col, list(codes))
        return inmask if c.op == "in" else ~inmask
    raise NotImplementedError(f"raw-path op {c.op}")


def row_mask(
    src: ColumnData,
    conds: list[Condition],
    begin_millis: int,
    end_millis: int,
) -> np.ndarray:
    """bool[n] time-range + AND'ed tag-predicate mask over one source."""
    mask = (src.ts >= begin_millis) & (src.ts < end_millis)
    for c in conds:
        mask &= _cond_mask(src, c)
    return mask


def criteria_mask(
    src: ColumnData,
    criteria,
    begin_millis: int,
    end_millis: int,
) -> np.ndarray:
    """bool[n] time-range + FULL criteria-tree mask (AND/OR) — the host
    twin of the device expr lowering (measure_exec._lower_criteria)."""
    from banyandb_tpu.api.model import LogicalExpression

    mask = (src.ts >= begin_millis) & (src.ts < end_millis)
    if criteria is None:
        return mask

    def walk(node) -> np.ndarray:
        if isinstance(node, Condition):
            return _cond_mask(src, node)
        assert isinstance(node, LogicalExpression), node
        left, right = walk(node.left), walk(node.right)
        return (left & right) if node.op == "and" else (left | right)

    return mask & walk(criteria)
