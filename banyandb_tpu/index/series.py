"""Per-segment series index: entity tags -> seriesID.

Analog of the reference's seg-.../sidx Bluge store
(banyand/internal/storage/index.go, IndexDB surface storage.go:101:
Insert/Update/Search/SearchWithoutSeries).  Each series is one doc keyed
by seriesID whose keyword fields are the *indexed* tag values; index-mode
measures additionally store whole data points here (one doc per point,
SearchWithoutSeries short-circuit at query, measure/query.go:506).
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable, Mapping, Optional

import numpy as np

from banyandb_tpu.index.inverted import (
    And,
    Doc,
    InvertedIndex,
    Query,
    TermQuery,
)


class SeriesIndex:
    """entity/tag docs for one (group, segment)."""

    def __init__(self, path: Optional[str | Path] = None):
        self._idx = InvertedIndex(path)

    def insert_series(
        self, series_id: int, tag_values: Mapping[str, bytes]
    ) -> None:
        """Register (idempotently) a series with its indexed tag values.
        The existence probe is contains() — no doc materialisation on
        the per-data-point write hot path."""
        if not self._idx.contains(series_id):
            self._idx.insert([Doc(doc_id=series_id, keywords=dict(tag_values))])

    def update_series(
        self, series_id: int, tag_values: Mapping[str, bytes]
    ) -> None:
        self._idx.insert([Doc(doc_id=series_id, keywords=dict(tag_values))])

    def search(self, query: Query = None, limit: Optional[int] = None) -> np.ndarray:
        """-> matching seriesID array (storage.go IndexDB.Search analog)."""
        return self._idx.search(query, limit)

    def search_entity(self, entity: Mapping[str, bytes]) -> np.ndarray:
        """Exact entity lookup via an AND of term queries."""
        q = And(tuple(TermQuery(k, v) for k, v in entity.items()))
        return self._idx.search(q)

    def tags_of(self, series_id: int) -> Optional[Mapping[str, bytes]]:
        doc = self._idx.get(series_id)
        return doc.keywords if doc else None

    def persist(self) -> None:
        self._idx.persist()

    def reclaim(self) -> None:
        """Persist + release memory; reloads lazily on next access."""
        self._idx.reclaim()

    def __len__(self) -> int:
        return len(self._idx)
