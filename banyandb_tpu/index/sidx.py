"""sidx: ordered secondary index as a part-based store.

Analog of the reference's sidx subsystem
(/root/reference/banyand/internal/sidx/interfaces.go:58 — a store with
its own mem -> flush -> merge part lifecycle keyed by a user-provided
int64 ordering key), replacing round 1's in-memory sorted projections.
Elements are (key, payload) pairs; parts reuse the columnar part format
with the ordering key in the timestamp column (PartWriter sorts rows by
(series=0, key) and records per-block [min, max] key bounds), so
range queries prune whole blocks by key range and stream the survivors
in key order via a k-way merge across parts.

Durability mirrors a TSDB shard: immutable part dirs + a snapshot file
listing live parts; flush is the commit point; merge rewrites victims
into one part (pure concatenation — no version dedup: equal keys are
distinct elements).
"""

from __future__ import annotations

import heapq
import json
import threading
from pathlib import Path
from typing import Iterator, Optional

import numpy as np

from banyandb_tpu.storage.part import Part, PartWriter
from banyandb_tpu.utils import fs

SNAPSHOT = "sidx-snapshot.snp"


class SidxStore:
    def __init__(self, root: str | Path):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        self._merge_lock = threading.Lock()  # one merge at a time
        self._flush_lock = threading.Lock()  # one flush at a time (two
        # concurrent flushes would duplicate the mem prefix, then the
        # double trim deletes elements that reached no part)
        self._mem_keys: list[int] = []
        self._mem_payloads: list[bytes] = []
        self._epoch = 0
        self._parts: dict[str, Part] = {}
        self.last_blocks_read = 0  # query instrumentation (tests/bench)
        self._load_snapshot()

    # -- lifecycle ----------------------------------------------------------
    def _load_snapshot(self) -> None:
        import shutil

        snp = self.root / SNAPSHOT
        listed: set[str] = set()
        if snp.exists():
            data = fs.read_json(snp)
            self._epoch = data["epoch"]
            listed = set(data["parts"])
            for name in data["parts"]:
                pdir = self.root / name
                if pdir.exists():
                    self._parts[name] = Part(pdir)
        # a part dir NOT in the snapshot is a crash-orphan (staged flush
        # never committed, or interrupted merge temp): remove it so the
        # store reopens exactly at its last published snapshot
        for pdir in self.root.iterdir():
            if pdir.is_dir() and pdir.name not in listed:
                shutil.rmtree(pdir, ignore_errors=True)

    def _publish(self) -> None:
        fs.atomic_write_json(
            self.root / SNAPSHOT,
            {"epoch": self._epoch, "parts": sorted(self._parts)},
        )

    def insert(self, key: int, payload: bytes) -> None:
        with self._lock:
            self._mem_keys.append(int(key))
            self._mem_payloads.append(payload)

    def __len__(self) -> int:
        with self._lock:
            n = len(self._mem_keys)
        return n + sum(p.total_count for p in self._parts.values())

    def flush(self) -> Optional[str]:
        txn = self.prepare_flush()
        if txn is None:
            return None
        return txn.commit()

    def prepare_flush(self) -> Optional["SidxFlushTxn"]:
        """Stage a flush WITHOUT publishing (PrepareFlushed analog,
        /root/reference/banyand/internal/sidx/interfaces.go:37): the part
        is written to disk but the snapshot is untouched until commit(),
        so a host engine can order the sidx commit point relative to its
        own store's publish.  Holds the flush lock until commit/abort —
        exactly one staged flush can be outstanding.

        Crash semantics: an unpublished part dir is an orphan; reopen
        removes it (not listed in the snapshot), as if the flush never
        happened."""
        self._flush_lock.acquire()
        try:
            # mem is only TRIMMED at commit (same lock), so a concurrent
            # range_query always sees every element in exactly one of
            # (mem prefix, new part) — no invisible window mid-flush.
            with self._lock:
                if not self._mem_keys:
                    self._flush_lock.release()
                    return None
                keys = list(self._mem_keys)
                payloads = list(self._mem_payloads)
                self._epoch += 1
                name = f"part-{self._epoch:016x}"
            n = len(keys)
            PartWriter.write(
                self.root / name,
                ts=np.asarray(keys, dtype=np.int64),
                series=np.zeros(n, dtype=np.int64),
                version=np.zeros(n, dtype=np.int64),
                tag_codes={},
                tag_dicts={},
                fields={},
                extra_meta={"sidx": True},
                payloads=payloads,
            )
            return SidxFlushTxn(self, name, n)
        except BaseException:
            import shutil

            # a half-written part dir is garbage now, not just at the
            # next reopen's orphan sweep
            try:
                shutil.rmtree(self.root / name, ignore_errors=True)
            except NameError:
                pass  # failed before the part name existed
            self._flush_lock.release()
            raise

    def _commit_staged(self, name: str, n: int) -> str:
        try:
            with self._lock:
                # Open + publish the part BEFORE trimming the mem prefix:
                # if either raises (bad metadata, disk full on publish) the
                # elements are still mem-resident and the staged dir is
                # just an orphan for the reopen sweep — nothing is lost.
                part = Part(self.root / name)
                self._parts[name] = part
                try:
                    self._publish()
                except BaseException:
                    del self._parts[name]
                    raise
                del self._mem_keys[:n]
                del self._mem_payloads[:n]
            return name
        finally:
            self._flush_lock.release()

    def _abort_staged(self, name: str) -> None:
        import shutil

        try:
            shutil.rmtree(self.root / name, ignore_errors=True)
            # the epoch bump is NOT rolled back: part names stay unique
        finally:
            self._flush_lock.release()

    def merge(self, max_parts: int = 8) -> Optional[str]:
        """Rewrite all parts into one when the count passes max_parts.
        Pure concatenation (the part writer re-sorts by key): equal keys
        are distinct elements and are all preserved."""
        if not self._merge_lock.acquire(blocking=False):
            return None  # another merge round is running
        try:
            return self._merge_locked(max_parts)
        finally:
            self._merge_lock.release()

    def _merge_locked(self, max_parts: int) -> Optional[str]:
        import os
        import shutil
        import uuid

        with self._lock:
            victims = list(self._parts.values())
        if len(victims) < max_parts:
            return None
        keys_l, payloads = [], []
        for p in victims:
            cols = p.read(
                range(len(p.blocks)), want_payload=True, cached=False
            )
            keys_l.append(cols.ts)
            payloads.extend(cols.payloads or [])
        keys = np.concatenate(keys_l)
        tmp = self.root / f".tmp-merge-{uuid.uuid4().hex}"
        shutil.rmtree(tmp, ignore_errors=True)
        PartWriter.write(
            tmp,
            ts=keys,
            series=np.zeros(len(keys), dtype=np.int64),
            version=np.zeros(len(keys), dtype=np.int64),
            tag_codes={},
            tag_dicts={},
            fields={},
            extra_meta={"sidx": True},
            payloads=payloads,
        )
        with self._lock:
            if any(v.name not in self._parts for v in victims):
                shutil.rmtree(tmp, ignore_errors=True)
                return None
            self._epoch += 1
            name = f"part-{self._epoch:016x}"
            os.rename(tmp, self.root / name)
            for v in victims:
                del self._parts[v.name]
            self._parts[name] = Part(self.root / name)
            self._publish()
        for v in victims:
            shutil.rmtree(v.dir, ignore_errors=True)
        return name

    # -- query --------------------------------------------------------------
    def _part_iter(
        self, part: Part, lo: Optional[int], hi: Optional[int], asc: bool
    ) -> Iterator[tuple[int, bytes]]:
        """Stream (key, payload) from one part in key order, reading one
        block at a time; blocks outside [lo, hi] are never read."""
        blocks = part.select_blocks(
            lo if lo is not None else -(1 << 62),
            (hi + 1) if hi is not None else (1 << 62),
        )
        if not asc:
            blocks = list(reversed(blocks))
        for bid in blocks:
            self.last_blocks_read += 1
            cols = part.read([bid], want_payload=True)
            keys = cols.ts
            order = range(len(keys)) if asc else range(len(keys) - 1, -1, -1)
            for i in order:
                k = int(keys[i])
                if lo is not None and k < lo:
                    continue
                if hi is not None and k > hi:
                    continue
                yield k, (cols.payloads[i] if cols.payloads else b"")

    def range_query(
        self,
        lo: Optional[int] = None,
        hi: Optional[int] = None,
        *,
        asc: bool = True,
        limit: Optional[int] = None,
    ) -> list[tuple[int, bytes]]:
        """Elements with key in [lo, hi], globally key-ordered across mem
        + all parts (k-way heap merge of per-part block streams).

        A concurrent merge can GC a victim part dir mid-stream; that
        read raises FileNotFoundError and the query retries against the
        fresh snapshot (the repo's standard retryable-snapshot-miss
        contract)."""
        for attempt in range(3):
            try:
                return self._range_query_once(lo, hi, asc=asc, limit=limit)
            except FileNotFoundError:
                if attempt == 2:
                    raise
        raise AssertionError("unreachable")

    def _range_query_once(
        self,
        lo: Optional[int],
        hi: Optional[int],
        *,
        asc: bool,
        limit: Optional[int],
    ) -> list[tuple[int, bytes]]:
        self.last_blocks_read = 0
        with self._lock:
            parts = list(self._parts.values())
            mem = sorted(
                (
                    (k, p)
                    for k, p in zip(self._mem_keys, self._mem_payloads)
                    if (lo is None or k >= lo) and (hi is None or k <= hi)
                ),
                reverse=not asc,
            )
        streams = [self._part_iter(p, lo, hi, asc) for p in parts]
        streams.append(iter(mem))
        merged = heapq.merge(
            *streams, key=lambda kp: kp[0] if asc else -kp[0]
        )
        out = []
        for kp in merged:
            out.append(kp)
            if limit is not None and len(out) >= limit:
                break
        return out


class SidxFlushTxn:
    """One staged sidx flush.  commit() publishes the part in the
    snapshot and trims the flushed mem prefix; abort() deletes the
    unpublished part dir.  Exactly one of the two must be called."""

    def __init__(self, store: SidxStore, name: str, n: int):
        self._store = store
        self.name = name
        self._n = n
        self._done = False

    def commit(self) -> str:
        assert not self._done, "txn already finished"
        self._done = True
        return self._store._commit_staged(self.name, self._n)

    def abort(self) -> None:
        assert not self._done, "txn already finished"
        self._done = True
        self._store._abort_staged(self.name)


def encode_ref(trace_id: str, ts_millis: int) -> bytes:
    """Payload for trace ordered queries: id + timestamp."""
    return json.dumps([trace_id, ts_millis]).encode()


def decode_ref(payload: bytes) -> tuple[str, int]:
    tid, ts = json.loads(payload)
    return tid, int(ts)
