"""Per-part element index + skipping blooms for streams.

Analog of the reference's stream element index and .tff bloom files
(/root/reference/banyand/stream/index.go + banyand/internal/storage's
tagFamilyFilter): index rules (database/v1 IndexRule) of type

- ``inverted``: a per-part value -> block-ids posting sidecar
  (``eidx_<tag>.bin``) so equality/IN predicates read only blocks that
  contain a matching element;
- ``skipping``: a per-block Bloom filter sidecar (``tff_<tag>.bin``)
  testing membership per block — smaller than postings, probabilistic.

Sidecars are built right after a part is written (flush AND merge) and
read lazily at query time; a part without sidecars (older part, failed
build) simply scans all time-selected blocks — pruning is strictly an
optimization, never a correctness dependency.
"""

from __future__ import annotations

import json
import struct
from pathlib import Path
from typing import Iterable, Optional

import numpy as np

from banyandb_tpu.storage.part import Part
from banyandb_tpu.utils import compress as zst
from banyandb_tpu.utils import fs
from banyandb_tpu.utils.bloom import Bloom


def build_part_index(
    part_dir: str | Path,
    inverted_tags: Iterable[str] = (),
    skipping_tags: Iterable[str] = (),
) -> list[str]:
    """Build sidecars for one immutable part; -> sidecar filenames."""
    part_dir = Path(part_dir)
    part = Part(part_dir)
    tags_present = set(part.meta.get("tags", ()))
    inv = [t for t in inverted_tags if t in tags_present]
    skp = [t for t in skipping_tags if t in tags_present]
    need = sorted(set(inv) | set(skp))
    if not need:
        return []
    # one decode pass per block covering ALL indexed tags (not per-tag)
    postings: dict[str, dict[int, set[int]]] = {t: {} for t in inv}
    blooms: dict[str, list[bytes]] = {t: [] for t in skp}
    dicts = {t: part.dict_for(t) for t in skp}
    for bid in range(len(part.blocks)):
        cols = part.read([bid], tags=need, cached=False)
        for t in inv:
            for code in np.unique(cols.tags[t]).tolist():
                postings[t].setdefault(code, set()).add(bid)
        for t in skp:
            codes = np.unique(cols.tags[t])
            bl = Bloom(max(len(codes), 1))
            d = dicts[t]
            for code in codes.tolist():
                if 0 <= code < len(d):
                    bl.add(d[code])
            blooms[t].append(bl.to_bytes())

    built: list[str] = []
    for t in inv:
        payload = {str(code): sorted(bids) for code, bids in postings[t].items()}
        fname = f"eidx_{t}.bin"
        fs.atomic_write(
            part_dir / fname, zst.compress(json.dumps(payload).encode())
        )
        built.append(fname)
    for t in skp:
        fname = f"tff_{t}.bin"
        blob = bytearray()
        blob.extend(struct.pack("<I", len(blooms[t])))
        for b in blooms[t]:
            blob.extend(struct.pack("<I", len(b)))
            blob.extend(b)
        fs.atomic_write(part_dir / fname, bytes(blob))
        built.append(fname)
    return built


def _sidecar_cache(part: Part) -> dict:
    """Decoded sidecars cached on the (immutable, long-lived) Part —
    postings/blooms/LUTs decode once per part, not once per query.
    A missing sidecar is NOT cached: flush builds sidecars after publish,
    so an early query must not pin 'absent' forever."""
    return part.__dict__.setdefault("_element_sidecars", {})


def _load_postings(part: Part, tag: str) -> Optional[dict[int, list[int]]]:
    cache = _sidecar_cache(part)
    key = ("eidx", tag)
    if key in cache:
        return cache[key]
    path = part.dir / f"eidx_{tag}.bin"
    if not path.exists():
        return None
    try:
        raw = json.loads(zst.decompress(path.read_bytes()))
        out = {int(k): v for k, v in raw.items()}
    except (OSError, ValueError):
        return None
    cache[key] = out
    return out


def _value_lut(part: Part, tag: str) -> dict[bytes, int]:
    cache = _sidecar_cache(part)
    key = ("lut", tag)
    lut = cache.get(key)
    if lut is None:
        lut = cache[key] = {v: i for i, v in enumerate(part.dict_for(tag))}
    return lut


def _load_blooms(part: Part, tag: str) -> Optional[list[Bloom]]:
    cache = _sidecar_cache(part)
    key = ("tff", tag)
    if key in cache:
        return cache[key]
    path = part.dir / f"tff_{tag}.bin"
    if not path.exists():
        return None
    try:
        blob = path.read_bytes()
        (n,) = struct.unpack_from("<I", blob, 0)
        off = 4
        out = []
        for _ in range(n):
            (size,) = struct.unpack_from("<I", blob, off)
            off += 4
            out.append(Bloom.from_bytes(blob[off : off + size]))
            off += size
    except (OSError, ValueError, struct.error):
        return None
    cache[key] = out
    return out


def prune_blocks(
    part: Part,
    conds,
    inverted_tags: set[str],
    skipping_tags: set[str],
) -> Optional[set[int]]:
    """-> allowed block ids, or None when no sidecar constrains the query.

    Only eq/in conditions prune (ne/not_in can't exclude a block).  The
    intersection over all prunable conditions is returned; an empty set
    means no block can match.
    """
    from banyandb_tpu.query.filter import tag_value_bytes

    allowed: Optional[set[int]] = None
    for c in conds:
        if c.op not in ("eq", "in"):
            continue
        values = [c.value] if c.op == "eq" else list(c.value)
        want = {tag_value_bytes(v) for v in values}
        cand: Optional[set[int]] = None
        if c.name in inverted_tags:
            postings = _load_postings(part, c.name)
            if postings is not None:
                lut = _value_lut(part, c.name)
                cand = set()
                for v in want:
                    code = lut.get(v)
                    if code is not None:
                        cand.update(postings.get(code, ()))
        if cand is None and c.name in skipping_tags:
            blooms = _load_blooms(part, c.name)
            if blooms is not None:
                cand = {
                    bid
                    for bid, bl in enumerate(blooms)
                    if any(v in bl for v in want)
                }
        if cand is not None:
            allowed = cand if allowed is None else (allowed & cand)
    return allowed
