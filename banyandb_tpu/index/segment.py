"""Immutable on-disk index segments (the Bluge/ICE-segment analog).

One segment = one file of raw little-endian array sections behind a JSON
TOC, opened with O(1) header reads and accessed via np.memmap — nothing
is parsed or materialised at open time, so a restart over S segments
costs O(S) header reads, not O(docs) (VERDICT r3 #3; reference:
pkg/index/inverted/inverted.go — FST dictionary + roaring postings in
immutable ICE segments).

Layout per keyword field (CSR postings):
    kw:<f>:terms_bytes / kw:<f>:terms_offs   sorted unique terms
    kw:<f>:toff                              CSR offsets into postings
    kw:<f>:post                              doc ids per term (sorted)
    kw:<f>:docterm                           per-doc term index (-1 absent)
per numeric field:
    num:<f>:docvals / num:<f>:present        per-doc value + presence
    num:<f>:svals / num:<f>:sids             (value, doc_id) sorted by value
plus "docids" (sorted int64) and "payload_offs"/"payload_bytes".

Term lookup is a binary search over the memmapped term dictionary
(O(log T) slice reads); postings come back as a memmap slice.  Deleted /
overwritten docs live in a *mutable sidecar* bitmap (`<seg>.tomb-<gen>`),
versioned per commit and referenced from the store manifest so segment
files themselves stay immutable (delete bitmaps, Lucene-style).
"""

from __future__ import annotations

import io
import json
from pathlib import Path
from typing import Mapping, Optional, Sequence

import numpy as np

_MAGIC = b"BTSEG1\n"
_ALIGN = 8


# ---------------------------------------------------------------------------
# build
# ---------------------------------------------------------------------------


def build_segment(
    ids: np.ndarray,
    kw: Mapping[str, tuple[Sequence[bytes], np.ndarray]],
    num: Mapping[str, tuple[np.ndarray, np.ndarray]],
    payloads: Sequence[bytes],
) -> bytes:
    """Serialize one immutable segment.

    ids: sorted unique int64 doc ids (n).
    kw: field -> (per-doc value bytes list, present uint8[n]).
    num: field -> (per-doc int64 values, present uint8[n]).
    payloads: per-doc payload bytes.
    """
    n = len(ids)
    ids = np.ascontiguousarray(ids, dtype=np.int64)
    if n > 1 and not (ids[1:] > ids[:-1]).all():
        raise ValueError("segment doc ids must be sorted unique")

    sections: dict[str, np.ndarray] = {"docids": ids}

    for f in sorted(kw):
        values, present = kw[f]
        present = np.ascontiguousarray(present, dtype=np.uint8)
        pres_idx = np.nonzero(present)[0]
        vals_present = [values[i] for i in pres_idx.tolist()]
        if vals_present:
            uniq_terms, inv = np.unique(
                np.asarray(vals_present, dtype=object), return_inverse=True
            )
            terms = [bytes(t) for t in uniq_terms.tolist()]
        else:
            terms, inv = [], np.zeros(0, dtype=np.int64)
        docterm = np.full(n, -1, dtype=np.int32)
        docterm[pres_idx] = inv.astype(np.int32)
        # CSR postings: doc ids per term, sorted within each term (the
        # docs are already id-sorted, so a stable sort by term keeps it)
        order = np.argsort(inv, kind="stable")
        post = ids[pres_idx][order]
        toff = np.zeros(len(terms) + 1, dtype=np.int64)
        if len(terms):
            counts = np.bincount(inv, minlength=len(terms))
            np.cumsum(counts, out=toff[1:])
        terms_bytes, terms_offs = _pack_bytes(terms)
        sections[f"kw:{f}:terms_bytes"] = terms_bytes
        sections[f"kw:{f}:terms_offs"] = terms_offs
        sections[f"kw:{f}:toff"] = toff
        sections[f"kw:{f}:post"] = post
        sections[f"kw:{f}:docterm"] = docterm

    for f in sorted(num):
        vals, present = num[f]
        vals = np.ascontiguousarray(vals, dtype=np.int64)
        present = np.ascontiguousarray(present, dtype=np.uint8)
        pres_idx = np.nonzero(present)[0]
        pvals = vals[pres_idx]
        order = np.argsort(pvals, kind="stable")
        sections[f"num:{f}:docvals"] = vals
        sections[f"num:{f}:present"] = present
        sections[f"num:{f}:svals"] = pvals[order]
        sections[f"num:{f}:sids"] = ids[pres_idx][order]

    pay_bytes, pay_offs = _pack_bytes(list(payloads))
    sections["payload_bytes"] = pay_bytes
    sections["payload_offs"] = pay_offs

    # ---- TOC + body ----
    toc: dict[str, list] = {}
    body = io.BytesIO()
    for name, arr in sections.items():
        off = body.tell()
        pad = (-off) % _ALIGN
        body.write(b"\x00" * pad)
        off += pad
        raw = arr.tobytes()
        body.write(raw)
        toc[name] = [off, str(arr.dtype), list(arr.shape)]
    header = json.dumps(
        {
            "n": n,
            "kw": sorted(kw),
            "num": sorted(num),
            "sections": toc,
        }
    ).encode()
    out = io.BytesIO()
    out.write(_MAGIC)
    out.write(len(header).to_bytes(4, "little"))
    out.write(header)
    base = out.tell()
    pad = (-base) % _ALIGN
    out.write(b"\x00" * pad)
    out.write(body.getvalue())
    return out.getvalue()


def _pack_bytes(values: list[bytes]) -> tuple[np.ndarray, np.ndarray]:
    offs = np.zeros(len(values) + 1, dtype=np.int64)
    np.cumsum([len(v) for v in values], out=offs[1:])
    blob = b"".join(values)
    return np.frombuffer(blob, dtype=np.uint8).copy(), offs


# ---------------------------------------------------------------------------
# open / read
# ---------------------------------------------------------------------------


class Segment:
    """Read-only view over one segment file + its mutable tombstone bitmap.

    All array access is lazy memmap; term dictionaries are searched with
    O(log T) slice reads, never fully decoded.
    """

    def __init__(self, path: Path, tomb_path: Optional[Path] = None):
        self.path = Path(path)
        with open(self.path, "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"bad segment magic {magic!r}: {path}")
            hlen = int.from_bytes(f.read(4), "little")
            hdr = json.loads(f.read(hlen))
            base = f.tell()
        base += (-base) % _ALIGN
        self._base = base
        self.n = int(hdr["n"])
        self.kw_fields: list[str] = hdr["kw"]
        self.num_fields: list[str] = hdr["num"]
        self._toc = hdr["sections"]
        self._maps: dict[str, np.ndarray] = {}
        # tombstones: memmapped read-only until first mutation
        self._tomb_dirty = False
        if tomb_path is not None and tomb_path.exists():
            self._tomb = np.memmap(tomb_path, dtype=np.uint8, mode="r")
            self._alive = self.n - int(self._tomb.sum())
        else:
            self._tomb = None  # all alive
            self._alive = self.n

    # -- sections ----------------------------------------------------------
    def _sec(self, name: str) -> np.ndarray:
        arr = self._maps.get(name)
        if arr is None:
            off, dtype, shape = self._toc[name]
            count = int(np.prod(shape)) if shape else 0
            if count == 0:
                arr = np.zeros(shape, dtype=dtype)
            else:
                arr = np.memmap(
                    self.path,
                    dtype=dtype,
                    mode="r",
                    offset=self._base + off,
                    shape=tuple(shape),
                )
            self._maps[name] = arr
        return arr

    @property
    def docids(self) -> np.ndarray:
        return self._sec("docids")

    # -- tombstones --------------------------------------------------------
    @property
    def alive_count(self) -> int:
        return self._alive

    def _tomb_writable(self) -> np.ndarray:
        if self._tomb is None:
            self._tomb = np.zeros(self.n, dtype=np.uint8)
        elif isinstance(self._tomb, np.memmap):
            self._tomb = np.asarray(self._tomb).copy()
        return self._tomb

    def tombstone_ids(self, ids: np.ndarray) -> int:
        """Mark any of `ids` present+alive in this segment as deleted.
        Returns the number of newly-dead docs."""
        if self.n == 0 or len(ids) == 0:
            return 0
        # dedupe: a doc_id repeated in one batch must decrement _alive once
        ids = np.unique(np.asarray(ids, dtype=np.int64))
        docids = self.docids
        slots = np.searchsorted(docids, ids)
        ok = (slots < self.n) & (docids[np.minimum(slots, self.n - 1)] == ids)
        slots = slots[ok]
        if slots.size == 0:
            return 0
        tomb = self._tomb if self._tomb is not None else None
        if tomb is not None:
            slots = slots[tomb[slots] == 0]
            if slots.size == 0:
                return 0
        t = self._tomb_writable()
        t[slots] = 1
        self._tomb_dirty = True
        self._alive -= int(slots.size)
        return int(slots.size)

    def _alive_mask_for(self, post_ids: np.ndarray) -> np.ndarray:
        """Boolean mask of alive docs for an array of doc ids known to be
        members of this segment."""
        if self._tomb is None:
            return np.ones(len(post_ids), dtype=bool)
        slots = np.searchsorted(self.docids, post_ids)
        return np.asarray(self._tomb)[slots] == 0

    def alive_ids(self) -> np.ndarray:
        if self._tomb is None:
            return np.asarray(self.docids)
        return np.asarray(self.docids)[np.asarray(self._tomb) == 0]

    # -- term dictionary ---------------------------------------------------
    def _term_at(self, f: str, i: int) -> bytes:
        offs = self._sec(f"kw:{f}:terms_offs")
        tb = self._sec(f"kw:{f}:terms_bytes")
        return tb[int(offs[i]) : int(offs[i + 1])].tobytes()

    def term_index(self, f: str, value: bytes) -> int:
        """Binary search the memmapped term dict; -1 when absent."""
        if f not in self.kw_fields:
            return -1
        offs = self._sec(f"kw:{f}:terms_offs")
        lo, hi = 0, len(offs) - 1
        while lo < hi:
            mid = (lo + hi) // 2
            if self._term_at(f, mid) < value:
                lo = mid + 1
            else:
                hi = mid
        if lo < len(offs) - 1 and self._term_at(f, lo) == value:
            return lo
        return -1

    def term_count(self, f: str) -> int:
        return len(self._sec(f"kw:{f}:terms_offs")) - 1 if f in self.kw_fields else 0

    # -- query eval --------------------------------------------------------
    def eval_term(self, f: str, value: bytes) -> np.ndarray:
        i = self.term_index(f, value)
        if i < 0:
            return np.zeros(0, dtype=np.int64)
        toff = self._sec(f"kw:{f}:toff")
        post = np.asarray(self._sec(f"kw:{f}:post")[int(toff[i]) : int(toff[i + 1])])
        return post[self._alive_mask_for(post)]

    def eval_range(self, f: str, lo, hi) -> np.ndarray:
        """Sorted doc ids with lo <= value <= hi (inclusive, None = open)."""
        if f not in self.num_fields:
            return np.zeros(0, dtype=np.int64)
        svals = self._sec(f"num:{f}:svals")
        a = int(np.searchsorted(svals, lo, "left")) if lo is not None else 0
        b = int(np.searchsorted(svals, hi, "right")) if hi is not None else len(svals)
        ids = np.asarray(self._sec(f"num:{f}:sids")[a:b])
        ids = ids[self._alive_mask_for(ids)]
        return np.sort(ids)

    def range_pairs(self, f: str, lo, hi) -> tuple[np.ndarray, np.ndarray]:
        """(values, doc_ids) in [lo, hi], ordered by value (sidx analog)."""
        if f not in self.num_fields:
            z = np.zeros(0, dtype=np.int64)
            return z, z
        svals = self._sec(f"num:{f}:svals")
        a = int(np.searchsorted(svals, lo, "left")) if lo is not None else 0
        b = int(np.searchsorted(svals, hi, "right")) if hi is not None else len(svals)
        vals = np.asarray(svals[a:b])
        ids = np.asarray(self._sec(f"num:{f}:sids")[a:b])
        keep = self._alive_mask_for(ids)
        return vals[keep], ids[keep]

    # -- doc materialisation ----------------------------------------------
    def slot_of(self, doc_id: int) -> int:
        """Slot index of doc_id if present AND alive, else -1."""
        docids = self.docids
        s = int(np.searchsorted(docids, doc_id))
        if s >= self.n or int(docids[s]) != doc_id:
            return -1
        if self._tomb is not None and self._tomb[s]:
            return -1
        return s

    def numeric_at(self, slot: int, f: str) -> Optional[int]:
        """One numeric field at one slot — two memmap reads, no decode."""
        if f not in self.num_fields:
            return None
        if not self._sec(f"num:{f}:present")[slot]:
            return None
        return int(self._sec(f"num:{f}:docvals")[slot])

    def doc_fields(self, slot: int) -> tuple[dict, dict, bytes]:
        """(keywords, numerics, payload) for one slot."""
        kws: dict[str, bytes] = {}
        for f in self.kw_fields:
            ti = int(self._sec(f"kw:{f}:docterm")[slot])
            if ti >= 0:
                kws[f] = self._term_at(f, ti)
        nums: dict[str, int] = {}
        for f in self.num_fields:
            if self._sec(f"num:{f}:present")[slot]:
                nums[f] = int(self._sec(f"num:{f}:docvals")[slot])
        offs = self._sec("payload_offs")
        payload = (
            self._sec("payload_bytes")[int(offs[slot]) : int(offs[slot + 1])]
            .tobytes()
        )
        return kws, nums, payload

    # -- columnar dump (for merge) ----------------------------------------
    def alive_columns(self):
        """(ids, kw {f: (values list, present)}, num {f: (vals, present)},
        payloads) restricted to alive docs — the builder's input shape."""
        alive = (
            np.ones(self.n, dtype=bool)
            if self._tomb is None
            else np.asarray(self._tomb) == 0
        )
        idx = np.nonzero(alive)[0]
        ids = np.asarray(self.docids)[idx]
        kw = {}
        for f in self.kw_fields:
            docterm = np.asarray(self._sec(f"kw:{f}:docterm"))[idx]
            present = (docterm >= 0).astype(np.uint8)
            # decode this segment's term dict once (O(T), merge-time only)
            offs = self._sec(f"kw:{f}:terms_offs")
            tb = self._sec(f"kw:{f}:terms_bytes")
            terms = [
                tb[int(offs[i]) : int(offs[i + 1])].tobytes()
                for i in range(len(offs) - 1)
            ]
            values = [terms[t] if t >= 0 else b"" for t in docterm.tolist()]
            kw[f] = (values, present)
        num = {
            f: (
                np.asarray(self._sec(f"num:{f}:docvals"))[idx],
                np.asarray(self._sec(f"num:{f}:present"))[idx],
            )
            for f in self.num_fields
        }
        offs = self._sec("payload_offs")
        pb = self._sec("payload_bytes")
        payloads = [
            pb[int(offs[i]) : int(offs[i + 1])].tobytes() for i in idx.tolist()
        ]
        return ids, kw, num, payloads

    def close(self) -> None:
        self._maps.clear()
