"""Index subsystem: inverted index + per-segment series index.

The reference embeds Bluge (FST term dict + roaring postings,
pkg/index/inverted/inverted.go) for four stores: the per-segment series
index, index-mode measures, the Property engine, and the Stream element
index.  The engines only ever issue exact-term and numeric-range queries
(SURVEY.md §7), so this build implements exactly that contract with
sorted-array postings — NumPy-vectorized set algebra host-side (the scan
plane stays on the TPU).
"""

from banyandb_tpu.index.inverted import Doc, InvertedIndex, TermQuery, RangeQuery, And, Or, Not
from banyandb_tpu.index.series import SeriesIndex
