"""Inverted index with sorted-array postings.

Design (vs the reference's Bluge wrapper, pkg/index/index.go:64,479,824):
- A document is (doc_id:int64, keyword fields: bytes values, numeric
  fields: int64 values, stored payload: bytes).
- Postings are sorted int64 doc-id arrays; boolean algebra is NumPy
  intersect/union/diff — the "roaring-lite" representation that a later
  C++ module can swap out behind the same surface.
- Numeric fields additionally keep a sorted (value, doc_id) projection
  for O(log n) range queries (the sidx key-range analog).
- Mutability follows the reference's Property/series model: updates are
  re-inserts of the same doc_id (last write wins), deletes are tombstones;
  compaction happens at persist time.

Persistence: one file via utils.encoding block codecs + zstd, atomically
replaced on flush; loads fully into memory (these indexes are per-segment
and bounded, like the reference's per-segment series index).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Mapping, Optional, Sequence, Union

import numpy as np

from banyandb_tpu.utils import compress as zst
from banyandb_tpu.utils import encoding as enc
from banyandb_tpu.utils import fs


@dataclass(frozen=True)
class Doc:
    doc_id: int
    keywords: Mapping[str, bytes] = field(default_factory=dict)
    numerics: Mapping[str, int] = field(default_factory=dict)
    payload: bytes = b""


@dataclass(frozen=True)
class TermQuery:
    field: str
    value: bytes


@dataclass(frozen=True)
class RangeQuery:
    field: str
    lo: Optional[int] = None  # inclusive
    hi: Optional[int] = None  # inclusive


@dataclass(frozen=True)
class And:
    clauses: tuple


@dataclass(frozen=True)
class Or:
    clauses: tuple


@dataclass(frozen=True)
class Not:
    clause: object


Query = Union[TermQuery, RangeQuery, And, Or, Not, None]


def _match_doc(d: Doc, q: Query) -> bool:
    """Direct predicate evaluation for pending (not-yet-built) docs."""
    if q is None:
        return True
    if isinstance(q, TermQuery):
        return d.keywords.get(q.field) == q.value
    if isinstance(q, RangeQuery):
        v = d.numerics.get(q.field)
        if v is None:
            return False
        return (q.lo is None or v >= q.lo) and (q.hi is None or v <= q.hi)
    if isinstance(q, And):
        return all(_match_doc(d, c) for c in q.clauses)
    if isinstance(q, Or):
        return any(_match_doc(d, c) for c in q.clauses)
    if isinstance(q, Not):
        return not _match_doc(d, q.clause)
    raise TypeError(f"unknown query {type(q)}")


_PENDING_REBUILD_THRESHOLD = 4096


class InvertedIndex:
    """One mutable index instance (a per-segment / per-shard store).

    Write amortization: fresh docs land in a pending buffer that queries
    scan linearly; the sorted postings are rebuilt only when the buffer
    passes _PENDING_REBUILD_THRESHOLD (or a built doc is overwritten) —
    an interleaved write/query workload does not pay an O(total docs)
    rebuild per query.
    """

    def __init__(self, path: Optional[str | Path] = None):
        self._lock = threading.RLock()
        self.path = Path(path) if path else None
        # doc_id -> Doc (live set; tombstoned ids removed)
        self._docs: dict[int, Doc] = {}
        self._pending: dict[int, Doc] = {}  # subset of _docs not yet built
        self._dirty = True
        # built lazily: postings + numeric projections
        self._postings: dict[tuple[str, bytes], np.ndarray] = {}
        self._numeric: dict[str, tuple[np.ndarray, np.ndarray]] = {}
        self._all_ids: np.ndarray = np.zeros(0, dtype=np.int64)
        # set by reclaim(): in-memory state dropped, reload before any op
        self._released = False
        if self.path and self.path.exists():
            self._load()

    def reclaim(self) -> None:
        """Persist, then release all in-memory state (idle-segment memory
        reclaim, segment.go:334 closeIdleSegments analog).

        The index object stays valid — every operation lazily reloads from
        the persisted file first — so concurrent holders of this instance
        never observe a dropped index, only a reload cost."""
        with self._lock:
            if not self.path or self._released:
                return  # memory-only indexes have no file to reload from
            self.persist()
            self._docs = {}
            self._pending = {}
            self._postings = {}
            self._numeric = {}
            self._all_ids = np.zeros(0, dtype=np.int64)
            self._dirty = True
            self._released = True

    def _ensure_loaded(self) -> None:
        """Reload after reclaim(). Caller holds self._lock."""
        if self._released:
            self._released = False
            if self.path.exists():
                self._load()

    # -- mutation ----------------------------------------------------------
    def insert(self, docs: Iterable[Doc]) -> None:
        """Insert or overwrite by doc_id (ModRevision-style last-write-wins)."""
        with self._lock:
            self._ensure_loaded()
            for d in docs:
                if not self._dirty and d.doc_id in self._docs and d.doc_id not in self._pending:
                    # overwrite of a built doc: postings hold stale entries
                    self._dirty = True
                self._docs[d.doc_id] = d
                self._pending[d.doc_id] = d
            if len(self._pending) > _PENDING_REBUILD_THRESHOLD:
                self._dirty = True

    def insert_if_newer(
        self, doc: Doc, version_field: str = "@version"
    ) -> bool:
        """Atomic check-and-insert: keep the doc with the higher version."""
        with self._lock:
            self._ensure_loaded()
            old = self._docs.get(doc.doc_id)
            if old is not None and old.numerics.get(version_field, 0) >= doc.numerics.get(version_field, 0):
                return False
            self.insert([doc])
            return True

    def delete(self, doc_ids: Iterable[int]) -> None:
        with self._lock:
            self._ensure_loaded()
            for i in doc_ids:
                if self._docs.pop(i, None) is not None:
                    self._pending.pop(i, None)
                    self._dirty = True

    def __len__(self) -> int:
        with self._lock:
            self._ensure_loaded()
            return len(self._docs)

    # -- build -------------------------------------------------------------
    def _rebuild(self) -> None:
        postings: dict[tuple[str, bytes], list[int]] = {}
        numeric: dict[str, list[tuple[int, int]]] = {}
        for doc_id, d in self._docs.items():
            for f, v in d.keywords.items():
                postings.setdefault((f, v), []).append(doc_id)
            for f, v in d.numerics.items():
                numeric.setdefault(f, []).append((v, doc_id))
        self._postings = {
            k: np.asarray(sorted(v), dtype=np.int64)
            for k, v in postings.items()
        }
        self._numeric = {}
        for f, pairs in numeric.items():
            pairs.sort()
            vals = np.asarray([p[0] for p in pairs], dtype=np.int64)
            ids = np.asarray([p[1] for p in pairs], dtype=np.int64)
            self._numeric[f] = (vals, ids)
        self._all_ids = np.asarray(sorted(self._docs.keys()), dtype=np.int64)
        self._pending = {}
        self._dirty = False

    def _ensure(self) -> None:
        self._ensure_loaded()
        if self._dirty:
            self._rebuild()

    # -- query -------------------------------------------------------------
    def search(self, query: Query = None, limit: Optional[int] = None) -> np.ndarray:
        """-> sorted doc_id array matching the query (None = all docs)."""
        with self._lock:
            self._ensure()
            ids = self._eval(query)
            if self._pending:
                extra = [
                    d.doc_id
                    for d in self._pending.values()
                    if _match_doc(d, query)
                ]
                if extra:
                    ids = np.union1d(ids, np.asarray(extra, dtype=np.int64))
            return ids[:limit] if limit is not None else ids

    def _eval(self, q: Query) -> np.ndarray:
        if q is None:
            return self._all_ids
        if isinstance(q, TermQuery):
            return self._postings.get((q.field, q.value), np.zeros(0, np.int64))
        if isinstance(q, RangeQuery):
            pair = self._numeric.get(q.field)
            if pair is None:
                return np.zeros(0, np.int64)
            vals, ids = pair
            lo = np.searchsorted(vals, q.lo, "left") if q.lo is not None else 0
            hi = np.searchsorted(vals, q.hi, "right") if q.hi is not None else len(vals)
            return np.unique(ids[lo:hi])
        if isinstance(q, And):
            out = None
            for c in q.clauses:
                ids = self._eval(c)
                out = ids if out is None else np.intersect1d(out, ids, assume_unique=False)
                if out.size == 0:
                    break
            return out if out is not None else self._all_ids
        if isinstance(q, Or):
            out = np.zeros(0, np.int64)
            for c in q.clauses:
                out = np.union1d(out, self._eval(c))
            return out
        if isinstance(q, Not):
            base = np.setdiff1d(self._all_ids, self._eval(q.clause))
            return base
        raise TypeError(f"unknown query {type(q)}")

    def range_ordered(
        self,
        field: str,
        lo: Optional[int] = None,
        hi: Optional[int] = None,
        *,
        asc: bool = True,
        limit: Optional[int] = None,
    ) -> np.ndarray:
        """doc_ids with lo <= numeric field <= hi, ORDERED by field value.

        The sidx analog (banyand/internal/sidx: key-ordered retrieval,
        e.g. traces by duration).  Pending docs are merged in at query
        time (small linear pass) instead of forcing a full rebuild.
        """
        with self._lock:
            self._ensure()
            pair = self._numeric.get(field, (np.zeros(0, np.int64), np.zeros(0, np.int64)))
            vals, ids = pair
            a = np.searchsorted(vals, lo, "left") if lo is not None else 0
            b = np.searchsorted(vals, hi, "right") if hi is not None else len(vals)
            vals, ids = vals[a:b], ids[a:b]
            if self._pending:
                extra = [
                    (d.numerics[field], d.doc_id)
                    for d in self._pending.values()
                    if field in d.numerics
                    and (lo is None or d.numerics[field] >= lo)
                    and (hi is None or d.numerics[field] <= hi)
                ]
                if extra:
                    pv = np.asarray([e[0] for e in extra], dtype=np.int64)
                    pi = np.asarray([e[1] for e in extra], dtype=np.int64)
                    vals = np.concatenate([vals, pv])
                    ids = np.concatenate([ids, pi])
                    order = np.argsort(vals, kind="stable")
                    ids = ids[order]
            out = ids if asc else ids[::-1]
            return out[:limit] if limit is not None else out

    def get(self, doc_id: int) -> Optional[Doc]:
        with self._lock:
            self._ensure_loaded()
            return self._docs.get(doc_id)

    def get_many(self, doc_ids: Sequence[int]) -> list[Doc]:
        with self._lock:
            self._ensure_loaded()
            return [self._docs[i] for i in doc_ids if i in self._docs]

    # -- persistence -------------------------------------------------------
    # v2: keyword columns carry presence bitmaps like numeric ones, so an
    # explicitly-empty keyword value (b"") survives the persist/_load round
    # trip — routine since idle reclaim, not just restart
    _MAGIC = b"BTIX2\n"

    def persist(self) -> None:
        if not self.path:
            return
        with self._lock:
            if self._released:
                return  # state already on disk; persisting now would
                # truncate the file to the (empty) in-memory doc set
            ids = sorted(self._docs.keys())
            kw_names = sorted({f for d in self._docs.values() for f in d.keywords})
            num_names = sorted({f for d in self._docs.values() for f in d.numerics})
            blobs: list[bytes] = []
            blobs.append(enc.encode_int64(np.asarray(ids, dtype=np.int64)))
            blobs.append(enc.encode_strings([f.encode() for f in kw_names]))
            blobs.append(enc.encode_strings([f.encode() for f in num_names]))
            for f in kw_names:
                blobs.append(
                    enc.encode_strings(
                        [self._docs[i].keywords.get(f, b"") for i in ids]
                    )
                )
                blobs.append(
                    enc.encode_int64(
                        np.asarray(
                            [1 if f in self._docs[i].keywords else 0 for i in ids],
                            dtype=np.int64,
                        )
                    )
                )
            for f in num_names:
                blobs.append(
                    enc.encode_int64(
                        np.asarray(
                            [self._docs[i].numerics.get(f, 0) for i in ids],
                            dtype=np.int64,
                        )
                    )
                )
                # presence bitmap (0 missing / 1 present)
                blobs.append(
                    enc.encode_int64(
                        np.asarray(
                            [1 if f in self._docs[i].numerics else 0 for i in ids],
                            dtype=np.int64,
                        )
                    )
                )
            blobs.append(enc.encode_strings([self._docs[i].payload for i in ids]))
            body = b"".join(
                len(b).to_bytes(4, "little") + b for b in blobs
            )
            fs.atomic_write(self.path, self._MAGIC + zst.compress(body))

    _MAGIC_V1 = b"BTIX1\n"

    def _load(self) -> None:
        blob = self.path.read_bytes()
        magic = blob[: len(self._MAGIC)]
        if magic not in (self._MAGIC, self._MAGIC_V1):
            raise ValueError(f"bad index file magic {magic!r}: {self.path}")
        v1 = magic == self._MAGIC_V1
        raw = zst.decompress(blob[len(self._MAGIC) :])
        off = 0
        blobs: list[bytes] = []
        while off < len(raw):
            ln = int.from_bytes(raw[off : off + 4], "little")
            off += 4
            blobs.append(raw[off : off + ln])
            off += ln
        it = iter(blobs)
        first = next(it)
        # id count is self-describing via encode_strings? ids blob needs count:
        # stored as int64 list; count from the kw/vals below — decode lazily:
        kw_names = [b.decode() for b in enc.decode_strings(next(it))]
        num_names = [b.decode() for b in enc.decode_strings(next(it))]
        # decode kw columns first to learn n
        kw_cols = {}
        kw_present = {}
        for f in kw_names:
            kw_cols[f] = enc.decode_strings(next(it))
            if v1:
                # v1 had no keyword presence bitmaps: b"" meant absent
                kw_present[f] = [1 if v != b"" else 0 for v in kw_cols[f]]
            else:
                kw_present[f] = enc.decode_int64(next(it), len(kw_cols[f]))
        n = len(next(iter(kw_cols.values()))) if kw_cols else None
        num_cols = {}
        num_present = {}
        for f in num_names:
            vals_blob = next(it)
            pres_blob = next(it)
            if n is None:
                # have to probe: decode with a guess is impossible; numeric
                # columns always follow keyword ones, so n must be known.
                raise ValueError("index file with numeric-only docs needs n")
            num_cols[f] = enc.decode_int64(vals_blob, n)
            num_present[f] = enc.decode_int64(pres_blob, n)
        payloads = enc.decode_strings(next(it))
        if n is None:
            n = len(payloads)
        ids = enc.decode_int64(first, n)
        for i in range(n):
            self._docs[int(ids[i])] = Doc(
                doc_id=int(ids[i]),
                keywords={
                    f: kw_cols[f][i] for f in kw_names if kw_present[f][i]
                },
                numerics={
                    f: int(num_cols[f][i])
                    for f in num_names
                    if num_present[f][i]
                },
                payload=payloads[i],
            )
        self._dirty = True
