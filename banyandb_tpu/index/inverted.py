"""Inverted index: memtable + immutable on-disk posting segments.

Design (vs the reference's Bluge wrapper, pkg/index/index.go:64,479,824;
segment store pkg/index/inverted/inverted.go:1-655 — FST dictionary +
roaring postings in immutable ICE segments):

- A document is (doc_id:int64, keyword fields: bytes values, numeric
  fields: int64 values, stored payload: bytes).
- Fresh docs land in a memtable dict; queries evaluate it with direct
  predicate checks (small, bounded by flush cadence).
- persist() flushes the memtable to a NEW immutable segment file
  (index/segment.py: CSR postings per field, memmap-at-rest) and
  atomically commits a manifest — incremental: O(memtable), never a
  whole-store rewrite.
- Overwrites and deletes mark *delete bitmaps* on older segments
  (mutable sidecars, versioned per commit, referenced by the manifest)
  so at most one live copy of a doc_id exists anywhere.
- When the segment count passes MERGE_FANOUT, persist() folds the
  smallest half into one segment (size-tiered background merge; the
  same part-lifecycle discipline the TSDB uses).
- Restart opens the manifest + segment headers only: O(segments), not
  O(docs); searches ride memmapped postings without materialising docs.

Mutability follows the reference's Property/series model: updates are
re-inserts of the same doc_id (last write wins), deletes are tombstones;
physical removal happens at merge.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable, Mapping, Optional, Sequence, Union

import numpy as np

from banyandb_tpu.index.segment import Segment, build_segment
from banyandb_tpu.utils import compress as zst
from banyandb_tpu.utils import encoding as enc
from banyandb_tpu.utils import fs


@dataclass(frozen=True)
class Doc:
    doc_id: int
    keywords: Mapping[str, bytes] = field(default_factory=dict)
    numerics: Mapping[str, int] = field(default_factory=dict)
    payload: bytes = b""


@dataclass(frozen=True)
class TermQuery:
    field: str
    value: bytes


@dataclass(frozen=True)
class RangeQuery:
    field: str
    lo: Optional[int] = None  # inclusive
    hi: Optional[int] = None  # inclusive


@dataclass(frozen=True)
class And:
    clauses: tuple


@dataclass(frozen=True)
class Or:
    clauses: tuple


@dataclass(frozen=True)
class Not:
    clause: object


Query = Union[TermQuery, RangeQuery, And, Or, Not, None]


def _match_doc(d: Doc, q: Query) -> bool:
    """Direct predicate evaluation for memtable docs."""
    if q is None:
        return True
    if isinstance(q, TermQuery):
        return d.keywords.get(q.field) == q.value
    if isinstance(q, RangeQuery):
        v = d.numerics.get(q.field)
        if v is None:
            return False
        return (q.lo is None or v >= q.lo) and (q.hi is None or v <= q.hi)
    if isinstance(q, And):
        return all(_match_doc(d, c) for c in q.clauses)
    if isinstance(q, Or):
        return any(_match_doc(d, c) for c in q.clauses)
    if isinstance(q, Not):
        return not _match_doc(d, q.clause)
    raise TypeError(f"unknown query {type(q)}")


_EMPTY = np.zeros(0, dtype=np.int64)


class InvertedIndex:
    """One mutable index instance (a per-segment / per-shard store)."""

    MERGE_FANOUT = 8

    def __init__(self, path: Optional[str | Path] = None):
        self._lock = threading.RLock()
        self.path = Path(path) if path else None
        self._mem: dict[int, Doc] = {}
        # oldest..newest; Segment owns its tombstone bitmap
        self._segs: list[tuple[str, Segment]] = []
        self._tomb_gens: dict[str, int] = {}
        self._next_seg = 1
        self._released = False
        if self.path is not None:
            tmpdir = self._tmpdir_path()
            if not self.path.exists() and tmpdir.exists():
                # crash between legacy-file unlink and dir rename
                tmpdir.rename(self.path)
            if self.path.exists():
                self._open()

    # -- lifecycle ---------------------------------------------------------
    def _tmpdir_path(self) -> Path:
        return self.path.parent / f".{self.path.name}.migrating"

    def _open(self) -> None:
        if self.path.is_file():
            self._load_legacy(self.path)
            return
        man_path = self.path / "manifest.json"
        if not man_path.exists():
            return  # fresh/empty dir: nothing committed yet
        man = fs.read_json(man_path)
        for ent in man["segments"]:
            name, gen = ent["name"], ent.get("tomb_gen", 0)
            tomb = self.path / f"{name}.tomb-{gen}" if gen else None
            seg = Segment(self.path / f"{name}.seg", tomb_path=tomb)
            self._segs.append((name, seg))
            self._tomb_gens[name] = gen
        self._next_seg = int(man.get("next_seg", len(self._segs) + 1))

    def reclaim(self) -> None:
        """Persist, then release all in-memory state (idle-segment memory
        reclaim, segment.go:334 closeIdleSegments analog).  The instance
        stays valid: every operation lazily reopens the manifest."""
        with self._lock:
            if not self.path or self._released:
                return
            self.persist()
            for _, seg in self._segs:
                seg.close()
            self._segs = []
            self._tomb_gens = {}
            self._mem = {}
            self._released = True

    def _ensure_loaded(self) -> None:
        """Reopen after reclaim(). Caller holds self._lock."""
        if self._released:
            self._released = False
            if self.path.exists():
                self._open()

    # -- mutation ----------------------------------------------------------
    def insert(self, docs: Iterable[Doc]) -> None:
        """Insert or overwrite by doc_id (ModRevision-style last-write-wins).
        Overwrites tombstone any older on-disk copy immediately so at most
        one live copy of a doc exists."""
        with self._lock:
            self._ensure_loaded()
            ids = []
            for d in docs:
                self._mem[d.doc_id] = d
                ids.append(d.doc_id)
            if self._segs and ids:
                arr = np.asarray(sorted(ids), dtype=np.int64)
                for _, seg in self._segs:
                    seg.tombstone_ids(arr)

    def insert_if_newer(
        self, doc: Doc, version_field: str = "@version"
    ) -> bool:
        """Atomic check-and-insert: keep the doc with the higher version."""
        with self._lock:
            self._ensure_loaded()
            old_v = self.get_numeric(doc.doc_id, version_field)
            if old_v is None and self.contains(doc.doc_id):
                old_v = 0  # doc exists but carries no version field
            if old_v is not None and old_v >= doc.numerics.get(version_field, 0):
                return False
            self.insert([doc])
            return True

    def delete(self, doc_ids: Iterable[int]) -> None:
        with self._lock:
            self._ensure_loaded()
            ids = list(doc_ids)
            for i in ids:
                self._mem.pop(i, None)
            if self._segs and ids:
                arr = np.asarray(sorted(ids), dtype=np.int64)
                for _, seg in self._segs:
                    seg.tombstone_ids(arr)

    def __len__(self) -> int:
        with self._lock:
            self._ensure_loaded()
            return len(self._mem) + sum(s.alive_count for _, s in self._segs)

    # -- query -------------------------------------------------------------
    def search(self, query: Query = None, limit: Optional[int] = None) -> np.ndarray:
        """-> sorted doc_id array matching the query (None = all docs)."""
        with self._lock:
            self._ensure_loaded()
            parts = [self._eval_segment(seg, query) for _, seg in self._segs]
            if self._mem:
                extra = [
                    d.doc_id for d in self._mem.values() if _match_doc(d, query)
                ]
                if extra:
                    parts.append(np.asarray(extra, dtype=np.int64))
            parts = [p for p in parts if p.size]
            if not parts:
                return _EMPTY
            out = (
                np.sort(parts[0])
                if len(parts) == 1
                else np.unique(np.concatenate(parts))
            )
            return out[:limit] if limit is not None else out

    def _eval_segment(self, seg: Segment, q: Query) -> np.ndarray:
        if q is None:
            return seg.alive_ids()
        if isinstance(q, TermQuery):
            return seg.eval_term(q.field, q.value)
        if isinstance(q, RangeQuery):
            return seg.eval_range(q.field, q.lo, q.hi)
        if isinstance(q, And):
            out = None
            for c in q.clauses:
                ids = self._eval_segment(seg, c)
                out = ids if out is None else np.intersect1d(out, ids)
                if out.size == 0:
                    break
            return out if out is not None else seg.alive_ids()
        if isinstance(q, Or):
            out = _EMPTY
            for c in q.clauses:
                out = np.union1d(out, self._eval_segment(seg, c))
            return out
        if isinstance(q, Not):
            # per-segment complement composes globally because tombstones
            # guarantee exactly one live copy of any doc across the store
            return np.setdiff1d(seg.alive_ids(), self._eval_segment(seg, q.clause))
        raise TypeError(f"unknown query {type(q)}")

    def range_ordered(
        self,
        field: str,
        lo: Optional[int] = None,
        hi: Optional[int] = None,
        *,
        asc: bool = True,
        limit: Optional[int] = None,
    ) -> np.ndarray:
        """doc_ids with lo <= numeric field <= hi, ORDERED by field value
        (the sidx analog: key-ordered retrieval, e.g. traces by duration).
        Merges the per-segment sorted projections + memtable extras."""
        with self._lock:
            self._ensure_loaded()
            vals_parts, ids_parts = [], []
            for _, seg in self._segs:
                v, i = seg.range_pairs(field, lo, hi)
                if v.size:
                    vals_parts.append(v)
                    ids_parts.append(i)
            if self._mem:
                extra = [
                    (d.numerics[field], d.doc_id)
                    for d in self._mem.values()
                    if field in d.numerics
                    and (lo is None or d.numerics[field] >= lo)
                    and (hi is None or d.numerics[field] <= hi)
                ]
                if extra:
                    vals_parts.append(np.asarray([e[0] for e in extra], dtype=np.int64))
                    ids_parts.append(np.asarray([e[1] for e in extra], dtype=np.int64))
            if not vals_parts:
                return _EMPTY
            vals = np.concatenate(vals_parts)
            ids = np.concatenate(ids_parts)
            order = np.argsort(vals, kind="stable")
            out = ids[order]
            if not asc:
                out = out[::-1]
            return out[:limit] if limit is not None else out

    def contains(self, doc_id: int) -> bool:
        """Existence probe without materialising the doc: memtable dict
        hit or a per-segment binary search — no column/payload reads.
        The measure write hot path (SeriesIndex.insert_series idempotency
        check) rides this on every data point."""
        with self._lock:
            self._ensure_loaded()
            if doc_id in self._mem:
                return True
            return any(seg.slot_of(doc_id) >= 0 for _, seg in self._segs)

    def get_numeric(self, doc_id: int, field: str) -> Optional[int]:
        """Read ONE numeric field of a doc without decoding keywords or
        payload (insert_if_newer's version probe)."""
        with self._lock:
            self._ensure_loaded()
            d = self._mem.get(doc_id)
            if d is not None:
                return d.numerics.get(field)
            for _, seg in reversed(self._segs):
                slot = seg.slot_of(doc_id)
                if slot >= 0:
                    return seg.numeric_at(slot, field)
            return None

    def get(self, doc_id: int) -> Optional[Doc]:
        with self._lock:
            self._ensure_loaded()
            d = self._mem.get(doc_id)
            if d is not None:
                return d
            for _, seg in reversed(self._segs):
                slot = seg.slot_of(doc_id)
                if slot >= 0:
                    kws, nums, payload = seg.doc_fields(slot)
                    return Doc(doc_id, kws, nums, payload)
            return None

    def get_many(self, doc_ids: Sequence[int]) -> list[Doc]:
        with self._lock:
            self._ensure_loaded()
            out = []
            for i in doc_ids:
                d = self.get(i)
                if d is not None:
                    out.append(d)
            return out

    # -- persistence -------------------------------------------------------
    def persist(self) -> None:
        """Commit pending state: flush the memtable to a new immutable
        segment, write updated delete bitmaps, atomically publish the
        manifest, then GC unreferenced files.  O(pending changes), not
        O(total docs) — plus an amortised size-tiered merge."""
        if not self.path:
            return
        with self._lock:
            if self._released:
                return  # state already on disk
            dirty_tombs = [
                (name, seg) for name, seg in self._segs if seg._tomb_dirty
            ]
            # Legacy single-file store: build the segmented dir next to it,
            # then unlink + rename (the whole legacy doc set is already in
            # the memtable, so nothing else needs carrying over).  Never
            # short-circuit while migrating — an all-docs-deleted legacy
            # store has an empty memtable but MUST still replace the file,
            # or the deleted docs resurrect on reopen.
            migrating = self.path.exists() and self.path.is_file()
            if not self._mem and not dirty_tombs and not migrating:
                return
            root = self._tmpdir_path() if migrating else self.path
            root.mkdir(parents=True, exist_ok=True)

            new_entries = []
            if self._mem:
                name = f"seg-{self._next_seg:06d}"
                self._next_seg += 1
                blob = build_segment(*self._columns_from_mem())
                fs.atomic_write(root / f"{name}.seg", blob)
                new_entries.append(name)
            # delete bitmaps: versioned sidecars, committed by the manifest
            for name, seg in dirty_tombs:
                gen = self._tomb_gens.get(name, 0) + 1
                fs.atomic_write(
                    root / f"{name}.tomb-{gen}",
                    np.ascontiguousarray(seg._tomb, dtype=np.uint8).tobytes(),
                )
                self._tomb_gens[name] = gen
                seg._tomb_dirty = False

            self._write_manifest(root, extra=new_entries)
            if migrating:
                self.path.unlink()
                root.rename(self.path)
            for name in new_entries:
                self._segs.append(
                    (name, Segment(self.path / f"{name}.seg"))
                )
            self._mem = {}
            self._maybe_merge()
            self._gc()

    def _columns_from_mem(self):
        ids = np.asarray(sorted(self._mem), dtype=np.int64)
        docs = [self._mem[int(i)] for i in ids]
        n = len(docs)
        kw_names = sorted({f for d in docs for f in d.keywords})
        num_names = sorted({f for d in docs for f in d.numerics})
        kw = {}
        for f in kw_names:
            kw[f] = (
                [d.keywords.get(f, b"") for d in docs],
                np.asarray([f in d.keywords for d in docs], dtype=np.uint8),
            )
        num = {}
        for f in num_names:
            num[f] = (
                np.asarray([d.numerics.get(f, 0) for d in docs], dtype=np.int64),
                np.asarray([f in d.numerics for d in docs], dtype=np.uint8),
            )
        return ids, kw, num, [d.payload for d in docs]

    def _write_manifest(self, root: Path, extra: Sequence[str] = ()) -> None:
        fs.atomic_write_json(
            root / "manifest.json",
            {
                "version": 1,
                "segments": [
                    {"name": name, "tomb_gen": self._tomb_gens.get(name, 0)}
                    for name, _ in self._segs
                ]
                + [{"name": n, "tomb_gen": 0} for n in extra],
                "next_seg": self._next_seg,
            },
        )

    def _maybe_merge(self) -> None:
        """Size-tiered compaction: fold the smallest half of the segments
        into one when the count passes MERGE_FANOUT.  Amortised log-
        structured cost; drops tombstoned docs physically."""
        if len(self._segs) < self.MERGE_FANOUT:
            return
        by_size = sorted(self._segs, key=lambda t: t[1].alive_count)
        victims = by_size[: max(2, len(self._segs) // 2)]
        victim_names = {name for name, _ in victims}

        # Columnar merge: concatenate the victims' alive columns and
        # re-sort by doc id — no per-doc Python objects.  Tombstones
        # guarantee doc ids are disjoint across segments.
        cols = [seg.alive_columns() for _, seg in victims]
        cols = [c for c in cols if len(c[0])]
        name = f"seg-{self._next_seg:06d}"
        self._next_seg += 1
        merged_n = 0
        if cols:
            all_ids = np.concatenate([c[0] for c in cols])
            order = np.argsort(all_ids, kind="stable")
            merged_n = len(all_ids)
            kw_names = sorted({f for c in cols for f in c[1]})
            num_names = sorted({f for c in cols for f in c[2]})
            kw = {}
            for f in kw_names:
                vals: list[bytes] = []
                pres_parts = []
                for c in cols:
                    n_c = len(c[0])
                    if f in c[1]:
                        vals.extend(c[1][f][0])
                        pres_parts.append(c[1][f][1])
                    else:
                        vals.extend([b""] * n_c)
                        pres_parts.append(np.zeros(n_c, dtype=np.uint8))
                kw[f] = (
                    [vals[i] for i in order.tolist()],
                    np.concatenate(pres_parts)[order],
                )
            num = {}
            for f in num_names:
                v_parts, p_parts = [], []
                for c in cols:
                    n_c = len(c[0])
                    if f in c[2]:
                        v_parts.append(c[2][f][0])
                        p_parts.append(c[2][f][1])
                    else:
                        v_parts.append(np.zeros(n_c, dtype=np.int64))
                        p_parts.append(np.zeros(n_c, dtype=np.uint8))
                num[f] = (
                    np.concatenate(v_parts)[order],
                    np.concatenate(p_parts)[order],
                )
            payloads_flat: list[bytes] = []
            for c in cols:
                payloads_flat.extend(c[3])
            payloads = [payloads_flat[i] for i in order.tolist()]
            blob = build_segment(all_ids[order], kw, num, payloads)
            fs.atomic_write(self.path / f"{name}.seg", blob)
        survivors = [t for t in self._segs if t[0] not in victim_names]
        if merged_n:
            survivors.append((name, Segment(self.path / f"{name}.seg")))
        for vname, vseg in victims:
            vseg.close()
            self._tomb_gens.pop(vname, None)
        self._segs = survivors
        self._write_manifest(self.path)

    def _gc(self) -> None:
        """Remove files no longer referenced by the manifest."""
        live = set()
        for name, _ in self._segs:
            live.add(f"{name}.seg")
            gen = self._tomb_gens.get(name, 0)
            if gen:
                live.add(f"{name}.tomb-{gen}")
        live.add("manifest.json")
        try:
            for p in self.path.iterdir():
                if p.name not in live and (
                    p.name.endswith(".seg")
                    or ".tomb-" in p.name
                ):
                    p.unlink(missing_ok=True)
        except OSError:
            pass  # GC is advisory; next persist retries

    # -- legacy single-file format (pre-segment stores) --------------------
    _MAGIC = b"BTIX2\n"
    _MAGIC_V1 = b"BTIX1\n"

    def _load_legacy(self, path: Path) -> None:
        """Read a v1/v2 whole-store file into the memtable; the next
        persist() migrates it to the segmented layout in place."""
        blob = path.read_bytes()
        magic = blob[: len(self._MAGIC)]
        if magic not in (self._MAGIC, self._MAGIC_V1):
            raise ValueError(f"bad index file magic {magic!r}: {path}")
        v1 = magic == self._MAGIC_V1
        raw = zst.decompress(blob[len(self._MAGIC) :])
        off = 0
        blobs: list[bytes] = []
        while off < len(raw):
            ln = int.from_bytes(raw[off : off + 4], "little")
            off += 4
            blobs.append(raw[off : off + ln])
            off += ln
        it = iter(blobs)
        first = next(it)
        kw_names = [b.decode() for b in enc.decode_strings(next(it))]
        num_names = [b.decode() for b in enc.decode_strings(next(it))]
        kw_cols = {}
        kw_present = {}
        for f in kw_names:
            kw_cols[f] = enc.decode_strings(next(it))
            if v1:
                kw_present[f] = [1 if v != b"" else 0 for v in kw_cols[f]]
            else:
                kw_present[f] = enc.decode_int64(next(it), len(kw_cols[f]))
        n = len(next(iter(kw_cols.values()))) if kw_cols else None
        num_cols = {}
        num_present = {}
        for f in num_names:
            vals_blob = next(it)
            pres_blob = next(it)
            if n is None:
                raise ValueError("index file with numeric-only docs needs n")
            num_cols[f] = enc.decode_int64(vals_blob, n)
            num_present[f] = enc.decode_int64(pres_blob, n)
        payloads = enc.decode_strings(next(it))
        if n is None:
            n = len(payloads)
        ids = enc.decode_int64(first, n)
        for i in range(n):
            self._mem[int(ids[i])] = Doc(
                doc_id=int(ids[i]),
                keywords={
                    f: kw_cols[f][i] for f in kw_names if kw_present[f][i]
                },
                numerics={
                    f: int(num_cols[f][i])
                    for f in num_names
                    if num_present[f][i]
                },
                payload=payloads[i],
            )
