"""Distributed measure aggregation over a device mesh.

Map-reduce with collectives instead of proto exchange:

  per device:  mask -> group key -> segment reduce  (the "map" on one
               shard/segment slice, same kernel family as
               query/measure_exec._build_kernel)
  collective:  psum(count/sums/hist), pmin/pmax over ('shard','seg')
               — replacing the liaison's partial-merge loop
               (banyand/dquery/measure.go:156)
  post:        top-k on the now-replicated group vector, still on device

Inputs are [S, R] arrays sharded over the mesh ('shard','seg' collapsed
into the leading dim); the whole step is one jit so XLA schedules scan
and collectives together.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from banyandb_tpu import ops

_NUM_HIST_BUCKETS = 512


@dataclass(frozen=True)
class DistPlan:
    """Static signature of the distributed aggregation step."""

    tags_code: tuple[str, ...]
    fields: tuple[str, ...]
    group_tags: tuple[str, ...]
    radices: tuple[int, ...]
    num_groups: int
    eq_preds: tuple[str, ...] = ()  # tag names with eq-code predicates
    topn: int = 0
    want_hist: str = ""  # field name for percentile histograms


def map_chunk(plan: DistPlan, chunk: dict, pred_codes: dict):
    """The map half of one device chunk: mask -> group key -> segment
    reduce.  -> (GroupReduceResult, key, mask).  Shared verbatim by the
    legacy single-width step below and the fused chunked-scan step
    (query/fused_exec._fused_dist_step), so the two mesh programs cannot
    drift on predicate/key/reduction semantics."""
    valid = chunk["valid"]
    masks = [valid]
    for t in plan.eq_preds:
        masks.append(chunk["tags"][t] == pred_codes[t])
    mask = ops.mask_and(*masks)

    key_cols = [chunk["tags"][t] for t in plan.group_tags]
    if key_cols:
        key, _ = ops.mixed_radix_key(key_cols, plan.radices)
    else:
        key = jnp.zeros_like(valid, dtype=jnp.int32)

    res = ops.group_reduce(
        key, mask, chunk["fields"], plan.num_groups, want_minmax=True
    )
    return res, key, mask


def _step(plan: DistPlan, chunk: dict, pred_codes: dict, hist_lo, hist_span):
    """One device's slice -> partials -> collectives -> result.

    shard_map hands each device a [1, R] view of the sharded [D, R] input;
    flatten to [R] so segment reductions see a flat row axis.
    """
    chunk = jax.tree.map(lambda a: a.reshape(-1), chunk)
    res, key, mask = map_chunk(plan, chunk, pred_codes)

    # ---- the collective reduce: ICI replaces the proto partial hop ----
    axes = ("shard", "seg")
    count = jax.lax.psum(res.count, axes)
    sums = {f: jax.lax.psum(res.sums[f], axes) for f in plan.fields}
    mins = {f: jax.lax.pmin(res.mins[f], axes) for f in plan.fields}
    maxs = {f: jax.lax.pmax(res.maxs[f], axes) for f in plan.fields}
    out = {"count": count, "sums": sums, "mins": mins, "maxs": maxs}

    if plan.want_hist:
        hist = ops.group_histogram(
            key,
            mask,
            chunk["fields"][plan.want_hist],
            plan.num_groups,
            hist_lo,
            hist_span,
            _NUM_HIST_BUCKETS,
        )
        out["hist"] = jax.lax.psum(hist, axes)

    if plan.topn:
        mean = out["sums"][plan.fields[0]] / jnp.maximum(out["count"], 1.0)
        vals, idx = ops.topk_groups(mean, out["count"] > 0, plan.topn)
        out["top_vals"], out["top_idx"] = vals, idx
    return out


_STEP_CACHE: dict[tuple, object] = {}


def build_distributed_step(mesh: Mesh, plan: DistPlan):
    """-> jitted f(chunks, pred_codes, hist_lo, hist_span) over the mesh.

    `chunks` arrays carry a leading device dim [S*G_seg, R] sharded over
    ('shard','seg'); outputs are replicated.  Steps are memoized per
    (mesh devices, plan) so repeated queries reuse the compiled program.
    """
    cache_key = (
        tuple(d.id for d in mesh.devices.flat),
        mesh.axis_names,
        plan,
    )
    cached = _STEP_CACHE.get(cache_key)
    if cached is not None:
        return cached
    data_spec = P(("shard", "seg"))

    # jax.shard_map is top-level only from 0.5; 0.4.x ships it under
    # jax.experimental — resolve whichever this runtime has
    if hasattr(jax, "shard_map"):
        _shard_map = jax.shard_map
    else:
        from jax.experimental.shard_map import shard_map as _shard_map
    step = _shard_map(
        partial(_step, plan),
        mesh=mesh,
        in_specs=(
            {
                "valid": data_spec,
                "tags": {t: data_spec for t in plan.tags_code},
                "fields": {f: data_spec for f in plan.fields},
            },
            {t: P() for t in plan.eq_preds},
            P(),
            P(),
        ),
        out_specs=_out_specs(plan),
    )

    def run(chunks, pred_codes, hist_lo, hist_span):
        return step(chunks, pred_codes, hist_lo, hist_span)

    jitted = jax.jit(run)
    _STEP_CACHE[cache_key] = jitted
    return jitted


def _out_specs(plan: DistPlan):
    spec = {
        "count": P(),
        "sums": {f: P() for f in plan.fields},
        "mins": {f: P() for f in plan.fields},
        "maxs": {f: P() for f in plan.fields},
    }
    if plan.want_hist:
        spec["hist"] = P()
    if plan.topn:
        spec["top_vals"] = P()
        spec["top_idx"] = P()
    return spec


def stack_shard_chunks(
    mesh: Mesh,
    per_shard_rows: list[dict],
    tags: tuple[str, ...],
    fields: tuple[str, ...],
    nrows: int,
) -> dict:
    """Pack per-shard host rows into mesh-sharded [D, nrows] arrays.

    Each entry of per_shard_rows: {"tags": {t: int32[n]}, "fields":
    {f: f32[n]}} for one device slot; rows beyond nrows are dropped by the
    caller's chunking loop, rows short of nrows are padded invalid.
    """
    d = mesh.devices.size
    assert len(per_shard_rows) == d, (len(per_shard_rows), d)
    valid = np.zeros((d, nrows), dtype=bool)
    tag_arrs = {t: np.zeros((d, nrows), dtype=np.int32) for t in tags}
    field_arrs = {f: np.zeros((d, nrows), dtype=np.float32) for f in fields}
    for i, rows in enumerate(per_shard_rows):
        n = min(len(next(iter(rows["tags"].values()))) if rows["tags"] else 0, nrows)
        if rows["fields"]:
            n = min(
                n if rows["tags"] else nrows,
                *(len(v) for v in rows["fields"].values()),
            )
        valid[i, :n] = True
        for t in tags:
            tag_arrs[t][i, :n] = rows["tags"][t][:n]
        for f in fields:
            field_arrs[f][i, :n] = rows["fields"][f][:n]

    shard_spec = NamedSharding(mesh, P(("shard", "seg")))
    return {
        "valid": jax.device_put(valid, shard_spec),
        "tags": {t: jax.device_put(a, shard_spec) for t, a in tag_arrs.items()},
        "fields": {
            f: jax.device_put(a, shard_spec) for f, a in field_arrs.items()
        },
    }


def distributed_aggregate(
    mesh: Mesh,
    plan: DistPlan,
    chunks: dict,
    pred_codes: Optional[Mapping[str, int]] = None,
    hist_lo: float = 0.0,
    hist_span: float = 1.0,
):
    """Convenience wrapper: build (cached by caller) + run one step."""
    step = build_distributed_step(mesh, plan)
    codes = {
        t: jnp.int32((pred_codes or {}).get(t, -1)) for t in plan.eq_preds
    }
    return step(chunks, codes, jnp.float32(hist_lo), jnp.float32(hist_span))
