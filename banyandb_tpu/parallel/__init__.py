"""Distributed execution over jax.sharding meshes.

The reference scales queries by scatter-gather over data nodes with proto
partial-aggregate exchange (pkg/query/logical/measure/measure_plan_distributed.go:296,
docs/concept/distributed-measure-aggregation.md).  Here the same map-reduce
shape rides the device mesh: each device scans its shard's chunk and the
partial combine is an XLA collective (psum over ICI), not a proto hop.
"""

from banyandb_tpu.parallel.mesh import make_mesh, shard_axis_size
from banyandb_tpu.parallel.dist_exec import (
    DistPlan,
    distributed_aggregate,
    stack_shard_chunks,
)
