"""Mesh construction helpers.

Axis vocabulary (DB analog of dp/tp/sp):
- ``shard``: data parallelism over storage shards — each device scans the
  rows its shard owns (the reference's per-data-node scan).
- ``seg``: segment/time parallelism within a shard — blocks of the same
  shard spread over a second axis (the reference scans segments
  concurrently per node).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh


def make_mesh(
    n_shard: int, n_seg: int = 1, *, devices=None
) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    need = n_shard * n_seg
    if len(devices) < need:
        raise ValueError(
            f"mesh {n_shard}x{n_seg} needs {need} devices, have {len(devices)}"
        )
    import numpy as np

    arr = np.asarray(devices[:need]).reshape(n_shard, n_seg)
    return Mesh(arr, ("shard", "seg"))


def shard_axis_size(mesh: Mesh) -> int:
    return mesh.shape["shard"]
