"""Mesh fast path for cluster measure aggregation.

When the data-node engines live in this process and share one device
mesh (the multi-node-in-one-process test/dryrun topology — and, on real
hardware, a liaison co-located with its data plane on one TPU slice),
the liaison's aggregate path runs the whole map+reduce as ONE jitted
step over the mesh: per-device scan/group/reduce, then psum/pmin/pmax
collectives over ICI (parallel/dist_exec.py) — instead of per-node
serde partials + host-numpy combine.

Reference analog: the vectorized fast-path switch in
pkg/query/vectorized/measure/adapter.go:43 — capability-checked per
query, falling back to the general path on any unsupported shape.

Parity contract: the mesh path reuses the host path's own gather
(measure_exec._gather_rows: row-exact time filter, global-dict recode,
version dedup per node) and its own finalizer
(measure_exec.finalize_partials), so anything the collective reduce
produces is shaped and selected identically to the host combine.
"""

from __future__ import annotations

import math

import numpy as np

from banyandb_tpu.query import measure_exec

_MAX_MESH_GROUPS = 1 << 16
_MIN_CHUNK_ROWS = 256
# Fused dist path: per-device slices are chunked at this fixed width and
# scanned inside ONE collective program (query/fused_exec), so the
# compiled-shape set is bounded instead of one unbounded-width kernel
# per row-count bucket.
_FUSED_DIST_CHUNK = 1 << 16


class MeshUnsupported(Exception):
    """Query shape the mesh plan cannot express; caller falls back."""


def _supported_conds(req) -> list:
    conds, expr = measure_exec._lower_criteria(req.criteria)
    if expr:
        raise MeshUnsupported("OR criteria trees ride the general path")
    names = []
    for c in conds:
        if c.op != "eq":
            raise MeshUnsupported(f"predicate op {c.op} not mesh-lowered")
        names.append(c.name)
    if len(set(names)) != len(names):
        raise MeshUnsupported("duplicate eq predicates on one tag")
    return conds


class MeshExecutor:
    """Executes supported aggregate queries on a shared mesh.

    engines_by_node: node name -> in-process MeasureEngine handle for the
    node's storage (same handles the LocalTransport topology serves).
    """

    def __init__(self, mesh, engines_by_node: dict):
        self.mesh = mesh
        self.engines = engines_by_node
        self.executions = 0  # test observability: fast path actually ran

    def execute(self, m, req, assignment):
        from banyandb_tpu.parallel import dist_exec

        group_tags = set(req.group_by.tag_names) if req.group_by else set()
        if (req.group_by or req.agg) and (
            set(req.tag_projection) - group_tags
        ):
            # representative-tag projection needs the host partial path's
            # scan-order tracking; the collective plane carries dense
            # sums only (applies to grouped AND global aggregates)
            raise MeshUnsupported("projection beyond group tags")
        if not (req.agg or req.group_by):
            raise MeshUnsupported("raw row queries ride scatter-gather")
        conds = _supported_conds(req)
        group_tags = tuple(req.group_by.tag_names) if req.group_by else ()
        agg = req.agg
        want_percentile = bool(agg and agg.function == "percentile")

        fields = set()
        if agg:
            fields.add(agg.field_name)
        if req.top:
            fields.add(req.top.field_name)
        if not fields:
            raise MeshUnsupported("group-by without aggregate field")
        fields = tuple(sorted(fields))

        tags_code = tuple(sorted(set(group_tags) | {c.name for c in conds}))
        gd = measure_exec.GlobalDicts(tags_code)

        # --- select sources per node (its assigned shards only) ----------
        # nodes' gathers are independent (per-node TSDBs; the shared
        # serving cache is lock-guarded), so decode them concurrently —
        # parallel_map preserves assignment order, keeping the combine
        # order (and thus results) identical to the serial loop
        from banyandb_tpu.storage.chunk_stream import parallel_map

        gather_ops = []
        for node, shards in assignment.items():
            eng = self.engines.get(node.name)
            if eng is None:
                raise MeshUnsupported(f"no in-process engine for {node.name}")
            gather_ops.append(
                lambda e=eng, sh=shards: e.gather_query_sources(
                    req, shard_ids=sh
                )
            )
        per_node_srcs = parallel_map(gather_ops)

        # group-cardinality budget BEFORE the expensive row gather/dedup:
        # union the sources' own dictionaries per group tag (dict metadata
        # only, no row work) so an over-budget query falls back cheaply
        est = 1
        for t in group_tags:
            union: set = set()
            for srcs in per_node_srcs:
                for src in srcs:
                    union.update(src.dicts.get(t, ()))
            est *= max(len(union), 1)
        if est > _MAX_MESH_GROUPS:
            raise MeshUnsupported(f"~{est} groups exceed the mesh budget")

        # --- gather rows per node, shared global dicts -------------------
        per_node_cols = []
        for srcs in per_node_srcs:
            cols = measure_exec._gather_rows(
                srcs,
                list(tags_code),
                list(fields),
                gd,
                req.time_range.begin_millis,
                req.time_range.end_millis,
            )
            if cols["ts"].shape[0]:
                per_node_cols.append(cols)

        radices = tuple(gd.size(t) for t in group_tags)
        num_groups = 1
        for r in radices:
            num_groups *= r
        if num_groups > _MAX_MESH_GROUPS:
            raise MeshUnsupported(f"{num_groups} groups exceed mesh budget")

        plan = dist_exec.DistPlan(
            tags_code=tags_code,
            fields=fields,
            group_tags=group_tags,
            radices=radices,
            num_groups=num_groups,
            eq_preds=tuple(c.name for c in conds),
        )
        pred_codes = {
            c.name: gd.code_of(
                c.name, measure_exec._tag_value_bytes(c.value)
            )
            for c in conds
        }

        from banyandb_tpu.query import fused_exec

        # read the A/B flag ONCE per query so pack and aggregate can
        # never disagree mid-flight on the chunk layout
        use_fused = fused_exec.fused_enabled()
        chunks, total, num_chunks = self._pack(
            plan, per_node_cols, use_fused
        )
        if total == 0:
            empty = self._to_partials(plan, gd, None, want_percentile)
            return measure_exec.finalize_partials(m, req, [empty])

        import jax

        # bdlint: disable=host-sync -- mesh result boundary: the whole
        # replicated pytree moves in one batched transfer
        out = jax.device_get(
            self._aggregate(
                plan, chunks, num_chunks, use_fused, pred_codes=pred_codes
            )
        )
        self.executions += 1

        if want_percentile:
            # two-step on the SAME packed chunks: global field range from
            # the first reduce, then a histogram reduce with that range
            # (the cluster path's two-round range agreement, on-mesh)
            f = agg.field_name
            count = np.asarray(out["count"], dtype=np.float64)
            mins = np.asarray(out["mins"][f], dtype=np.float64)
            maxs = np.asarray(out["maxs"][f], dtype=np.float64)
            nz = count > 0
            lo = float(mins[nz].min()) if nz.any() else 0.0
            hi = float(maxs[nz].max()) if nz.any() else 1.0
            span = max(hi - lo, 1e-6)
            hist_plan = dist_exec.DistPlan(
                tags_code=plan.tags_code,
                fields=plan.fields,
                group_tags=plan.group_tags,
                radices=plan.radices,
                num_groups=plan.num_groups,
                eq_preds=plan.eq_preds,
                want_hist=f,
            )
            # bdlint: disable=host-sync -- second-pass result boundary
            out = jax.device_get(
                self._aggregate(
                    hist_plan,
                    chunks,
                    num_chunks,
                    use_fused,
                    pred_codes=pred_codes,
                    hist_lo=lo,
                    hist_span=span,
                )
            )
            partial = self._to_partials(
                hist_plan, gd, out, True, hist_lo=lo, hist_span=span
            )
        else:
            partial = self._to_partials(plan, gd, out, False)
        return measure_exec.finalize_partials(m, req, [partial])

    # -- execution ---------------------------------------------------------
    def _aggregate(
        self,
        plan,
        chunks,
        num_chunks,
        use_fused,
        pred_codes=None,
        hist_lo: float = 0.0,
        hist_span: float = 1.0,
    ):
        """One collective reduce over the mesh: the fused chunked-scan
        step when the A/B flag is on, the legacy single-width step
        otherwise (both carry the identical psum/pmin/pmax set)."""
        from banyandb_tpu.parallel import dist_exec
        from banyandb_tpu.query import fused_exec

        if use_fused:
            return fused_exec.fused_distributed_aggregate(
                self.mesh,
                plan,
                num_chunks,
                chunks,
                pred_codes=pred_codes,
                hist_lo=hist_lo,
                hist_span=hist_span,
            )
        return dist_exec.distributed_aggregate(
            self.mesh,
            plan,
            chunks,
            pred_codes=pred_codes,
            hist_lo=hist_lo,
            hist_span=hist_span,
        )

    # -- packing -----------------------------------------------------------
    def _pack(self, plan, per_node_cols, use_fused: bool = False):
        """Distribute all (already per-node deduped) rows over the mesh's
        device slots as [D, num_chunks * nrows] arrays.

        Legacy (staged) layout is one chunk whose width is the
        power-of-two bucket of the per-device row count — unbounded as
        data grows, one XLA compile per new bucket.  The fused layout
        caps the chunk width at _FUSED_DIST_CHUNK and buckets the CHUNK
        COUNT instead (scanned on-device inside the one collective
        program), bounding the compile-shape set; below the cap the two
        layouts — and their math — are identical."""
        d = int(self.mesh.devices.size)
        if per_node_cols:
            tags = {
                t: np.concatenate([c["tags_code"][t] for c in per_node_cols])
                for t in plan.tags_code
            }
            flds = {
                f: np.concatenate(
                    [c["fields"][f] for c in per_node_cols]
                ).astype(np.float32)
                for f in plan.fields
            }
            total = next(iter(tags.values())).shape[0] if tags else (
                next(iter(flds.values())).shape[0]
            )
        else:
            tags = {t: np.zeros(0, np.int32) for t in plan.tags_code}
            flds = {f: np.zeros(0, np.float32) for f in plan.fields}
            total = 0

        per = max(math.ceil(total / d) if total else 1, 1)
        nrows = max(1 << (per - 1).bit_length(), _MIN_CHUNK_ROWS)
        num_chunks = 1
        if use_fused and nrows > _FUSED_DIST_CHUNK:
            from banyandb_tpu.query import fused_exec

            num_chunks = fused_exec.chunk_count_bucket(
                math.ceil(per / _FUSED_DIST_CHUNK)
            )
            nrows = _FUSED_DIST_CHUNK
        slots = []
        for i in range(d):
            s, e = i * per, min((i + 1) * per, total)
            slots.append(
                {
                    "tags": {t: a[s:e] for t, a in tags.items()},
                    "fields": {f: a[s:e] for f, a in flds.items()},
                }
            )
        from banyandb_tpu.parallel import dist_exec

        chunks = dist_exec.stack_shard_chunks(
            self.mesh, slots, plan.tags_code, plan.fields, num_chunks * nrows
        )
        return chunks, total, num_chunks

    # -- result shaping ----------------------------------------------------
    @staticmethod
    def _to_partials(
        plan, gd, out, want_hist, hist_lo: float = 0.0, hist_span: float = 1.0
    ):
        if out is None:
            return measure_exec.Partials(
                group_tags=plan.group_tags,
                groups=[],
                count=np.zeros(0, dtype=np.float64),
                sums={f: np.zeros(0, dtype=np.float64) for f in plan.fields},
                mins={f: np.zeros(0, dtype=np.float64) for f in plan.fields},
                maxs={f: np.zeros(0, dtype=np.float64) for f in plan.fields},
            )
        count = np.asarray(out["count"], dtype=np.float64)
        nz = np.nonzero(count > 0)[0]
        values = {t: gd.values(t) for t in plan.group_tags}
        if plan.group_tags:
            codes = np.unravel_index(nz, plan.radices)
            groups = [
                tuple(
                    values[t][codes[i][k]]
                    for i, t in enumerate(plan.group_tags)
                )
                for k in range(nz.size)
            ]
        else:
            groups = [()] if nz.size else []
        take = lambda a: np.asarray(a, dtype=np.float64)[nz]  # noqa: E731
        partial = measure_exec.Partials(
            group_tags=plan.group_tags,
            groups=groups,
            count=count[nz],
            sums={f: take(out["sums"][f]) for f in plan.fields},
            mins={f: take(out["mins"][f]) for f in plan.fields},
            maxs={f: take(out["maxs"][f]) for f in plan.fields},
        )
        if want_hist and plan.want_hist:
            partial.hist = np.asarray(out["hist"], dtype=np.float64)[nz]
            partial.hist_lo = hist_lo
            partial.hist_span = hist_span
        for f in plan.fields:
            if nz.size:
                partial.field_stats[f] = (
                    float(partial.mins[f].min()),
                    float(partial.maxs[f].max()),
                )
        return partial
