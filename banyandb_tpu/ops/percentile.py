"""Per-group percentile/quantile kernels.

The reference has no first-class percentile aggregate (clients post-process
bucketed measures); SURVEY.md §7 step 1 promotes it to a native aggregate.
Device strategy: fixed-bucket histogram per group via one segment reduction
over the combined (group, bucket) id, then vectorized CDF inversion with
linear interpolation inside the hit bucket.  Exactness contract: within one
bucket width over [lo, hi]; callers needing exact values run sort-based
quantile on a single group.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def group_histogram(
    key: jax.Array,
    valid: jax.Array,
    values: jax.Array,
    num_groups: int,
    lo,
    span,
    num_buckets: int = 512,
) -> jax.Array:
    """-> f32 [num_groups, num_buckets] per-group counts over [lo, lo+span].

    `lo`/`span` may be traced scalars (two-pass percentile reuses one
    compiled kernel across queries). The single shared histogram kernel —
    percentile, the measure executor, and the distributed step all call
    this.
    """
    if (num_groups + 1) * num_buckets >= 2**31:
        # The combined (group, bucket) segment id must fit int32 or scatter
        # indices silently wrap under jit (same guard as mixed_radix_key).
        raise ValueError(
            f"num_groups={num_groups} x num_buckets={num_buckets} "
            "overflows int32 segment ids"
        )
    width = span / num_buckets
    bucket = jnp.clip(
        ((values - lo) / width).astype(jnp.int32), 0, num_buckets - 1
    )
    safe_key = jnp.where(valid, key, jnp.int32(num_groups))
    combined = safe_key * jnp.int32(num_buckets) + bucket
    return jax.ops.segment_sum(
        valid.astype(jnp.float32),
        combined,
        num_segments=(num_groups + 1) * num_buckets,
    ).reshape(num_groups + 1, num_buckets)[:num_groups]


def group_percentile_histogram(
    key: jax.Array,
    valid: jax.Array,
    values: jax.Array,
    num_groups: int,
    quantiles,
    *,
    lo: float,
    hi: float,
    num_buckets: int = 512,
) -> jax.Array:
    """-> f32 [num_groups, len(quantiles)] interpolated quantile estimates.

    Values are clamped into [lo, hi]; empty groups return lo.
    """
    q = jnp.asarray(quantiles, dtype=jnp.float32)
    width = (hi - lo) / num_buckets
    counts = group_histogram(
        key, valid, values, num_groups, lo, hi - lo, num_buckets
    )

    cdf = jnp.cumsum(counts, axis=-1)  # [G, B]
    total = cdf[:, -1:]  # [G, 1]
    # Rank of the q-quantile: ceil(q*N) clamped to [1, N] so q=0 lands on the
    # min-value bucket rather than degenerating to `lo`.
    target = jnp.clip(jnp.ceil(q[None, :] * total), 1.0, jnp.maximum(total, 1.0))
    # First bucket whose cumulative count reaches the target rank.
    hit = jnp.argmax(cdf[:, None, :] >= target[:, :, None], axis=-1)  # [G, Q]
    cdf_at = jnp.take_along_axis(cdf, hit, axis=-1)
    cnt_at = jnp.take_along_axis(counts, hit, axis=-1)
    prev_cdf = cdf_at - cnt_at
    # Linear interpolation of the rank inside the hit bucket.
    frac = jnp.where(cnt_at > 0, (target - prev_cdf) / jnp.maximum(cnt_at, 1.0), 0.0)
    est = lo + (hit.astype(jnp.float32) + jnp.clip(frac, 0.0, 1.0)) * width
    return jnp.where(total > 0, est, lo)
