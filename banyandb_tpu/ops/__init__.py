"""Device kernel substrate: the TPU-native columnar execution primitives.

This layer replaces the reference's per-row Go scan loop
(banyand/measure/query.go:594, pkg/query/vectorized/) with dense, statically
shaped JAX computations that XLA fuses onto the TPU's VPU/MXU.
"""

from banyandb_tpu.ops.blocks import ColumnBatch, pad_rows_bucket
from banyandb_tpu.ops.decode import (
    decode_chunk,
    delta_decode,
    dict_gather,
    dict_remap,
    dod_decode,
    ints_to_f32,
    widen_codes,
)
from banyandb_tpu.ops.filter import (
    mask_and,
    mask_or,
    mask_not,
    cmp_mask,
    in_set_mask,
    time_range_mask,
)
from banyandb_tpu.ops.groupby import (
    mixed_radix_key,
    group_reduce,
    GroupReduceResult,
)
from banyandb_tpu.ops.topk import topk_groups
from banyandb_tpu.ops.percentile import (
    group_histogram,
    group_percentile_histogram,
)
from banyandb_tpu.ops.dedup import latest_by_version
