"""Group-by + aggregation kernels.

The reference aggregates with Go hash maps over decoded rows
(pkg/query/aggregation, pkg/query/vectorized/measure/groupby_agg.go).  On
TPU there is no hash table: tags are dictionary codes, so a group key is a
*mixed-radix* int32 composed from the code columns, bounded by the product
of dictionary sizes.  Aggregation is then a dense segment reduction:

- ``scatter``: jax.ops.segment_sum/min/max (XLA scatter).
- ``matmul``: one-hot(keys) @ values on the MXU in one shot — for modest
  group counts (<= ~4096) and row counts that fit a single operand.
- ``matmul_tiled``: lax.scan over row tiles of MXU one-hot contractions —
  the TPU path for large N where one-shot matmul won't fit and scatter
  underuses the hardware.

All produce identical results; ``method="auto"`` picks per shape and
backend (TPU prefers the MXU paths).
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp

def mixed_radix_key(
    columns: Sequence[jax.Array], radices: Sequence[int]
) -> tuple[jax.Array, int]:
    """Compose dictionary-code columns into a single dense group key.

    key = ((c0*r1 + c1)*r2 + c2)... ; group count = prod(radices).
    Host code recovers per-tag codes with np.unravel_index(key, radices).
    """
    assert len(columns) == len(radices) and columns
    total = 1
    for r in radices:
        total *= int(r)
    if total >= 2**31:
        # int32 keys would wrap on device and silently merge groups; callers
        # must pre-reduce cardinality (hash-bucket tags) before grouping.
        raise ValueError(
            f"group cardinality {total} overflows int32 keys; "
            "bucket the tag dictionaries first"
        )
    key = columns[0].astype(jnp.int32)
    for c, r in zip(columns[1:], radices[1:]):
        key = key * jnp.int32(r) + c.astype(jnp.int32)
    return key, total


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GroupReduceResult:
    """Per-group aggregates; arrays have leading dim num_groups."""

    count: jax.Array  # f32 [G] — valid-row count per group
    sums: Mapping[str, jax.Array]  # f32 [G] per field
    mins: Mapping[str, jax.Array]  # f32 [G] per field (+inf when empty)
    maxs: Mapping[str, jax.Array]  # f32 [G] per field (-inf when empty)

    def mean(self, field: str) -> jax.Array:
        return self.sums[field] / jnp.maximum(self.count, 1.0)

    @property
    def nonempty(self) -> jax.Array:
        return self.count > 0


def _pick_method(nrows: int, num_groups: int) -> str:
    # One-hot matmul materializes an [N, G+1] f32 operand through the MXU;
    # worth it while G stays in the low thousands AND the operand stays
    # under a VMEM-friendly working set.  Past that, TPUs still prefer the
    # tiled MXU scan (scatter is slow on TPU); other backends scatter.
    if num_groups <= 4096:
        if nrows * (num_groups + 1) <= 2**25:
            return "matmul"
        if jax.default_backend() == "tpu":
            return "matmul_tiled"
    return "scatter"


def group_reduce(
    key: jax.Array,
    valid: jax.Array,
    fields: Mapping[str, jax.Array],
    num_groups: int,
    *,
    want_minmax: bool = True,
    method: str = "auto",
) -> GroupReduceResult:
    """Segment-reduce rows into per-group count/sum/min/max.

    Invalid rows are routed to a spill group (index num_groups) and dropped,
    so padding never pollutes real groups.
    """
    if method == "auto":
        method = _pick_method(key.shape[-1], num_groups)

    validf = valid.astype(jnp.float32)
    safe_key = jnp.where(valid, key, jnp.int32(num_groups))

    if method == "matmul":
        # [N, G+1] one-hot; MXU contraction gives counts and sums in one
        # fused pass per field.  f32 accumulate keeps int-valued fields exact
        # up to 2^24 per group partial (parts are merged in f64 on host).
        groups = jax.lax.broadcasted_iota(jnp.int32, (num_groups + 1,), 0)
        onehot = (safe_key[:, None] == groups[None, :]).astype(jnp.float32)
        count = (validf @ onehot)[:num_groups]
        sums = {
            name: ((col * validf) @ onehot)[:num_groups]
            for name, col in fields.items()
        }
    elif method == "matmul_tiled":
        # Large-N variant: scan over row tiles so each [TILE, G+1] one-hot
        # stays VMEM-sized while sums still ride the MXU — the TPU
        # alternative to scatter when N*G won't fit at once.
        TILE = 8192
        n = safe_key.shape[-1]
        pad = (-n) % TILE
        kp = jnp.pad(safe_key, (0, pad), constant_values=num_groups)
        vp = jnp.pad(validf, (0, pad))
        fps = {name: jnp.pad(col, (0, pad)) for name, col in fields.items()}
        groups = jax.lax.broadcasted_iota(jnp.int32, (num_groups + 1,), 0)
        names = sorted(fields.keys())

        def tile_fn(carry, xs):
            k_t, v_t, f_t = xs
            onehot = (k_t[:, None] == groups[None, :]).astype(jnp.float32)
            cnt = carry[0] + v_t @ onehot
            sums_t = [
                carry[1 + i] + (f_t[i] * v_t) @ onehot
                for i in range(len(names))
            ]
            return (cnt, *sums_t), None

        init = tuple(
            jnp.zeros(num_groups + 1, jnp.float32) for _ in range(1 + len(names))
        )
        tiles = (
            kp.reshape(-1, TILE),
            vp.reshape(-1, TILE),
            jnp.stack([fps[nm].reshape(-1, TILE) for nm in names], axis=1)
            if names
            else jnp.zeros((kp.shape[0] // TILE, 0, TILE), jnp.float32),
        )
        out, _ = jax.lax.scan(tile_fn, init, tiles)
        count = out[0][:num_groups]
        sums = {nm: out[1 + i][:num_groups] for i, nm in enumerate(names)}
    elif method == "scatter":
        seg = jax.ops.segment_sum
        count = seg(validf, safe_key, num_segments=num_groups + 1)[:num_groups]
        sums = {
            name: seg(col * validf, safe_key, num_segments=num_groups + 1)[
                :num_groups
            ]
            for name, col in fields.items()
        }
    else:
        raise ValueError(f"unknown group_reduce method {method!r}")

    mins: dict[str, jax.Array] = {}
    maxs: dict[str, jax.Array] = {}
    if want_minmax:
        # Invalid rows are already routed to the sliced-off spill segment by
        # safe_key, so no value masking is needed here.
        for name, col in fields.items():
            mins[name] = jax.ops.segment_min(
                col, safe_key, num_segments=num_groups + 1
            )[:num_groups]
            maxs[name] = jax.ops.segment_max(
                col, safe_key, num_segments=num_groups + 1
            )[:num_groups]

    return GroupReduceResult(count=count, sums=sums, mins=mins, maxs=maxs)
