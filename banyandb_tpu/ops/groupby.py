"""Group-by + aggregation kernels.

The reference aggregates with Go hash maps over decoded rows
(pkg/query/aggregation, pkg/query/vectorized/measure/groupby_agg.go).  On
TPU there is no hash table: tags are dictionary codes, so a group key is a
*mixed-radix* int32 composed from the code columns, bounded by the product
of dictionary sizes.  Aggregation is then a dense segment reduction:

- ``scatter``: jax.ops.segment_sum/min/max (XLA scatter).
- ``matmul``: one-hot(keys) @ values on the MXU in one shot — for modest
  group counts (<= ~4096) and row counts that fit a single operand.
- ``matmul_tiled``: lax.scan over row tiles of MXU one-hot contractions.
  Kept as an oracle/fallback; measured slower than pallas on real TPU
  (docs/tpu_measurements.md) so ``auto`` never picks it.
- ``pallas``: the hand-tiled Pallas kernel (ops.pallas_kernels) for
  count/sums; min/max still ride XLA scatter.
- ``sort``: segment-sort grouping — stable sort by key, then the same
  bounded-span scatter reduction over now-contiguous group runs.  The
  high-radix regime of the hash-vs-sort crossover (arXiv 2411.13245).

All produce identical results; ``method="auto"`` resolves through
``select_group_method`` per shape and backend from the measured
crossovers (sort above SORT_GROUPS_THRESHOLD groups on any backend;
below it TPU: pallas for bounded group counts, else scatter; off-TPU:
matmul for small operands, else scatter).

Precision contract (tested by tests/test_precision.py): per-group sums
accumulate in f32 *within* a bounded row tile (<= 65536 rows for scatter,
8192 for matmul_tiled, 2048 for pallas); tile partials combine across
tiles with Kahan-compensated f32, so the cross-tile error is O(eps)
independent of total row count. The one-shot ``matmul`` path is only
selected for operands <= 2^25 elements (<= ~32k rows at G=1024), where a
single f32 MXU contraction stays within ~K*eps/2 of exact. Callers
merging partials across kernel invocations (measure_exec, the cluster
combine plane) accumulate in f64 on the host. Counts are integer-valued
and exact to 2^24 per tile — far above any tile bound here.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp

def mixed_radix_key(
    columns: Sequence[jax.Array], radices: Sequence[int]
) -> tuple[jax.Array, int]:
    """Compose dictionary-code columns into a single dense group key.

    key = ((c0*r1 + c1)*r2 + c2)... ; group count = prod(radices).
    Host code recovers per-tag codes with np.unravel_index(key, radices).
    """
    assert len(columns) == len(radices) and columns
    total = 1
    for r in radices:
        total *= int(r)
    if total >= 2**31:
        # int32 keys would wrap on device and silently merge groups; callers
        # must pre-reduce cardinality (hash-bucket tags) before grouping.
        raise ValueError(
            f"group cardinality {total} overflows int32 keys; "
            "bucket the tag dictionaries first"
        )
    key = columns[0].astype(jnp.int32)
    for c, r in zip(columns[1:], radices[1:]):
        key = key * jnp.int32(r) + c.astype(jnp.int32)
    return key, total


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GroupReduceResult:
    """Per-group aggregates; arrays have leading dim num_groups."""

    count: jax.Array  # f32 [G] — valid-row count per group
    sums: Mapping[str, jax.Array]  # f32 [G] per field
    mins: Mapping[str, jax.Array]  # f32 [G] per field (+inf when empty)
    maxs: Mapping[str, jax.Array]  # f32 [G] per field (-inf when empty)

    def mean(self, field: str) -> jax.Array:
        return self.sums[field] / jnp.maximum(self.count, 1.0)

    @property
    def nonempty(self) -> jax.Array:
        return self.count > 0


def _kahan_add(s: jax.Array, c: jax.Array, x: jax.Array):
    """One compensated accumulation step; true sum ~= s - c."""
    y = x - c
    t = s + y
    return t, (t - s) - y


def _kahan_tiled_reduce(
    safe_key: jax.Array,
    validf: jax.Array,
    masked_fields: Mapping[str, jax.Array],
    num_groups: int,
    tile: int,
    partial_fn,
):
    """Shared scaffold for bounded-span accumulation (precision contract):
    pad rows to a tile multiple, scan tiles, Kahan-combine the per-tile
    [G+1] partials produced by ``partial_fn(key_t, valid_t, fields_t)``
    (ordered [count, field_0, ...]; fields arrive pre-masked by validf).
    -> (count [G], sums {name: [G]})."""
    names = sorted(masked_fields.keys())
    n = safe_key.shape[-1]
    pad = (-n) % tile
    kp = jnp.pad(safe_key, (0, pad), constant_values=num_groups)
    vp = jnp.pad(validf, (0, pad))
    fps = {nm: jnp.pad(masked_fields[nm], (0, pad)) for nm in names}

    def step(carry, xs):
        parts = partial_fn(*xs)
        return (
            tuple(_kahan_add(s, c, p) for (s, c), p in zip(carry, parts)),
            None,
        )

    zero = jnp.zeros(num_groups + 1, jnp.float32)
    init = tuple((zero, zero) for _ in range(1 + len(names)))
    tiles = (
        kp.reshape(-1, tile),
        vp.reshape(-1, tile),
        jnp.stack([fps[nm].reshape(-1, tile) for nm in names], axis=1)
        if names
        else jnp.zeros((kp.shape[0] // tile, 0, tile), jnp.float32),
    )
    out, _ = jax.lax.scan(step, init, tiles)
    count = (out[0][0] - out[0][1])[:num_groups]
    sums = {
        nm: (out[1 + i][0] - out[1 + i][1])[:num_groups]
        for i, nm in enumerate(names)
    }
    return count, sums


# High-radix crossover for hash- vs sort-based grouping.  The empirical
# study arXiv 2411.13245 finds scatter-style hash grouping wins while the
# per-group accumulator table stays cache/VMEM-resident (low-radix
# dictionary keys) and segment-sort grouping wins once the table spills
# (high-radix or unknown-cardinality keys): sorted runs stream memory
# sequentially instead of scattering over a huge [G] table.
SORT_GROUPS_THRESHOLD = 1 << 16


def select_group_method(nrows: int, num_groups: int) -> str:
    """Per-signature group-by strategy (the ``method="auto"`` policy).

    Both the staged and the fused whole-plan executor resolve through
    this ONE function from the same (nrows, num_groups) signature
    fields, so an A/B flip can never pair different reduction orders —
    and the ``sort`` path is stable-sorted, keeping per-group
    accumulation in row order (bit-identical to ``scatter``).

    Measured on a real v5e-1 (2026-07-29, docs/tpu_measurements.md): the
    Pallas kernel is best-or-equal at every (N, G) tried — 11.3 Grows/s
    at N=2^23 standalone vs 5.8 for one-shot matmul (which also OOMs
    once N*(G+1) f32 exceeds HBM) and ~15 Mrows/s for eager scatter /
    matmul_tiled, which drown in per-op dispatch.  Inside a fused jit
    XLA's scatter reaches HBM bandwidth too, but pallas never loses, so
    TPU takes it for bounded group counts (4 group tiles at GTILE=2048:
    each extra tile re-streams the whole input from HBM).  Off-TPU,
    pallas only interprets; one-hot matmul wins small operands.  Above
    SORT_GROUPS_THRESHOLD groups (either backend) the accumulator table
    no longer fits close storage and segment-sort grouping takes over
    per the 2411.13245 crossover.
    """
    if num_groups > SORT_GROUPS_THRESHOLD:
        return "sort"
    if jax.default_backend() == "tpu" and num_groups <= 4 * 2048:
        return "pallas"
    if num_groups <= 4096 and nrows * (num_groups + 1) <= 2**25:
        return "matmul"
    return "scatter"


# back-compat alias (pre-fused-executor name)
_pick_method = select_group_method


def _scatter_reduce(
    safe_key: jax.Array,
    validf: jax.Array,
    masked_fields: Mapping[str, jax.Array],
    num_groups: int,
):
    """count/sums via XLA scatter, Kahan-tiled beyond the span bound.

    Shared by the hash (``scatter``) and segment-sort (``sort``) paths:
    fields arrive pre-masked (col * validf), rows beyond the span bound
    combine with Kahan-compensated f32 (precision contract above).
    """
    seg = jax.ops.segment_sum
    CHUNK = 65536
    if safe_key.shape[-1] <= CHUNK:
        count = seg(validf, safe_key, num_segments=num_groups + 1)[:num_groups]
        sums = {
            name: seg(col, safe_key, num_segments=num_groups + 1)[:num_groups]
            for name, col in masked_fields.items()
        }
        return count, sums

    def sc_partial(k_t, v_t, f_t):
        return [seg(v_t, k_t, num_segments=num_groups + 1)] + [
            seg(f_t[i], k_t, num_segments=num_groups + 1)
            for i in range(f_t.shape[0])
        ]

    return _kahan_tiled_reduce(
        safe_key, validf, masked_fields, num_groups, CHUNK, sc_partial
    )


def group_reduce(
    key: jax.Array,
    valid: jax.Array,
    fields: Mapping[str, jax.Array],
    num_groups: int,
    *,
    want_minmax: bool = True,
    method: str = "auto",
) -> GroupReduceResult:
    """Segment-reduce rows into per-group count/sum/min/max.

    Invalid rows are routed to a spill group (index num_groups) and dropped,
    so padding never pollutes real groups.
    """
    if method == "auto":
        method = select_group_method(key.shape[-1], num_groups)

    validf = valid.astype(jnp.float32)
    safe_key = jnp.where(valid, key, jnp.int32(num_groups))

    if method == "matmul":
        # [N, G+1] one-hot; MXU contraction gives counts and sums in one
        # fused pass per field.  f32 accumulate keeps int-valued fields exact
        # up to 2^24 per group partial (parts are merged in f64 on host).
        groups = jax.lax.broadcasted_iota(jnp.int32, (num_groups + 1,), 0)
        onehot = (safe_key[:, None] == groups[None, :]).astype(jnp.float32)
        count = (validf @ onehot)[:num_groups]
        sums = {
            name: ((col * validf) @ onehot)[:num_groups]
            for name, col in fields.items()
        }
    elif method == "matmul_tiled":
        # Large-N variant: scan over row tiles so each [TILE, G+1] one-hot
        # stays VMEM-sized while sums still ride the MXU — the TPU
        # alternative to scatter when N*G won't fit at once.  Tile partials
        # combine with Kahan-compensated f32 (precision contract above).
        groups = jax.lax.broadcasted_iota(jnp.int32, (num_groups + 1,), 0)

        def mm_partial(k_t, v_t, f_t):
            onehot = (k_t[:, None] == groups[None, :]).astype(jnp.float32)
            return [v_t @ onehot] + [
                f_t[i] @ onehot for i in range(f_t.shape[0])
            ]

        count, sums = _kahan_tiled_reduce(
            safe_key,
            validf,
            {nm: col * validf for nm, col in fields.items()},
            num_groups,
            8192,
            mm_partial,
        )
    elif method == "scatter":
        count, sums = _scatter_reduce(
            safe_key,
            validf,
            {nm: col * validf for nm, col in fields.items()},
            num_groups,
        )
    elif method == "sort":
        # Segment-sort grouping (the 2411.13245 high-radix regime): a
        # STABLE sort by group key makes every group a contiguous run,
        # so the reduction streams memory sequentially instead of
        # scattering over a [G] table that no longer fits close storage.
        # Stability keeps per-group accumulation in row order — within
        # the span bound the result is bit-identical to the hash path.
        order = jnp.argsort(safe_key, stable=True)
        count, sums = _scatter_reduce(
            safe_key[order],
            validf[order],
            {nm: (col * validf)[order] for nm, col in fields.items()},
            num_groups,
        )
    elif method == "pallas":
        # Hand-tiled kernel: one pass computes count + ALL field sums
        # (compiled on TPU, interpret elsewhere); min/max below still
        # ride XLA scatter.
        from banyandb_tpu.ops import pallas_kernels

        interpret = jax.default_backend() != "tpu"
        n = safe_key.shape[-1]
        pad = (-n) % pallas_kernels.TILE
        kp = jnp.pad(safe_key, (0, pad), constant_values=num_groups)
        vp = jnp.pad(valid, (0, pad))
        names = sorted(fields.keys())
        vals = (
            jnp.stack(
                [
                    jnp.pad(fields[nm].astype(jnp.float32), (0, pad))
                    for nm in names
                ]
            )
            if names
            else jnp.zeros((0, kp.shape[0]), jnp.float32)
        )
        count, sums_arr = pallas_kernels.fused_group_multi(
            kp,
            jnp.ones_like(kp, dtype=bool),
            vals,
            vp,
            num_groups=num_groups,
            interpret=interpret,
        )
        sums = {nm: sums_arr[i] for i, nm in enumerate(names)}
    else:
        raise ValueError(f"unknown group_reduce method {method!r}")

    mins: dict[str, jax.Array] = {}
    maxs: dict[str, jax.Array] = {}
    if want_minmax:
        # Invalid rows are already routed to the sliced-off spill segment by
        # safe_key, so no value masking is needed here.
        for name, col in fields.items():
            mins[name] = jax.ops.segment_min(
                col, safe_key, num_segments=num_groups + 1
            )[:num_groups]
            maxs[name] = jax.ops.segment_max(
                col, safe_key, num_segments=num_groups + 1
            )[:num_groups]

    return GroupReduceResult(count=count, sums=sums, mins=mins, maxs=maxs)
