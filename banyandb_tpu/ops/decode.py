"""Device-side decode kernels.

The reference decodes int64 columns on the CPU with delta / delta-of-delta +
zigzag varint (pkg/encoding/int_list.go:27) and dictionary-encodes low-
cardinality byte columns (pkg/encoding/dictionary.go).  On TPU, variable-
width varint decode is hostile to the VPU, so the on-disk format (see
banyandb_tpu.utils.encoding) stores *fixed-width* deltas; the prefix-sum
reconstruction and dictionary gather run on device where they fuse into the
scan pipeline.
"""

from __future__ import annotations

import jax.numpy as jnp


def delta_decode(first, deltas):
    """Reconstruct the FULL series of ``len(deltas) + 1`` values:
    out[0] == first, out[i] == first + sum(deltas[:i]).

    Matches the on-disk encoder (utils/encoding.encode_int64: `first` stored
    separately + np.diff payload) so a device caller can feed the decoded
    delta payload directly.  Mirrors encoding.EncodeTypeDelta
    (pkg/encoding/int_list.go:60) as a cumsum instead of a sequential loop.
    """
    first = jnp.asarray(first, dtype=deltas.dtype)
    rest = first[..., None] + jnp.cumsum(deltas, axis=-1, dtype=deltas.dtype)
    head = jnp.broadcast_to(first[..., None], rest.shape[:-1] + (1,))
    return jnp.concatenate([head, rest], axis=-1)


def dod_decode(first, first_delta, dods):
    """Delta-of-delta decode (pkg/encoding/int_list.go:66 analog).

    Reconstructs the FULL series of ``len(dods) + 1`` values from second
    differences with two cumsums: out[0] == first,
    out[1] == first + first_delta + dods[0] (encoders emit dods[0] = 0),
    out[i] == out[i-1] + (first_delta + sum(dods[:i])).
    """
    first = jnp.asarray(first, dtype=dods.dtype)
    deltas = first_delta + jnp.cumsum(dods, axis=-1, dtype=dods.dtype)
    rest = first[..., None] + jnp.cumsum(deltas, axis=-1, dtype=deltas.dtype)
    head = jnp.broadcast_to(first[..., None], rest.shape[:-1] + (1,))
    return jnp.concatenate([head, rest], axis=-1)


def dict_gather(dictionary, codes):
    """Materialize dictionary-encoded values: out[i] = dictionary[codes[i]].

    The scan pipeline usually *avoids* this by pushing predicates onto the
    codes themselves (storage-and-format.md§7.3 dictionary-as-filter); this
    exists for projections of numeric dictionary columns.
    """
    return jnp.take(dictionary, codes, axis=0)
