"""Device-side decode kernels.

The reference decodes int64 columns on the CPU with delta / delta-of-delta +
zigzag varint (pkg/encoding/int_list.go:27) and dictionary-encodes low-
cardinality byte columns (pkg/encoding/dictionary.go).  On TPU, variable-
width varint decode is hostile to the VPU, so the on-disk format (see
banyandb_tpu.utils.encoding) stores *fixed-width* deltas; the prefix-sum
reconstruction and dictionary gather run on device where they fuse into the
scan pipeline.
"""

from __future__ import annotations

import jax.numpy as jnp


def delta_decode(first, deltas):
    """Reconstruct the FULL series of ``len(deltas) + 1`` values:
    out[0] == first, out[i] == first + sum(deltas[:i]).

    Matches the on-disk encoder (utils/encoding.encode_int64: `first` stored
    separately + np.diff payload) so a device caller can feed the decoded
    delta payload directly.  Mirrors encoding.EncodeTypeDelta
    (pkg/encoding/int_list.go:60) as a cumsum instead of a sequential loop.
    Narrow i8/i16 delta payloads always widen to i32 first (a narrow
    cumsum would wrap), so the output dtype is max(deltas.dtype, i32) on
    every backend.  ``first`` must fit the compute dtype — raw int64
    column heads (absolute timestamps) must be REBASED by the caller
    (the chunk pipeline's epoch-relative convention) or decoded with
    i64 deltas under host x64; a concrete out-of-range ``first`` raises
    instead of silently wrapping.  On TPU the 1-D i32 shape class
    routes through the tiled Pallas prefix-sum kernel
    (ops/pallas_kernels.prefix_sum_narrow), bit-identical to the jnp
    cumsum fallback below.
    """
    import numpy as _np

    import jax

    if deltas.dtype in (jnp.int8, jnp.int16):
        deltas = deltas.astype(jnp.int32)
    if (
        isinstance(first, (int, _np.integer))  # concrete host scalar
        and deltas.dtype == jnp.int32
        and not -(2**31) <= first < 2**31
    ):
        raise ValueError(
            f"first={first} does not fit the i32 decode width; "
            "rebase it to an epoch offset (ts - epoch) or pass i64 deltas"
        )
    if (
        jax.default_backend() == "tpu"
        and deltas.ndim == 1
        and deltas.dtype == jnp.int32
    ):
        from banyandb_tpu.ops import pallas_kernels

        if (deltas.shape[0] + 1) % pallas_kernels.TILE == 0:
            x = jnp.concatenate(
                [jnp.asarray(first, jnp.int32)[None], deltas]
            )
            return pallas_kernels.prefix_sum_narrow(x)
    first = jnp.asarray(first, dtype=deltas.dtype)
    rest = first[..., None] + jnp.cumsum(deltas, axis=-1, dtype=deltas.dtype)
    head = jnp.broadcast_to(first[..., None], rest.shape[:-1] + (1,))
    return jnp.concatenate([head, rest], axis=-1)


def dod_decode(first, first_delta, dods):
    """Delta-of-delta decode (pkg/encoding/int_list.go:66 analog).

    Reconstructs the FULL series of ``len(dods) + 1`` values from second
    differences with two cumsums: out[0] == first,
    out[1] == first + first_delta + dods[0] (encoders emit dods[0] = 0),
    out[i] == out[i-1] + (first_delta + sum(dods[:i])).
    """
    first = jnp.asarray(first, dtype=dods.dtype)
    deltas = first_delta + jnp.cumsum(dods, axis=-1, dtype=dods.dtype)
    rest = first[..., None] + jnp.cumsum(deltas, axis=-1, dtype=deltas.dtype)
    head = jnp.broadcast_to(first[..., None], rest.shape[:-1] + (1,))
    return jnp.concatenate([head, rest], axis=-1)


def dict_gather(dictionary, codes):
    """Materialize dictionary-encoded values: out[i] = dictionary[codes[i]].

    The scan pipeline usually *avoids* this by pushing predicates onto the
    codes themselves (storage-and-format.md§7.3 dictionary-as-filter); this
    exists for projections of numeric dictionary columns.  Out-of-range
    codes clip to the dictionary bounds instead of wrapping (the OOB
    guard: a corrupt code must never read another row's slot).
    """
    return jnp.take(dictionary, codes, axis=0, mode="clip")


def widen_codes(codes):
    """Narrow stored-width dict codes (i8/i16) -> the i32 the plan
    kernels consume.  THE hot decode op of the compressed-ship path: the
    column crossed PCIe at stored width and widens here, on device."""
    return codes.astype(jnp.int32)


def dict_remap(codes, lut2d, src_ord):
    """Local -> global dictionary code remap, on device.

    ``codes``: narrow per-row LOCAL codes (any shape), ``src_ord``: the
    per-row source ordinal (same shape), ``lut2d``: ``[S, L]`` i32 table
    whose row ``s`` maps source s's local codes to global codes
    (storage/encoded.pack_luts).  Replaces the host-side per-source
    ``lut[codes]`` gather of the decoded path; exact integer math, so
    the A/B is byte-identical.  The flattened take clips (OOB guard) —
    in-range by construction, never wrapping on corrupt input."""
    flat = lut2d.reshape(-1)
    idx = (
        src_ord.astype(jnp.int32) * lut2d.shape[-1]
        + codes.astype(jnp.int32)
    )
    return jnp.take(flat, idx, mode="clip")


def ints_to_f32(vals):
    """Narrow int field column -> f32, on device.  Exact (and therefore
    byte-identical to the host f64 -> f32 cast) because every i8/i16
    value is representable in f32."""
    return vals.astype(jnp.float32)


def decode_chunk(chunk: dict) -> dict:
    """The device-side decode stage: encoded chunk pytree -> the
    canonical chunk the plan kernels consume.

    Runs as the FIRST stage inside the fused per-chunk program
    (measure_exec._build_kernel wraps the kernel body with it; the fused
    executor applies it to the whole stacked ``[C, nrows]`` batch before
    its lax.scan), so decode work fuses into the one dispatch per
    part-batch instead of running as host numpy in the gather stage.

    Encoded chunks carry (pad/ship stage, measure_exec._device_chunk):

    - ``tags_enc``  narrow local dict codes per tag column
    - ``tags_lut``  [S, L] local->global LUT per tag column
    - ``src_ord``   per-row source ordinal (shared by all tag columns)
    - ``fields_enc``  narrow exact-int field columns

    Chunks without those keys (``BYDB_DEVICE_DECODE=0``) pass through
    unchanged, which is what makes the A/B flag a pure ship-form flip.
    """
    if "tags_enc" not in chunk and "fields_enc" not in chunk:
        return chunk
    out = {
        k: v
        for k, v in chunk.items()
        if k not in ("tags_enc", "tags_lut", "src_ord", "fields_enc")
    }
    tags_code = dict(out.get("tags_code", {}))
    for t, codes in chunk.get("tags_enc", {}).items():
        tags_code[t] = dict_remap(
            _maybe_pallas_widen(codes), chunk["tags_lut"][t], chunk["src_ord"]
        )
    out["tags_code"] = tags_code
    fields = dict(out.get("fields", {}))
    for f, vals in chunk.get("fields_enc", {}).items():
        fields[f] = ints_to_f32(_maybe_pallas_widen(vals))
    out["fields"] = fields
    return out


def _maybe_pallas_widen(vals):
    """Route the hot i8/i16 widen through the Pallas decode kernel on
    TPU (ops/pallas_kernels.widen_narrow; bench r03 proved ~89 Gpoints/s
    viability for this shape class); plain jnp elsewhere — the CPU
    fallback the tests pin parity against."""
    import jax

    if jax.default_backend() != "tpu" or vals.ndim != 1:
        return vals
    if vals.dtype not in (jnp.int8, jnp.int16):
        return vals
    from banyandb_tpu.ops import pallas_kernels

    if vals.shape[0] % pallas_kernels.TILE != 0:
        return vals
    return pallas_kernels.widen_narrow(vals)
