"""Device-friendly columnar block model.

The reference stores measure data as per-series columnar blocks capped at
8192 rows / 2 MiB (banyand/measure/measure.go:41-46) and scans them row by
row in Go.  Here a *batch* of blocks is a set of padded dense arrays with a
validity mask — the shape every scan/filter/aggregate kernel consumes.

Rows are padded to bucketed sizes (powers of two up to MAX_ROWS) so XLA sees
a small, finite set of shapes and compiles each pipeline once per bucket.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

# The reference caps blocks at 8192 rows (banyand/measure/measure.go:46).
MAX_ROWS = 8192
_BUCKETS = tuple(2**i for i in range(6, 14))  # 64 .. 8192


def pad_rows_bucket(n: int) -> int:
    """Smallest shape bucket >= n. Keeps the set of compiled shapes finite."""
    for b in _BUCKETS:
        if n <= b:
            return b
    raise ValueError(f"row count {n} exceeds MAX_ROWS={MAX_ROWS}")


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ColumnBatch:
    """A flattened batch of rows drawn from one or more storage blocks.

    All arrays share the leading dimension N (padded row count).

    - ``ts``: int32 timestamp offsets from ``epoch_millis`` (host-side int64).
      A segment spans at most a day, so millisecond offsets fit int32; this
      keeps the device hot path free of int64 emulation.
    - ``series``: int32 *local* series ordinals (dense ids assigned at batch
      build time; the host keeps the ordinal -> seriesID int64 mapping).
    - ``valid``: bool row-validity mask (padding and filtered rows are 0).
    - ``fields``: float32 measure field columns (int fields are cast; exact
      int aggregation is handled by the i64 host fallback when requested).
    - ``tags``: int32 dictionary codes per tag column.
    - ``version``: int32 write-version offsets for dedup-by-version.
    """

    ts: jax.Array
    series: jax.Array
    valid: jax.Array
    fields: Mapping[str, jax.Array]
    tags: Mapping[str, jax.Array]
    version: jax.Array

    @property
    def nrows(self) -> int:
        return self.ts.shape[0]

    @staticmethod
    def build(
        *,
        ts_millis: np.ndarray,
        epoch_millis: int,
        series_ordinal: np.ndarray,
        fields: Mapping[str, np.ndarray],
        tag_codes: Mapping[str, np.ndarray],
        version: np.ndarray | None = None,
    ) -> "ColumnBatch":
        """Pack host numpy columns into a padded device batch."""
        n = int(ts_millis.shape[0])
        nb = pad_rows_bucket(max(n, 1))
        if n:
            off_lo = int(ts_millis.min()) - epoch_millis
            off_hi = int(ts_millis.max()) - epoch_millis
            if off_lo < -(2**31) or off_hi >= 2**31:
                raise ValueError(
                    f"timestamp offsets [{off_lo}, {off_hi}] exceed int32; "
                    "epoch_millis must come from the enclosing segment"
                )

        def pad(a: np.ndarray, dtype) -> jax.Array:
            out = np.zeros((nb,), dtype=dtype)
            out[:n] = a.astype(dtype, copy=False)
            return jnp.asarray(out)

        valid = np.zeros((nb,), dtype=bool)
        valid[:n] = True
        if version is None:
            version = np.zeros((n,), dtype=np.int32)
        elif n:
            # Dedup only needs relative version order; rebase epoch-style
            # int64 versions to offsets so they survive the int32 cast.
            version = np.asarray(version, dtype=np.int64)
            version = version - int(version.min())
            if int(version.max()) >= 2**31:
                raise ValueError("version spread exceeds int32 offsets")
        return ColumnBatch(
            ts=pad(ts_millis - epoch_millis, np.int32),
            series=pad(series_ordinal, np.int32),
            valid=jnp.asarray(valid),
            fields={k: pad(v, np.float32) for k, v in fields.items()},
            tags={k: pad(v, np.int32) for k, v in tag_codes.items()},
            version=pad(version, np.int32),
        )
