"""Top-N over group aggregates.

Replaces the reference's Go heap flow (pkg/flow/streaming/topn_heap.go and
the query-side re-rank in banyand/measure/topn_post_processor.go) with a
single lax.top_k over the dense per-group aggregate vector.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_SENTINEL = jnp.finfo(jnp.float32).max


def topk_groups(
    metric: jax.Array,
    nonempty: jax.Array,
    n: int,
    *,
    descending: bool = True,
) -> tuple[jax.Array, jax.Array]:
    """(values, group_indices) of the top-n (or bottom-n) non-empty groups.

    Empty groups sort last in either direction; callers drop entries whose
    returned value is +/-inf-sentinel by checking nonempty[indices].
    """
    if descending:
        m = jnp.where(nonempty, metric, -_SENTINEL)
        vals, idx = jax.lax.top_k(m, n)
    else:
        m = jnp.where(nonempty, -metric, -_SENTINEL)
        vals, idx = jax.lax.top_k(m, n)
        vals = -vals
    return vals, idx
