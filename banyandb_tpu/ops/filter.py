"""Predicate-mask kernels.

The reference evaluates tag predicates row-by-row in Go operators
(pkg/query/vectorized/measure/*.go filter operators).  Here predicates are
dense vector compares producing bool masks that XLA fuses with the
downstream aggregation — a filtered scan is one kernel, not an operator
chain.

String predicates never see raw bytes on device: equality/IN lower to
dictionary-code compares (the host resolves the literal to its code, or to
an always-false mask when absent), mirroring the reference's
dictionary-as-exact-filter trick (docs/concept/storage-and-format.md§7.3).
"""

from __future__ import annotations

import jax.numpy as jnp

_OPS = {
    "eq": lambda c, v: c == v,
    "ne": lambda c, v: c != v,
    "lt": lambda c, v: c < v,
    "le": lambda c, v: c <= v,
    "gt": lambda c, v: c > v,
    "ge": lambda c, v: c >= v,
}


def cmp_mask(column, op: str, value):
    """Elementwise compare mask. `op` in eq/ne/lt/le/gt/ge."""
    return _OPS[op](column, value)


def in_set_mask(column, values):
    """mask[i] = column[i] in values. `values` is a small static-size array;
    lowered to a broadcast compare + any-reduce (VPU-friendly)."""
    vals = jnp.asarray(values)
    return jnp.any(column[..., None] == vals, axis=-1)


def time_range_mask(ts, lo, hi):
    """Half-open [lo, hi) time-range mask over int32 ts offsets."""
    return (ts >= lo) & (ts < hi)


def mask_and(*masks):
    out = masks[0]
    for m in masks[1:]:
        out = out & m
    return out


def mask_or(*masks):
    out = masks[0]
    for m in masks[1:]:
        out = out | m
    return out


def mask_not(mask):
    return ~mask
