"""Version dedup inside a parallel scan.

The reference dedups measure rows by keeping the max write-version per
(seriesID, timestamp) during its sequential merge-sort scan
(banyand/measure columnar read path).  A sequential scan does not map to
the VPU, so here dedup is a multi-operand sort: order rows by
(series, ts, -version) and invalidate every row that shares (series, ts)
with its sorted predecessor — the survivor is exactly the max-version row.
All operands stay int32.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def latest_by_version(
    series: jax.Array,
    ts: jax.Array,
    version: jax.Array,
    valid: jax.Array,
) -> jax.Array:
    """-> refined bool validity mask keeping one max-version row per key."""
    n = series.shape[-1]
    # Invalid rows sort last (series=INT32_MAX) and stay invalid.
    big = jnp.int32(2147483647)
    s = jnp.where(valid, series, big)
    t = jnp.where(valid, ts, big)
    negv = jnp.where(valid, -version, big)
    idx = jax.lax.broadcasted_iota(jnp.int32, (n,), 0)
    s_s, t_s, _, idx_s = jax.lax.sort((s, t, negv, idx), num_keys=3)
    first = jnp.concatenate(
        [
            jnp.ones((1,), dtype=bool),
            (s_s[1:] != s_s[:-1]) | (t_s[1:] != t_s[:-1]),
        ]
    )
    keep_sorted = first
    keep = jnp.zeros((n,), dtype=bool).at[idx_s].set(keep_sorted)
    return keep & valid
