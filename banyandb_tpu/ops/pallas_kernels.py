"""Pallas TPU kernels for the scan hot loop.

The XLA path (ops.group_reduce) already fuses mask+reduce well; these
hand-written kernels exist for the cases where explicit control of VMEM
tiling wins: streaming HBM-resident row tiles through MXU one-hot
contractions computing the filtered per-group sums/count for ALL fields
at once without materializing the one-hot operand in HBM.  Grid =
(group tiles, row tiles), rows innermost: for each group tile the full
row stream is revisited (so G > GTILE costs one extra HBM pass per
additional group tile — the picker bounds this), and the accumulators
live in output blocks indexed by the group tile only (revisited by
every row step — TPU grids execute sequentially, so read-modify-write
accumulation across steps is sound).

Precision contract (shared with ops.group_reduce): each row tile's
partial is an f32 MXU contraction over TILE=2048 rows; tile partials are
combined with Kahan-compensated f32 accumulation across grid steps, so
the cross-tile error stays O(eps) independent of row count (instead of
O(n_tiles * eps) for naive f32 accumulation).

Runs in interpret mode on CPU for correctness tests; compiled mode on
TPU (pallas_guide.md patterns: grid accumulation, @pl.when init).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE = 2048


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# Group-dimension tile: bounds the [GTILE, TILE] one-hot operand
# (2048x2048 f32 = 16 MiB) plus the [F, GTILE] accumulator blocks in
# VMEM, so group counts in the tens of thousands compile instead of
# exhausting VMEM.  16 MiB leaves little headroom beyond a few fields on
# a 128 MiB-VMEM v5e — verified to compile at G=40k; shrink GTILE before
# growing anything else here.
GTILE = 2048


def _fused_kernel(
    codes_ref,
    pred_ref,
    vals_ref,
    valid_ref,
    count_ref,
    sum_ref,
    ccomp_ref,
    scomp_ref,
):
    # Grid is (group tiles, row tiles) with the row dimension innermost:
    # for a fixed group tile j the kernel streams every row tile i,
    # accumulating into the same output blocks (TPU grids run
    # sequentially, so read-modify-write across i is sound).
    j = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        count_ref[:] = jnp.zeros_like(count_ref)
        sum_ref[:] = jnp.zeros_like(sum_ref)
        ccomp_ref[:] = jnp.zeros_like(ccomp_ref)
        scomp_ref[:] = jnp.zeros_like(scomp_ref)

    codes = codes_ref[:]  # [1, TILE] int32 group codes
    pred = pred_ref[:]  # [1, TILE] int32 0/1 predicate flags
    vals = vals_ref[:]  # [F, TILE] f32
    valid = valid_ref[:]  # [1, TILE] f32 (1.0 valid)

    # predicate arrives as a per-row 0/1 flag; multiply is the AND
    mask = valid * pred.astype(jnp.float32)  # [1, TILE]

    # Mosaic cannot lower 1-D integer indexing (it becomes an unsupported
    # gather), so the one-hot is built transposed — [GTILE, TILE] with
    # row r equal to group j*GTILE + r — and contracted along TILE via
    # dot_general with a transposed RHS, which maps straight onto the MXU.
    g = count_ref.shape[1]
    gids = j * g + jax.lax.broadcasted_iota(
        jnp.int32, (g, codes.shape[1]), 0
    )
    onehot_t = (gids == codes).astype(jnp.float32)  # [GTILE, TILE]
    dn = (((1,), (1,)), ((), ()))
    cnt_p = jax.lax.dot_general(
        mask, onehot_t, dn, preferred_element_type=jnp.float32
    )  # [1, GTILE]
    sum_p = jax.lax.dot_general(
        vals * mask, onehot_t, dn, preferred_element_type=jnp.float32
    )  # [F, GTILE] — one contraction, all fields

    # Kahan-compensated add of this tile's partials into the accumulators.
    y = cnt_p - ccomp_ref[:]
    t = count_ref[:] + y
    ccomp_ref[:] = (t - count_ref[:]) - y
    count_ref[:] = t

    y = sum_p - scomp_ref[:]
    t = sum_ref[:] + y
    scomp_ref[:] = (t - sum_ref[:]) - y
    sum_ref[:] = t


@functools.partial(jax.jit, static_argnames=("num_groups", "interpret"))
def fused_group_multi(
    codes: jax.Array,
    pred_mask: jax.Array,
    values: jax.Array,
    valid: jax.Array,
    *,
    num_groups: int,
    interpret: bool = False,
):
    """Filtered per-group (count, per-field sums) in one pass.

    codes: int32 [N] group codes; pred_mask: bool [N] predicate;
    values: f32 [F, N] stacked field columns; valid: bool [N].
    N must be a TILE multiple. -> (count f32 [G], sums f32 [F, G])
    """
    n = codes.shape[0]
    assert n % TILE == 0, f"N={n} must be a multiple of {TILE}"
    nf = values.shape[0]
    if n == 0:
        # a zero-size grid dimension never invokes the kernel, so the
        # @pl.when init would never run and the outputs would be
        # whatever the allocator held — return real zeros instead
        return (
            jnp.zeros(num_groups, jnp.float32),
            jnp.zeros((nf, num_groups), jnp.float32),
        )
    if nf == 0:
        # zero-dim blocks don't lower; run a dummy field and drop it
        count, _ = fused_group_multi(
            codes,
            pred_mask,
            jnp.zeros((1, n), jnp.float32),
            valid,
            num_groups=num_groups,
            interpret=interpret,
        )
        return count, jnp.zeros((0, num_groups), jnp.float32)
    # Pad the group axis to a GTILE multiple; padded groups match no row
    # code (codes are < num_groups) and are sliced off below.
    gt = min(GTILE, _round_up(num_groups, 128))
    gpad = _round_up(num_groups, gt)
    grid = (gpad // gt, n // TILE)

    codes2 = codes.reshape(1, n)
    pred2 = pred_mask.astype(jnp.int32).reshape(1, n)
    valid2 = valid.astype(jnp.float32).reshape(1, n)

    row_spec = pl.BlockSpec((1, TILE), lambda j, i: (0, i))
    val_spec = pl.BlockSpec((nf, TILE), lambda j, i: (0, i))
    cacc_spec = pl.BlockSpec((1, gt), lambda j, i: (0, j))
    sacc_spec = pl.BlockSpec((nf, gt), lambda j, i: (0, j))

    count, total, ccomp, scomp = pl.pallas_call(
        _fused_kernel,
        grid=grid,
        in_specs=[row_spec, row_spec, val_spec, row_spec],
        out_specs=(cacc_spec, sacc_spec, cacc_spec, sacc_spec),
        out_shape=(
            jax.ShapeDtypeStruct((1, gpad), jnp.float32),
            jax.ShapeDtypeStruct((nf, gpad), jnp.float32),
            jax.ShapeDtypeStruct((1, gpad), jnp.float32),
            jax.ShapeDtypeStruct((nf, gpad), jnp.float32),
        ),
        interpret=interpret,
    )(codes2, pred2, values, valid2)
    # Fold the residual compensation back in (classic Kahan final step;
    # the compensation holds the negated running error).
    return (
        (count - ccomp)[0, :num_groups],
        (total - scomp)[:, :num_groups],
    )


# -- device-side decode kernels (ROADMAP item 3) -----------------------------
# The compressed-ship path (storage/encoded.py + ops/decode.py) lands
# narrow i8/i16 columns in HBM; these kernels widen them at VMEM tile
# granularity.  bench r03 measured the Pallas decode shape class at
# ~89 Gpoints/s — the jnp fallback (ops.decode.widen_codes / a plain
# jnp.cumsum) is what runs on CPU and is what the parity tests pin.


def _widen_kernel(x_ref, out_ref):
    out_ref[:] = x_ref[:].astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("interpret",))
def widen_narrow(x: jax.Array, *, interpret: bool = False) -> jax.Array:
    """Narrow i8/i16 column [N] -> i32, tiled through VMEM.

    N must be a TILE multiple (the pad/ship stage's power-of-two row
    buckets guarantee this above TILE); callers with other shapes use
    the jnp fallback."""
    n = x.shape[0]
    assert n % TILE == 0, f"N={n} must be a multiple of {TILE}"
    x2 = x.reshape(1, n)
    out = pl.pallas_call(
        _widen_kernel,
        grid=(n // TILE,),
        in_specs=[pl.BlockSpec((1, TILE), lambda i: (0, i))],
        out_specs=pl.BlockSpec((1, TILE), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int32),
        interpret=interpret,
    )(x2)
    return out[0]


def _prefix_sum_kernel(x_ref, out_ref, carry_ref):
    # Sequential TPU grid: tile i adds the running total of tiles < i
    # (carried in a [1, 1] output block every step revisits) to its own
    # in-tile cumsum — an exact integer prefix sum across the column.
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        carry_ref[:] = jnp.zeros_like(carry_ref)

    c = jnp.cumsum(x_ref[:].astype(jnp.int32), axis=-1) + carry_ref[0, 0]
    out_ref[:] = c
    carry_ref[0, 0] = c[0, -1]


@functools.partial(jax.jit, static_argnames=("interpret",))
def prefix_sum_narrow(x: jax.Array, *, interpret: bool = False) -> jax.Array:
    """Inclusive i32 prefix sum of a narrow delta column [N] (N a TILE
    multiple) — the delta-decode hot loop: with x[0] = first and
    x[1:] = deltas, out IS the decoded series (ops.decode.delta_decode's
    fixed-width contract).  Exact integer math, so the jnp.cumsum
    fallback is bit-identical."""
    n = x.shape[0]
    assert n % TILE == 0, f"N={n} must be a multiple of {TILE}"
    x2 = x.reshape(1, n)
    out, _carry = pl.pallas_call(
        _prefix_sum_kernel,
        grid=(n // TILE,),
        in_specs=[pl.BlockSpec((1, TILE), lambda i: (0, i))],
        out_specs=(
            pl.BlockSpec((1, TILE), lambda i: (0, i)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((1, n), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        interpret=interpret,
    )(x2)
    return out[0]


@functools.partial(jax.jit, static_argnames=("num_groups", "interpret"))
def fused_group_sum(
    codes: jax.Array,
    pred_mask: jax.Array,
    values: jax.Array,
    valid: jax.Array,
    *,
    num_groups: int,
    interpret: bool = False,
):
    """Single-field convenience wrapper around fused_group_multi.

    codes: int32 [N]; pred_mask: bool [N]; values: f32 [N]; valid: bool
    [N]. -> (count f32 [G], sum f32 [G])
    """
    count, sums = fused_group_multi(
        codes,
        pred_mask,
        values.reshape(1, -1),
        valid,
        num_groups=num_groups,
        interpret=interpret,
    )
    return count, sums[0]
