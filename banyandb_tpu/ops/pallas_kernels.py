"""Pallas TPU kernels for the scan hot loop.

The XLA path (ops.group_reduce) already fuses mask+reduce well; these
hand-written kernels exist for the cases where explicit control of VMEM
tiling wins: one pass over HBM-resident row tiles computing the
filtered per-group sum/count without materializing the one-hot operand
in HBM.  Grid = row tiles; the [G] accumulators live in the output block
(revisited by every grid step — TPU grids execute sequentially, so
read-modify-write accumulation across steps is sound).

Runs in interpret mode on CPU for correctness tests; compiled mode on
TPU (pallas_guide.md patterns: grid accumulation, @pl.when init).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE = 2048


def _fused_kernel(codes_ref, pred_ref, vals_ref, valid_ref, count_ref, sum_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        count_ref[:] = jnp.zeros_like(count_ref)
        sum_ref[:] = jnp.zeros_like(sum_ref)

    codes = codes_ref[:]  # [1, TILE] int32 group codes
    pred = pred_ref[:]  # [1, TILE] int32 0/1 predicate flags
    vals = vals_ref[:]  # [1, TILE] f32
    valid = valid_ref[:]  # [1, TILE] f32 (1.0 valid)

    # predicate arrives as a per-row 0/1 flag; multiply is the AND
    mask = valid * pred.astype(jnp.float32)

    g = count_ref.shape[1]
    groups = jax.lax.broadcasted_iota(jnp.int32, (1, g), 1)
    onehot = (codes[0, :, None] == groups[0, None, :]).astype(jnp.float32)
    count_ref[:] += (mask[0, :] @ onehot)[None, :]
    sum_ref[:] += ((vals[0, :] * mask[0, :]) @ onehot)[None, :]


@functools.partial(jax.jit, static_argnames=("num_groups", "interpret"))
def fused_group_sum(
    codes: jax.Array,
    pred_mask: jax.Array,
    values: jax.Array,
    valid: jax.Array,
    *,
    num_groups: int,
    interpret: bool = False,
):
    """Filtered per-group (count, sum) in one pass.

    codes: int32 [N] group codes; pred_mask: bool [N] predicate;
    values: f32 [N]; valid: bool [N]. N must be a TILE multiple.
    -> (count f32 [G], sum f32 [G])
    """
    n = codes.shape[0]
    assert n % TILE == 0, f"N={n} must be a multiple of {TILE}"
    grid = (n // TILE,)

    codes2 = codes.reshape(1, n)
    pred2 = pred_mask.astype(jnp.int32).reshape(1, n)
    vals2 = values.reshape(1, n)
    valid2 = valid.astype(jnp.float32).reshape(1, n)

    row_spec = pl.BlockSpec((1, TILE), lambda i: (0, i))
    acc_spec = pl.BlockSpec((1, num_groups), lambda i: (0, 0))

    count, total = pl.pallas_call(
        _fused_kernel,
        grid=grid,
        in_specs=[row_spec, row_spec, row_spec, row_spec],
        out_specs=(acc_spec, acc_spec),
        out_shape=(
            jax.ShapeDtypeStruct((1, num_groups), jnp.float32),
            jax.ShapeDtypeStruct((1, num_groups), jnp.float32),
        ),
        interpret=interpret,
    )(codes2, pred2, vals2, valid2)
    return count[0], total[0]
