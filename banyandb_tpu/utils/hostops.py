"""Host-side (NumPy) shared algorithms used by both the storage and query
layers."""

from __future__ import annotations

import numpy as np


def dedup_max_version(
    series: np.ndarray, ts: np.ndarray, version: np.ndarray
) -> np.ndarray:
    """-> sorted row indices keeping the max-version row per (series, ts).

    The write-version contract of the measure model (reference dedups at
    merge-sort time; we dedup here at merge and at query gather).  lexsort
    is ascending, so -version puts each key run's winner first.
    """
    if series.size == 0:
        return np.zeros(0, dtype=np.int64)
    order = np.lexsort((-version, ts, series))
    s_s, t_s = series[order], ts[order]
    first = np.empty(len(order), dtype=bool)
    first[0] = True
    first[1:] = (s_s[1:] != s_s[:-1]) | (t_s[1:] != t_s[:-1])
    return np.sort(order[first])
