"""ctypes bindings to cpp/libbydb_native.so (the native hot-loop module).

Loaded lazily and optional: every caller has a NumPy fallback, so the
framework runs pure-Python when the .so hasn't been built (`make -C cpp`).
"""

from __future__ import annotations

import ctypes
from pathlib import Path
from typing import Optional

import numpy as np

_SO_PATHS = [
    Path(__file__).resolve().parents[2] / "cpp" / "libbydb_native.so",
    Path("libbydb_native.so"),
]

_lib = None
_tried = False


def lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    for p in _SO_PATHS:
        try:
            L = ctypes.CDLL(str(p))
        except OSError:
            continue
        L.bydb_delta_encode.restype = ctypes.c_int
        L.bydb_delta_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int),
        ]
        L.bydb_delta_decode.restype = ctypes.c_int
        L.bydb_delta_decode.argtypes = [
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int, ctypes.c_void_p,
        ]
        L.bydb_zigzag_varint_encode.restype = ctypes.c_int64
        L.bydb_zigzag_varint_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        L.bydb_zigzag_varint_decode.restype = ctypes.c_int64
        L.bydb_zigzag_varint_decode.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ]
        L.bydb_crc32.restype = ctypes.c_uint32
        L.bydb_crc32.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint32]
        _lib = L
        break
    return _lib


def delta_encode(values: np.ndarray) -> Optional[tuple[bytes, int]]:
    """-> (packed deltas, width) or None when the native lib is absent."""
    L = lib()
    if L is None:
        return None
    v = np.ascontiguousarray(values, dtype=np.int64)
    out = np.empty(max(v.size - 1, 1) * 8, dtype=np.uint8)
    out_len = ctypes.c_int64()
    width = ctypes.c_int()
    rc = L.bydb_delta_encode(
        v.ctypes.data, v.size, out.ctypes.data,
        ctypes.byref(out_len), ctypes.byref(width),
    )
    if rc != 0:
        return None
    return out[: out_len.value].tobytes(), width.value


def delta_decode(first: int, payload: bytes, count: int, width: int) -> Optional[np.ndarray]:
    L = lib()
    if L is None:
        return None
    # Validate before touching C: corrupt blobs must become Python errors,
    # not out-of-bounds reads.
    if width not in (1, 2, 4, 8):
        raise ValueError(f"bad delta width {width}")
    if count < 1:
        raise ValueError(f"bad row count {count}")
    if len(payload) != (count - 1) * width:
        raise ValueError(
            f"delta payload {len(payload)}B != (count-1)*width {(count - 1) * width}B"
        )
    buf = np.frombuffer(payload, dtype=np.uint8)
    out = np.empty(count, dtype=np.int64)
    L.bydb_delta_decode(
        first, buf.ctypes.data if buf.size else None, count, width, out.ctypes.data
    )
    return out


def zigzag_varint_encode(values: np.ndarray) -> Optional[bytes]:
    L = lib()
    if L is None:
        return None
    v = np.ascontiguousarray(values, dtype=np.int64)
    out = np.empty(v.size * 10 + 1, dtype=np.uint8)
    n = L.bydb_zigzag_varint_encode(v.ctypes.data, v.size, out.ctypes.data)
    return out[:n].tobytes()


def zigzag_varint_decode(payload: bytes, count: int) -> Optional[np.ndarray]:
    L = lib()
    if L is None:
        return None
    buf = np.frombuffer(payload, dtype=np.uint8)
    out = np.empty(count, dtype=np.int64)
    got = L.bydb_zigzag_varint_decode(
        buf.ctypes.data if buf.size else None, buf.size, out.ctypes.data, count
    )
    return out[:got]


def crc32(data: bytes, seed: int = 0) -> Optional[int]:
    L = lib()
    if L is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8)
    return int(L.bydb_crc32(buf.ctypes.data if buf.size else None, buf.size, seed))
