"""Child-process registry for leak tracking (bdsan process hygiene).

Owners of child processes (the shard-worker pool, cluster/workers.py)
register every spawn and unregister on reap; the sanitize LeakTracker
(sanitize/leaks.py) reads the registry to assert that no test leaves a
worker process running or unreaped — the process analog of the
gleak-style thread-parity check.

A registered pid counts as leaked whether or not the process still
runs: an exited-but-unregistered child is a reap the owner forgot
(close() was never called), which is exactly what the check exists to
catch.

Lives in utils (L0) so fabric-layer owners can report downward while
the L6 sanitizer reads without an upward import edge.
"""

from __future__ import annotations

import threading

_LOCK = threading.Lock()
_PROCS: dict[int, str] = {}  # pid -> label


def register(pid: int, label: str) -> None:
    with _LOCK:
        _PROCS[pid] = label


def unregister(pid: int) -> None:
    with _LOCK:
        _PROCS.pop(pid, None)


def snapshot() -> frozenset:
    """Registered pids right now (leak-check baseline)."""
    with _LOCK:
        return frozenset(_PROCS)


def live(exclude: frozenset = frozenset()) -> list:
    """(pid, label) for registered processes outside ``exclude`` —
    still running OR still registered (spawned but never reaped — a
    zombie the owner forgot to close())."""
    with _LOCK:
        return [
            (pid, label) for pid, label in _PROCS.items() if pid not in exclude
        ]
