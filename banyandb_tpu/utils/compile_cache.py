"""Persistent XLA compilation cache wiring (+ hit/miss counters).

The cold path pays one XLA compile per plan signature per PROCESS; on a
restart every dashboard query recompiles kernels whose HLO has not
changed.  Pointing ``jax_compilation_cache_dir`` at a directory that
outlives the process (default: ``<data-root>/compile-cache``) makes plan
kernels compile once per machine — the Tailwind-style "plans stay
resident across restarts" property, at the XLA executable layer.

Resolution order for the directory, most specific wins:

    explicit CLI flag (``--compile-cache-dir``, via enable_at)
      >  BYDB_COMPILE_CACHE_DIR env var (``off``/``0`` disables)
      >  the caller's computed default (``enable(default_dir)``)

Wiring is process-global and first-wins (the cache key hashes the whole
HLO, so sharing one directory between roots is safe); ``stats()`` feeds
the /metrics surface and the bench artifact.  Hit/miss counts come from
jax's own monitoring events (``/jax/compilation_cache/cache_hits`` and
``.../cache_misses``) so they reflect what XLA actually did, not what we
hoped.
"""

from __future__ import annotations

import os
import threading

from banyandb_tpu.utils.envflag import env_str

_DISABLE_VALUES = ("0", "off", "no", "none", "false", "disabled")

_lock = threading.Lock()
_state = {
    "enabled": False,
    "dir": None,
    "hits": 0,
    "misses": 0,
    "listener": False,
    "error": None,
}


def _install_listener() -> None:
    """Count persistent-cache hits/misses via jax monitoring events.

    Private-API dependent (jax._src.monitoring); counters degrade to 0
    rather than break wiring if the surface moves."""
    if _state["listener"]:
        return
    try:
        from jax._src import monitoring

        def _on_event(event: str, **kw) -> None:
            # int += under the GIL; counters are best-effort telemetry
            if event.endswith("/cache_hits"):
                _state["hits"] += 1
            elif event.endswith("/cache_misses"):
                _state["misses"] += 1

        monitoring.register_event_listener(_on_event)
        _state["listener"] = True
    except Exception as e:  # noqa: BLE001 — counters are optional
        _state["error"] = f"listener: {type(e).__name__}: {e}"


def _wire(target: str) -> str | None:
    with _lock:
        if _state["enabled"]:
            return _state["dir"]  # first wiring wins (process-global)
        import jax

        try:
            os.makedirs(target, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", target)
            # default thresholds skip sub-second compiles — exactly the
            # population a dashboard's plan kernels live in
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception as e:  # noqa: BLE001 — cache is an optimization
            _state["error"] = f"{type(e).__name__}: {e}"
            return None
        _install_listener()
        _state["enabled"] = True
        _state["dir"] = target
        return target


def enable(default_dir=None) -> str | None:
    """Enable the persistent cache; env overrides the computed default.

    Returns the active directory, or None when disabled (env set to an
    off-value, or no directory resolvable).  Idempotent; later calls
    with a different directory keep the first wiring."""
    env = env_str("BYDB_COMPILE_CACHE_DIR")
    if env and env.strip().lower() in _DISABLE_VALUES:
        return None
    target = env or (str(default_dir) if default_dir else None)
    if not target:
        return None
    return _wire(target)


def enable_at(path) -> str | None:
    """Explicit-path form for CLI flags (flag already folded env/file
    precedence via config.py); off-values disable."""
    if str(path).strip().lower() in _DISABLE_VALUES:
        return None
    return _wire(str(path))


def active_dir() -> str | None:
    return _state["dir"]


def stats() -> dict:
    """Telemetry for /metrics and the bench artifact."""
    entries = 0
    d = _state["dir"]
    if _state["enabled"] and d and os.path.isdir(d):
        try:
            entries = sum(1 for _ in os.scandir(d))
        except OSError:
            entries = 0
    return {
        "enabled": _state["enabled"],
        "dir": d,
        "hits": _state["hits"],
        "misses": _state["misses"],
        "entries": entries,
        "error": _state["error"],
    }
