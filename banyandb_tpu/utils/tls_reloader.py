"""TLS certificate hot-reload for gRPC servers.

Analog of the reference's fsnotify-based reloader
(/root/reference/pkg/tls/reloader.go:55): rotated cert/key files take
effect WITHOUT restarting the server.  gRPC Python exposes exactly the
right hook — ``dynamic_ssl_server_credentials`` calls a configuration
fetcher on every TLS handshake — so the reloader only needs to re-read
the PEM files when their mtimes change (mtime polling instead of
fsnotify; the fetcher runs per-handshake, so a poll loop isn't even
needed).
"""

from __future__ import annotations

import threading
from pathlib import Path
from typing import Optional


class CertReloader:
    def __init__(self, cert_file: str | Path, key_file: str | Path):
        self.cert_file = Path(cert_file)
        self.key_file = Path(key_file)
        self._lock = threading.Lock()
        self._mtimes: tuple[float, float] = (-1.0, -1.0)
        self._pair: Optional[tuple[bytes, bytes]] = None
        self.reloads = 0  # observability: how many rotations served
        self._refresh()

    @staticmethod
    def _pair_valid(key: bytes, cert: bytes) -> bool:
        """True when the key actually matches the cert — a handshake
        mid-rotation (cert written, key not yet) must not adopt a
        mismatched pair."""
        import ssl
        import tempfile

        try:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            with tempfile.NamedTemporaryFile(suffix=".pem") as f:
                f.write(key)
                f.write(b"\n")
                f.write(cert)
                f.flush()
                ctx.load_cert_chain(f.name)
            return True
        except (ssl.SSLError, OSError, ValueError):
            return False

    def _refresh(self) -> None:
        """Re-read the PEMs when either file's mtime moved.  A rotation
        in progress (cert written, key not yet — a MISMATCHED pair)
        keeps serving the last good pair; the matching half lands on a
        later handshake once both files rotated."""
        try:
            mt = (
                self.cert_file.stat().st_mtime,
                self.key_file.stat().st_mtime,
            )
        except OSError:
            return
        with self._lock:
            if mt == self._mtimes and self._pair is not None:
                return
            try:
                pair = (self.key_file.read_bytes(), self.cert_file.read_bytes())
            except OSError:
                return
            if pair != self._pair and not self._pair_valid(*pair):
                return  # mid-rotation mismatch: keep the last good pair
            if self._pair is not None and pair != self._pair:
                self.reloads += 1
            self._mtimes = mt
            self._pair = pair

    def current_pair(self) -> tuple[bytes, bytes]:
        self._refresh()
        with self._lock:
            if self._pair is None:
                raise FileNotFoundError(
                    f"TLS material unreadable: {self.cert_file}, {self.key_file}"
                )
            return self._pair

    def server_credentials(self):
        """gRPC server credentials that pick up rotated files per
        handshake (no restart, no rebind)."""
        import grpc

        def fetch():
            key, cert = self.current_pair()
            return grpc.ssl_server_certificate_configuration([(key, cert)])

        return grpc.dynamic_ssl_server_credentials(
            fetch(), lambda: fetch(), require_client_authentication=False
        )
