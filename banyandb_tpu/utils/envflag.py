"""One definition of boolean env-flag parsing.

Every BYDB_* on/off switch accepts the same spellings; keeping the
accepted set in one place stops the copies from drifting (the fourth
hand-rolled ``_ON`` tuple is where "y" silently works in one module and
not the next).
"""

from __future__ import annotations

import os

_ON = ("1", "on", "yes", "true")


def env_flag(name: str, default: bool = False) -> bool:
    """Boolean env flag: unset -> ``default``; set -> value must spell
    truth (``1/on/yes/true``, case/space-insensitive) to be True."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() in _ON
