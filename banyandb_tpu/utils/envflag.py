"""One definition of BYDB_* env-flag parsing.

Every BYDB_* on/off switch accepts the same spellings; keeping the
accepted set in one place stops the copies from drifting (the fourth
hand-rolled ``_ON`` tuple is where "y" silently works in one module and
not the next).  Numeric flags parse here too, with one shared
malformed-value policy: fall back to the default instead of crashing a
server at boot over a typo'd tuning knob.
"""

from __future__ import annotations

import os

_ON = ("1", "on", "yes", "true")


def env_flag(name: str, default: bool = False) -> bool:
    """Boolean env flag: unset -> ``default``; set -> value must spell
    truth (``1/on/yes/true``, case/space-insensitive) to be True."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() in _ON


def env_float(name: str, default: float) -> float:
    """Float env flag; unset or malformed -> ``default``."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw.strip())
    except ValueError:
        return default


def env_int(name: str, default: int) -> int:
    """Integer env flag; unset or malformed -> ``default``."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw.strip())
    except ValueError:
        return default


def env_str(name: str, default: str = "") -> str:
    """String env flag; unset -> ``default`` (set-but-empty is kept:
    an operator exporting ``BYDB_X=`` explicitly chose empty)."""
    raw = os.environ.get(name)
    return default if raw is None else raw


# The BYDB_* flag registry: every flag the package reads, one line
# each.  bdwire's wire-envflag analyzer cross-checks this table against
# the live env_* call sites AND docs/flags.md, both directions — a flag
# read without an entry here fails --check, and so does a stale entry.
FLAGS: dict[str, str] = {
    "BYDB_AUTOREG": "bool: streamagg auto-registration from query shapes",
    "BYDB_AUTOREG_BACKOFF_S": "float: autoreg re-proposal backoff",
    "BYDB_AUTOREG_INTERVAL_S": "float: autoreg scan interval",
    "BYDB_AUTOREG_MAX_SIGNATURES": "int: autoreg signature cap",
    "BYDB_AUTOREG_MAX_STATE_MB": "int: autoreg total state budget",
    "BYDB_AUTOREG_MIN_HITS": "int: query-shape hits before autoreg",
    "BYDB_COMPILE_CACHE_DIR": "str: persistent XLA compile-cache dir",
    "BYDB_CONFIG": "str: server config file path (CLI --config wins)",
    "BYDB_DEVICE_CACHE_BYTES": "int: device-resident block cache budget",
    "BYDB_DEVICE_DECODE": "bool: decode encoded blocks on-device",
    "BYDB_FAULTS": "str: fault-injection schedule spec (cluster/faults)",
    "BYDB_FUSED": "bool: fused scan->aggregate execution",
    "BYDB_FUSED_MAX_MB": "int: fused-exec working-set ceiling",
    "BYDB_MAX_PERSISTENT_GROUPS": "int: persistent group-by cardinality cap",
    "BYDB_PARTIALS_FRAME_V1": "bool: columnar v1 partials wire frame",
    "BYDB_PIPELINE": "bool: decode/compute pipelining",
    "BYDB_PLANNER": "bool: cost-based adaptive planner",
    "BYDB_PRECOMPILE": "bool: kernel precompile pass at startup",
    "BYDB_PREFETCH_DEPTH": "int: chunk-stream prefetch depth",
    "BYDB_QOS": "bool: multi-tenant QoS plane",
    "BYDB_QOS_MAX_QUEUE_S": "float: max admission-queue wait",
    "BYDB_QOS_QUERY_GLOBAL_MAX": "int: global concurrent-query cap",
    "BYDB_QOS_TENANTS": "str: per-tenant quota spec list",
    "BYDB_QOS_TENANT_SEP": "str: group-name -> tenant separator",
    "BYDB_QUERY_DEADLINE_S": "float: cluster query deadline budget",
    "BYDB_REPAIR_INTERVAL_S": "float: replica-repair round interval",
    "BYDB_SANITIZE": "bool: runtime sanitizers (bdsan)",
    "BYDB_SCAN_CHUNK": "int: measure scan chunk rows",
    "BYDB_SELF_MEASURE_INTERVAL_S": "float: self-observability interval",
    "BYDB_SELF_TRACE": "bool: mirror query span trees into _monitoring.self_query",
    "BYDB_SELF_TRACE_INTERVAL_S": "float: self-trace flush cadence",
    "BYDB_SELF_TRACE_MS": "float: self-trace sampling threshold (0 = all)",
    "BYDB_SELF_TRACE_QUEUE": "int: self-trace queue cap (full = shed)",
    "BYDB_SERVING_CACHE_BYTES": "int: serving-cache byte budget",
    "BYDB_SERVING_CACHE_CAP": "int: serving-cache entry cap",
    "BYDB_SLOWLOG_CAPACITY": "int: slow-query recorder ring size",
    "BYDB_SLOW_QUERY_MS": "float: slow-query threshold",
    "BYDB_STREAMAGG": "bool: streaming aggregation subsystem",
    "BYDB_STREAMAGG_AUTOLOAD": "bool: reload streamagg states at boot",
    "BYDB_STREAMAGG_MAX_WINDOWS": "int: streamagg window cap",
    "BYDB_STREAMAGG_WINDOW_MS": "int: streamagg default window width",
    "BYDB_TOPN_VERSION_ROWS": "int: topn version-table row cap",
    "BYDB_WORKERS": "int: shard worker process count (0 = in-process)",
    "BYDB_WORKER_FLUSH_S": "float: worker journal flush interval",
    "BYDB_WORKER_JOURNAL_MB": "int: worker journal size budget",
    "BYDB_ZONE_SKIP": "bool: zone-map block skipping",
}
