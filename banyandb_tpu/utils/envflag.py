"""One definition of BYDB_* env-flag parsing.

Every BYDB_* on/off switch accepts the same spellings; keeping the
accepted set in one place stops the copies from drifting (the fourth
hand-rolled ``_ON`` tuple is where "y" silently works in one module and
not the next).  Numeric flags parse here too, with one shared
malformed-value policy: fall back to the default instead of crashing a
server at boot over a typo'd tuning knob.
"""

from __future__ import annotations

import os

_ON = ("1", "on", "yes", "true")


def env_flag(name: str, default: bool = False) -> bool:
    """Boolean env flag: unset -> ``default``; set -> value must spell
    truth (``1/on/yes/true``, case/space-insensitive) to be True."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() in _ON


def env_float(name: str, default: float) -> float:
    """Float env flag; unset or malformed -> ``default``."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw.strip())
    except ValueError:
        return default


def env_int(name: str, default: int) -> int:
    """Integer env flag; unset or malformed -> ``default``."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw.strip())
    except ValueError:
        return default
