"""Host-side column codecs (NumPy-vectorized).

The reference encodes int64 columns with const/delta/delta-of-delta + zigzag
varint picked per block (pkg/encoding/int_list.go:27,33-74) and dictionary-
encodes low-cardinality byte columns (pkg/encoding/dictionary.go).  Varint
is a sequential decode — hostile both to NumPy and to the TPU — so this
format keeps the same *compression ideas* but with fixed-width outputs:

    int64 column -> mode (const | delta | raw)
                 -> deltas downcast to the smallest width (i8/i16/i32/i64)
                 -> zstd level-1 frame

Decode is a widen + cumsum (vectorizable on host, or on device via
ops.decode.delta_decode).  Floats ride the same path via the reference's
decimal-mantissa idea (float.go): value * 10^p as int64 when exact, else
raw float64 bytes.
"""

from __future__ import annotations

import numpy as np

from banyandb_tpu.utils import compress as zst
from banyandb_tpu.utils import native

_MODE_CONST = 0
_MODE_DELTA = 1
_MODE_RAW = 2
_MODE_FLOAT_RAW = 3
_MODE_FLOAT_INT = 4  # float encoded as scaled int64 (decimal mantissa)

_WIDTHS = ((np.int8, 1), (np.int16, 2), (np.int32, 4), (np.int64, 8))


def _downcast(a: np.ndarray) -> tuple[np.ndarray, int]:
    lo, hi = (int(a.min()), int(a.max())) if a.size else (0, 0)
    for dt, code in _WIDTHS:
        info = np.iinfo(dt)
        if lo >= info.min and hi <= info.max:
            return a.astype(dt), code
    raise AssertionError("int64 always fits")


def encode_int64(values: np.ndarray) -> bytes:
    """-> mode byte + width byte + first (i64 LE) + zstd(deltas)."""
    v = np.ascontiguousarray(values, dtype=np.int64)
    n = v.size
    if n == 0:
        return bytes([_MODE_CONST, 8]) + (0).to_bytes(8, "little", signed=True)
    first = int(v[0])
    if n == 1 or (v == first).all():
        return bytes([_MODE_CONST, 8]) + first.to_bytes(8, "little", signed=True)
    # Delta overflow check: int64 diff can wrap; fall back to raw.
    ok = True
    if abs(first) > 2**62:
        deltas = np.diff(v)
        ok = (v[1:].astype(object) - v[:-1].astype(object) == deltas).all()
    if ok:
        # Native single-pass encode (cpp/bydb_native.cpp) when built; the
        # payload layout is identical to the NumPy path.
        nat = native.delta_encode(v)
        if nat is not None:
            payload, width = nat
        else:
            packed, width = _downcast(np.diff(v))
            payload = packed.tobytes()
        return (
            bytes([_MODE_DELTA, width])
            + first.to_bytes(8, "little", signed=True)
            + zst.compress(payload)
        )
    return (
        bytes([_MODE_RAW, 8])
        + first.to_bytes(8, "little", signed=True)
        + zst.compress(v.tobytes())
    )


def decode_int64(blob: bytes, count: int) -> np.ndarray:
    mode, width = blob[0], blob[1]
    first = int.from_bytes(blob[2:10], "little", signed=True)
    if mode == _MODE_CONST:
        return np.full(count, first, dtype=np.int64)
    payload = zst.decompress(blob[10:])
    if mode == _MODE_RAW:
        return np.frombuffer(payload, dtype=np.int64).copy()
    nat = native.delta_decode(first, payload, count, width)
    if nat is not None:
        return nat
    dtype = {1: np.int8, 2: np.int16, 4: np.int32, 8: np.int64}[width]
    deltas = np.frombuffer(payload, dtype=dtype).astype(np.int64)
    out = np.empty(count, dtype=np.int64)
    out[0] = first
    np.cumsum(deltas, out=out[1:])
    out[1:] += first
    return out


def encode_float64(values: np.ndarray) -> bytes:
    """Decimal-mantissa trick (pkg/encoding/float.go analog): if v * 10^p is
    integral for small p, ship ints through the delta path."""
    v = np.ascontiguousarray(values, dtype=np.float64)
    if np.isfinite(v).all():
        for p in (0, 1, 2, 3):
            as_int = np.round(v * (10.0**p))
            # The only requirement is bit-exact round trip of the decode
            # expression (int / 10^p), not exactness of the scaling itself.
            if (np.abs(as_int) < 2**53).all() and (
                as_int.astype(np.int64) / (10.0**p) == v
            ).all():
                return bytes([_MODE_FLOAT_INT, p]) + encode_int64(
                    as_int.astype(np.int64)
                )
    return bytes([_MODE_FLOAT_RAW, 0]) + zst.compress(v.tobytes())


def decode_float64(blob: bytes, count: int) -> np.ndarray:
    mode, p = blob[0], blob[1]
    if mode == _MODE_FLOAT_INT:
        return decode_int64(blob[2:], count).astype(np.float64) / (10.0**p)
    if mode == _MODE_FLOAT_RAW:
        return np.frombuffer(zst.decompress(blob[2:]), dtype=np.float64).copy()
    raise ValueError(f"bad float mode {mode}")


def encode_dict_codes(codes: np.ndarray) -> bytes:
    """Dictionary code column: downcast + zstd (codes are small ints)."""
    packed, width = _downcast(np.ascontiguousarray(codes, dtype=np.int64))
    return bytes([width]) + zst.compress(packed.tobytes())


def _dict_codes_view(blob: bytes) -> np.ndarray:
    """Read-only frombuffer view of the stored code payload."""
    width = blob[0]
    dtype = {1: np.int8, 2: np.int16, 4: np.int32, 8: np.int64}[width]
    return np.frombuffer(zst.decompress(blob[1:]), dtype=dtype)


def decode_dict_codes(blob: bytes, count: int) -> np.ndarray:
    return _dict_codes_view(blob).astype(np.int32)  # astype = one copy


def decode_dict_codes_narrow(blob: bytes, count: int) -> np.ndarray:
    """Dict codes at their STORED narrow width (i8/i16/i32) — the
    device-decode ship form (ROADMAP item 3): the widen-to-i32 happens
    on device (ops.decode), so a code column crosses PCIe at 1/4 or 1/2
    of the dense width.  i64-stored codes (never produced by
    encode_dict_codes' downcast, but tolerated) widen here."""
    out = _dict_codes_view(blob)
    if out.dtype == np.int64:
        return out.astype(np.int32)
    return out.copy()  # writable (frombuffer views are read-only)


def encode_strings(values: list[bytes]) -> bytes:
    """Length-prefixed byte blocks + zstd (pkg/encoding/bytes.go analog).
    Used for dictionaries and raw payload columns (trace spans)."""
    lens = np.fromiter((len(x) for x in values), dtype=np.int64, count=len(values))
    body = b"".join(values)
    head = len(values).to_bytes(4, "little") + encode_int64(lens)
    return len(head).to_bytes(4, "little") + head + zst.compress(body)


def decode_strings(blob: bytes) -> list[bytes]:
    head_len = int.from_bytes(blob[:4], "little")
    head = blob[4 : 4 + head_len]
    n = int.from_bytes(head[:4], "little")
    lens = decode_int64(head[4:], n)
    body = zst.decompress(blob[4 + head_len :])
    out: list[bytes] = []
    off = 0
    for ln in lens.tolist():
        out.append(body[off : off + ln])
        off += ln
    return out
