"""Bloom filter (pkg/filter analog — the reference's .tff skipping-index
and per-part traceID.filter).

NumPy bit array + k blake2b-derived hash functions; serialized form is
versioned and endian-stable.
"""

from __future__ import annotations

import hashlib

import numpy as np

_MAGIC = b"BLF1"


class Bloom:
    def __init__(self, n_items: int, bits_per_item: int = 10, k: int = 7):
        self.m = max(64, n_items * bits_per_item)
        self.k = k
        self.bits = np.zeros((self.m + 63) // 64, dtype=np.uint64)

    @staticmethod
    def _hashes(value: bytes, k: int, m: int) -> list[int]:
        h = hashlib.blake2b(value, digest_size=16).digest()
        a = int.from_bytes(h[:8], "little")
        b = int.from_bytes(h[8:], "little") | 1
        return [((a + i * b) % (1 << 64)) % m for i in range(k)]

    def add(self, value: bytes) -> None:
        for pos in self._hashes(value, self.k, self.m):
            # bdlint: disable=wp-shared-state -- a Bloom under
            # construction is function-local to one part build
            # (write_trace_bloom / flush); it crosses threads only after
            # serialization, as immutable bytes on disk
            self.bits[pos >> 6] |= np.uint64(1 << (pos & 63))

    def __contains__(self, value: bytes) -> bool:
        for pos in self._hashes(value, self.k, self.m):
            if not (int(self.bits[pos >> 6]) >> (pos & 63)) & 1:
                return False
        return True

    def to_bytes(self) -> bytes:
        head = _MAGIC + self.m.to_bytes(8, "little") + self.k.to_bytes(1, "little")
        return head + self.bits.tobytes()

    @classmethod
    def from_bytes(cls, blob: bytes) -> "Bloom":
        assert blob[:4] == _MAGIC, "bad bloom frame"
        out = cls.__new__(cls)
        out.m = int.from_bytes(blob[4:12], "little")
        out.k = blob[12]
        out.bits = np.frombuffer(blob[13:], dtype=np.uint64).copy()
        return out
