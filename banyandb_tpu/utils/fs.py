"""Filesystem helpers: atomic writes and dir scanning.

Analog of pkg/fs/file_system.go:55 (atomic write = temp file + fsync +
rename) — crash mid-write never leaves a torn file visible.
"""

from __future__ import annotations

import json
import os
import tempfile
from pathlib import Path


def atomic_write(path: str | Path, data: bytes) -> None:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=f".{path.name}.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str | Path, obj) -> None:
    atomic_write(path, json.dumps(obj, indent=1, sort_keys=True).encode())


def read_json(path: str | Path):
    with open(path, "rb") as f:
        return json.loads(f.read())
