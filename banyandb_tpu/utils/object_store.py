"""Native HTTP object-store drivers: S3 (SigV4), GCS (JSON API), Azure
Blob (SharedKey).

The reference's remote FS drivers (pkg/fs/remote/{aws,gcp,azure}) ride
the vendor SDKs; none of those SDKs ship in this image, so these drivers
speak the wire protocols directly over stdlib HTTP — which also makes
the auth/signing paths first-class, testable code instead of SDK
internals:

- S3: AWS Signature Version 4 (the full canonical-request -> string-to-
  sign -> derived-key chain, hmac/hashlib only), virtual path-style
  requests, ListObjectsV2 XML.
- GCS: JSON/upload API with OAuth2 Bearer tokens.
- Azure Blob: SharedKey authorization (canonicalized headers/resource
  hmac-sha256) and List Blobs XML.

All three satisfy admin.backup.RemoteFS (put/get/list) and compose with
backup/restore/lifecycle unchanged.  tests/test_object_store.py runs
them against in-process HTTP fakes that RECOMPUTE and verify each
scheme's signature — a wrong secret is rejected at the protocol level,
like the reference's dockertest minio/azurite suites
(test/integration/dockertesthelper/minio_init.go).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from pathlib import Path


class ObjectStoreError(RuntimeError):
    def __init__(self, status: int, body: str):
        self.status = status
        super().__init__(f"object store HTTP {status}: {body[:200]}")


def _http(req: urllib.request.Request) -> bytes:
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.read()
    except urllib.error.HTTPError as e:
        raise ObjectStoreError(e.code, e.read().decode("utf-8", "replace")) from e
    except (urllib.error.URLError, OSError) as e:
        # connection-level failures (refused, DNS, TLS, socket timeout)
        # surface as the module's error type; status 0 = no HTTP reply
        raise ObjectStoreError(0, f"connection failed: {e}") from e


# -- AWS Signature Version 4 -------------------------------------------------


def _sha256_hex(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_headers(
    method: str,
    url: str,
    *,
    access_key: str,
    secret_key: str,
    region: str = "us-east-1",
    service: str = "s3",
    payload: bytes = b"",
    now: datetime.datetime | None = None,
) -> dict[str, str]:
    """Build the SigV4 Authorization + companion headers for a request.

    The canonical chain follows the SigV4 spec exactly (and therefore
    interoperates with real S3/minio): canonical request over the sorted
    signed headers, string-to-sign over its hash, signature from the
    date/region/service derived key.
    """
    u = urllib.parse.urlsplit(url)
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    payload_hash = _sha256_hex(payload)

    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in sorted(urllib.parse.parse_qsl(u.query, keep_blank_values=True))
    )
    headers = {
        "host": u.netloc,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    signed = ";".join(sorted(headers))
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in sorted(headers))
    canonical_request = "\n".join(
        [
            method,
            # S3 canonical URI = the path exactly as sent on the wire
            # (already percent-encoded once by the caller; re-quoting
            # here would double-encode and real S3 would 403)
            u.path or "/",
            canonical_query,
            canonical_headers,
            signed,
            payload_hash,
        ]
    )
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        ["AWS4-HMAC-SHA256", amz_date, scope, _sha256_hex(canonical_request.encode())]
    )
    key = _hmac(
        _hmac(_hmac(_hmac(b"AWS4" + secret_key.encode(), datestamp), region), service),
        "aws4_request",
    )
    signature = hmac.new(key, string_to_sign.encode(), hashlib.sha256).hexdigest()
    return {
        "Host": u.netloc,
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={signature}"
        ),
    }


class _PrefixedCloudFS:
    """Shared key/prefix handling for bucket-store drivers (the base of
    admin/backup's gated-SDK drivers AND the raw-REST drivers below).

    Directory semantics (match LocalDirFS): a non-empty list() prefix
    only matches keys *under* it, never string-prefix siblings like
    "<prefix>-archive/...".
    """

    prefix: str

    def _key(self, rel: str) -> str:
        return f"{self.prefix}/{rel}" if self.prefix else rel

    def _probe(self, prefix: str) -> str:
        full = self._key(prefix).strip("/")
        return full + "/" if full else ""

    def _strip(self, key: str) -> str:
        return key[len(self.prefix) + 1 :] if self.prefix else key

    def list(self, prefix: str) -> list[str]:
        return sorted(
            self._strip(k) for k in self._iter_keys(self._probe(prefix))
        )


class HttpS3FS(_PrefixedCloudFS):
    """S3 RemoteFS over raw REST + SigV4 (pkg/fs/remote/aws analog).

    endpoint: e.g. "http://127.0.0.1:9000" (minio) or
    "https://s3.us-east-1.amazonaws.com"; path-style addressing.
    """

    def __init__(
        self,
        endpoint: str,
        bucket: str,
        *,
        access_key: str,
        secret_key: str,
        region: str = "us-east-1",
        prefix: str = "",
    ):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.prefix = prefix.strip("/")

    def _url(self, key: str = "", query: str = "") -> str:
        path = f"/{self.bucket}"
        if key:
            path += "/" + urllib.parse.quote(key)
        return self.endpoint + path + (f"?{query}" if query else "")

    def _request(self, method: str, url: str, payload: bytes = b"") -> bytes:
        hdrs = sigv4_headers(
            method,
            url,
            access_key=self.access_key,
            secret_key=self.secret_key,
            region=self.region,
            payload=payload,
        )
        req = urllib.request.Request(
            url, data=payload if method == "PUT" else None, method=method
        )
        for k, v in hdrs.items():
            req.add_header(k, v)
        return _http(req)

    def put(self, rel: str, local: Path) -> None:
        self._request("PUT", self._url(self._key(rel)), Path(local).read_bytes())

    def get(self, rel: str, local: Path) -> None:
        local = Path(local)
        local.parent.mkdir(parents=True, exist_ok=True)
        local.write_bytes(self._request("GET", self._url(self._key(rel))))

    def _iter_keys(self, probe: str):
        token = ""
        while True:
            q = "list-type=2&prefix=" + urllib.parse.quote(probe, safe="")
            if token:
                q += "&continuation-token=" + urllib.parse.quote(token, safe="")
            body = self._request("GET", self._url(query=q))
            root = ET.fromstring(body)
            ns = root.tag.partition("}")[0] + "}" if "}" in root.tag else ""
            for c in root.findall(f"{ns}Contents/{ns}Key"):
                yield c.text or ""
            token = (root.findtext(f"{ns}NextContinuationToken") or "").strip()
            if not token:
                return

    def delete(self, rel: str) -> None:
        self._request("DELETE", self._url(self._key(rel)))


# -- GCS JSON API ------------------------------------------------------------


class HttpGcsFS(_PrefixedCloudFS):
    """GCS RemoteFS over the JSON/upload API with a Bearer token
    (pkg/fs/remote/gcp analog).  token_fn supplies a fresh OAuth2 token
    (a static lambda in tests; metadata-server fetch in deployments)."""

    def __init__(self, endpoint: str, bucket: str, *, token_fn, prefix: str = ""):
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.token_fn = token_fn
        self.prefix = prefix.strip("/")

    def _request(self, method: str, url: str, payload: bytes | None = None) -> bytes:
        req = urllib.request.Request(url, data=payload, method=method)
        req.add_header("Authorization", f"Bearer {self.token_fn()}")
        return _http(req)

    def put(self, rel: str, local: Path) -> None:
        name = urllib.parse.quote(self._key(rel), safe="")
        url = (
            f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o"
            f"?uploadType=media&name={name}"
        )
        self._request("POST", url, Path(local).read_bytes())

    def get(self, rel: str, local: Path) -> None:
        local = Path(local)
        local.parent.mkdir(parents=True, exist_ok=True)
        name = urllib.parse.quote(self._key(rel), safe="")
        url = f"{self.endpoint}/storage/v1/b/{self.bucket}/o/{name}?alt=media"
        local.write_bytes(self._request("GET", url))

    def _iter_keys(self, probe: str):
        import json

        token = ""
        while True:
            url = (
                f"{self.endpoint}/storage/v1/b/{self.bucket}/o"
                f"?prefix={urllib.parse.quote(probe, safe='')}"
            )
            if token:
                url += "&pageToken=" + urllib.parse.quote(token, safe="")
            resp = json.loads(self._request("GET", url))
            for o in resp.get("items", []):
                yield o["name"]
            token = resp.get("nextPageToken", "")
            if not token:
                return


# -- Azure Blob SharedKey ----------------------------------------------------


def azure_sharedkey_auth(
    method: str,
    url: str,
    *,
    account: str,
    key_b64: str,
    content_length: int,
    extra_headers: dict[str, str],
) -> str:
    """Authorization header for Azure Blob SharedKey (the reference's
    pkg/fs/remote/azure auth path): hmac-sha256 over the canonicalized
    string-to-sign."""
    import base64

    u = urllib.parse.urlsplit(url)
    canon_headers = "".join(
        f"{k}:{extra_headers[k]}\n"
        for k in sorted(extra_headers)
        if k.startswith("x-ms-")
    )
    canon_resource = f"/{account}{u.path}"
    if u.query:
        for k, v in sorted(urllib.parse.parse_qsl(u.query, keep_blank_values=True)):
            canon_resource += f"\n{k}:{v}"
    string_to_sign = "\n".join(
        [
            method,
            "",  # Content-Encoding
            "",  # Content-Language
            str(content_length) if content_length else "",
            "",  # Content-MD5
            "",  # Content-Type
            "",  # Date (x-ms-date used instead)
            "",  # If-Modified-Since
            "",  # If-Match
            "",  # If-None-Match
            "",  # If-Unmodified-Since
            "",  # Range
            canon_headers + canon_resource,
        ]
    )
    sig = base64.b64encode(
        hmac.new(
            base64.b64decode(key_b64), string_to_sign.encode(), hashlib.sha256
        ).digest()
    ).decode()
    return f"SharedKey {account}:{sig}"


class HttpAzureBlobFS(_PrefixedCloudFS):
    """Azure Blob RemoteFS over REST + SharedKey (pkg/fs/remote/azure
    analog).  endpoint: e.g. "http://127.0.0.1:10000/devstoreaccount1"
    (azurite) or "https://<account>.blob.core.windows.net"."""

    def __init__(
        self,
        endpoint: str,
        container: str,
        *,
        account: str,
        key_b64: str,
        prefix: str = "",
    ):
        self.endpoint = endpoint.rstrip("/")
        self.container = container
        self.account = account
        self.key_b64 = key_b64
        self.prefix = prefix.strip("/")

    def _request(
        self, method: str, url: str, payload: bytes | None = None, blob: bool = False
    ) -> bytes:
        now = datetime.datetime.now(datetime.timezone.utc)
        hdrs = {
            "x-ms-date": now.strftime("%a, %d %b %Y %H:%M:%S GMT"),
            "x-ms-version": "2021-08-06",
        }
        if blob:
            hdrs["x-ms-blob-type"] = "BlockBlob"
        auth = azure_sharedkey_auth(
            method,
            url,
            account=self.account,
            key_b64=self.key_b64,
            content_length=len(payload) if payload else 0,
            extra_headers=hdrs,
        )
        req = urllib.request.Request(url, data=payload, method=method)
        for k, v in hdrs.items():
            req.add_header(k, v)
        req.add_header("Authorization", auth)
        return _http(req)

    def put(self, rel: str, local: Path) -> None:
        url = f"{self.endpoint}/{self.container}/{urllib.parse.quote(self._key(rel))}"
        self._request("PUT", url, Path(local).read_bytes(), blob=True)

    def get(self, rel: str, local: Path) -> None:
        local = Path(local)
        local.parent.mkdir(parents=True, exist_ok=True)
        url = f"{self.endpoint}/{self.container}/{urllib.parse.quote(self._key(rel))}"
        local.write_bytes(self._request("GET", url))

    def _iter_keys(self, probe: str):
        marker = ""
        while True:
            url = (
                f"{self.endpoint}/{self.container}?restype=container&comp=list"
                f"&prefix={urllib.parse.quote(probe, safe='')}"
            )
            if marker:
                url += "&marker=" + urllib.parse.quote(marker, safe="")
            root = ET.fromstring(self._request("GET", url))
            for name in root.iter("Name"):
                yield name.text or ""
            marker = (root.findtext("NextMarker") or "").strip()
            if not marker:
                return
