"""Platform utilities (the reference's L0: pkg/fs, pkg/encoding,
pkg/compress, pkg/timestamp, pkg/convert analogs)."""
