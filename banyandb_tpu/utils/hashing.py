"""Series identity and shard routing.

The reference routes entity -> seriesID -> shard with xxhash
(pkg/partition/route.go:30, pkg/convert). Here series ids are 63-bit
blake2b digests of the entity tuple (deterministic across processes,
no external dep); shard = seriesID % shard_num, same contract.
"""

from __future__ import annotations

import hashlib

_SEP = b"\x00\x01"


def series_id(entity_values: list[bytes]) -> int:
    """63-bit stable hash of the entity tag tuple (non-negative int64)."""
    h = hashlib.blake2b(_SEP.join(entity_values), digest_size=8).digest()
    return int.from_bytes(h, "little") & 0x7FFF_FFFF_FFFF_FFFF


def shard_id(sid: int, shard_num: int) -> int:
    """shardID = seriesID % shard_num (pkg/partition/route.go:30 contract)."""
    return sid % shard_num


def entity_bytes(value) -> bytes:
    """Canonical byte form of one entity tag value."""
    if isinstance(value, bytes):
        return value
    if isinstance(value, str):
        return value.encode()
    if isinstance(value, bool):
        return b"\x01" if value else b"\x00"
    if isinstance(value, int):
        return value.to_bytes(8, "little", signed=True)
    raise TypeError(f"unsupported entity tag type {type(value)}")
