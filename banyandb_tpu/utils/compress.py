"""zstd block compression via ctypes on the system libzstd.

Mirrors pkg/compress (level-1 zstd on meta/primary/large payloads,
pkg/compress/zstd.go) without a Go/py dependency: the container ships
libzstd.so.1. Falls back to zlib if libzstd is missing so the format
stays readable anywhere (the frame is tagged with a 1-byte codec id).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import zlib

_CODEC_ZSTD = b"\x01"
_CODEC_ZLIB = b"\x02"

_zstd = None
try:  # pragma: no cover - environment probe
    _name = ctypes.util.find_library("zstd") or "libzstd.so.1"
    _lib = ctypes.CDLL(_name)
    _lib.ZSTD_compressBound.restype = ctypes.c_size_t
    _lib.ZSTD_compressBound.argtypes = [ctypes.c_size_t]
    _lib.ZSTD_compress.restype = ctypes.c_size_t
    _lib.ZSTD_compress.argtypes = [
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_int,
    ]
    _lib.ZSTD_decompress.restype = ctypes.c_size_t
    _lib.ZSTD_decompress.argtypes = [
        ctypes.c_void_p,
        ctypes.c_size_t,
        ctypes.c_void_p,
        ctypes.c_size_t,
    ]
    _lib.ZSTD_isError.restype = ctypes.c_uint
    _lib.ZSTD_isError.argtypes = [ctypes.c_size_t]
    _zstd = _lib
except OSError:
    _zstd = None

# The reference compresses at level 1 (pkg/compress): speed over ratio for
# the flush/merge hot path.
LEVEL = 1


def compress(data: bytes) -> bytes:
    """-> tagged frame: codec byte + uncompressed length (u32 LE) + payload."""
    header = len(data).to_bytes(4, "little")
    if _zstd is not None:
        bound = _zstd.ZSTD_compressBound(len(data))
        out = ctypes.create_string_buffer(bound)
        n = _zstd.ZSTD_compress(out, bound, data, len(data), LEVEL)
        if not _zstd.ZSTD_isError(n):
            return _CODEC_ZSTD + header + out.raw[:n]
    return _CODEC_ZLIB + header + zlib.compress(data, LEVEL)


def decompress(frame: bytes) -> bytes:
    codec, raw_len = frame[:1], int.from_bytes(frame[1:5], "little")
    payload = frame[5:]
    if codec == _CODEC_ZSTD:
        if _zstd is None:
            raise RuntimeError("zstd frame but libzstd unavailable")
        out = ctypes.create_string_buffer(raw_len)
        n = _zstd.ZSTD_decompress(out, raw_len, payload, len(payload))
        if _zstd.ZSTD_isError(n) or n != raw_len:
            raise ValueError("corrupt zstd frame")
        return out.raw
    if codec == _CODEC_ZLIB:
        return zlib.decompress(payload)
    raise ValueError(f"unknown codec id {codec!r}")
