"""In-memory write buffer (the reference's memPart,
banyand/measure/tstable.go mustAddDataPoints path).

Accumulates rows column-wise with string-tag interning so a flush is a
sort + encode, and a query over hot data can build a device batch without
re-parsing rows.
"""

from __future__ import annotations

import threading
from typing import Mapping

import numpy as np

from banyandb_tpu.storage.part import ColumnData


class MemTable:
    def __init__(self, tag_names: list[str], field_names: list[str]):
        self._lock = threading.Lock()
        self.tag_names = list(tag_names)
        self.field_names = list(field_names)
        self._ts: list[int] = []
        self._series: list[int] = []
        self._version: list[int] = []
        self._tag_codes: dict[str, list[int]] = {t: [] for t in tag_names}
        self._dicts: dict[str, dict[bytes, int]] = {t: {} for t in tag_names}
        self._fields: dict[str, list[float]] = {f: [] for f in field_names}

    def __len__(self) -> int:
        return len(self._ts)

    def append(
        self,
        ts_millis: int,
        series_id: int,
        version: int,
        tag_values: Mapping[str, bytes],
        field_values: Mapping[str, float],
    ) -> None:
        with self._lock:
            self._ts.append(ts_millis)
            self._series.append(series_id)
            self._version.append(version)
            for t in self.tag_names:
                d = self._dicts[t]
                v = tag_values.get(t, b"")
                code = d.setdefault(v, len(d))
                self._tag_codes[t].append(code)
            for f in self.field_names:
                self._fields[f].append(float(field_values.get(f, 0.0)))

    def drain(self) -> list[tuple[str, ColumnData, dict]]:
        """Flush protocol: [(part-name-suffix, columns, extra metadata)]."""
        return [("", self.snapshot_columns(), {})]

    def snapshot_columns(self) -> ColumnData:
        """Columnar view of the buffered rows (for hot-data queries/flush)."""
        with self._lock:
            return ColumnData(
                ts=np.asarray(self._ts, dtype=np.int64),
                series=np.asarray(self._series, dtype=np.int64),
                version=np.asarray(self._version, dtype=np.int64),
                tags={
                    t: np.asarray(self._tag_codes[t], dtype=np.int32)
                    for t in self.tag_names
                },
                fields={
                    f: np.asarray(self._fields[f], dtype=np.float64)
                    for f in self.field_names
                },
                dicts={
                    t: [v for v, _ in sorted(self._dicts[t].items(), key=lambda kv: kv[1])]
                    for t in self.tag_names
                },
            )
