"""In-memory write buffer (the reference's memPart,
banyand/measure/tstable.go mustAddDataPoints path).

Accumulates rows column-wise with string-tag interning so a flush is a
sort + encode, and a query over hot data can build a device batch without
re-parsing rows.
"""

from __future__ import annotations

import threading
from typing import Mapping

import numpy as np

from banyandb_tpu.storage.part import ColumnData

# Monotonic memtable generation counter (itertools.count is GIL-atomic).
import itertools as _itertools

_MEM_GEN = _itertools.count(1)


class PayloadMemtable:
    """Shard memtable keyed by resource name, for payload-bearing engines
    (stream elements / trace spans).  `meta_key` names the resource kind
    recorded in flushed part metadata ("stream" / "trace")."""

    def __init__(self, meta_key: str):
        self.meta_key = meta_key
        self._lock = threading.Lock()
        self._tables: dict[str, "MemTable"] = {}

    def __len__(self) -> int:
        return sum(len(t) for t in self._tables.values())

    def append(self, resource, tag_names, ts, sid, tags, payload) -> None:
        with self._lock:
            tbl = self._tables.get(resource)
            if tbl is None:
                tbl = self._tables[resource] = MemTable(
                    tag_names, [], with_payload=True
                )
        tbl.append(ts, sid, 0, tags, {}, payload=payload)

    def columns_for(self, resource: str):
        tbl = self._tables.get(resource)
        return tbl.snapshot_columns() if tbl else None

    def drain(self) -> list:
        return [
            (name, tbl.snapshot_columns(), {self.meta_key: name})
            for name, tbl in self._tables.items()
        ]


class MemTable:
    def __init__(
        self,
        tag_names: list[str],
        field_names: list[str],
        with_payload: bool = False,
    ):
        self._lock = threading.Lock()
        self.tag_names = list(tag_names)
        self.field_names = list(field_names)
        self._ts: list[int] = []
        self._series: list[int] = []
        self._version: list[int] = []
        self._tag_codes: dict[str, list[int]] = {t: [] for t in tag_names}
        self._dicts: dict[str, dict[bytes, int]] = {t: {} for t in tag_names}
        self._fields: dict[str, list[float]] = {f: [] for f in field_names}
        self._payloads: list[bytes] | None = [] if with_payload else None
        self._snapshot_cache: tuple[int, ColumnData] | None = None
        # process-unique generation: id() would recycle after GC and
        # alias a new table's cache_key onto a dead one's cached rows
        self._gen = next(_MEM_GEN)

    def __len__(self) -> int:
        return len(self._ts)

    def append(
        self,
        ts_millis: int,
        series_id: int,
        version: int,
        tag_values: Mapping[str, bytes],
        field_values: Mapping[str, float],
        payload: bytes | None = None,
    ) -> None:
        with self._lock:
            self._ts.append(ts_millis)
            self._series.append(series_id)
            self._version.append(version)
            for t in self.tag_names:
                d = self._dicts[t]
                v = tag_values.get(t, b"")
                code = d.setdefault(v, len(d))
                self._tag_codes[t].append(code)
            for f in self.field_names:
                self._fields[f].append(float(field_values.get(f, 0.0)))
            if self._payloads is not None:
                self._payloads.append(payload or b"")

    def append_bulk(
        self,
        ts_millis: "np.ndarray",
        series_ids: "np.ndarray",
        versions: "np.ndarray",
        tag_values: Mapping[str, list],
        field_values: Mapping[str, "np.ndarray"],
        payloads: list | None = None,
    ) -> None:
        """Vectorized append: columns land in one extend per column.

        tag_values: per-tag row values — either list[bytes] (interned
        here via np.unique so each distinct value hits the dict once) or
        an already dictionary-encoded column (duck-typed: has .values +
        .codes, models.measure.DictColumn) whose dict remaps straight
        into this table's dict — zero per-row Python.
        """
        n = len(ts_millis)
        with self._lock:
            self._ts.extend(ts_millis.tolist())
            self._series.extend(series_ids.tolist())
            self._version.extend(versions.tolist())
            for t in self.tag_names:
                vals = tag_values.get(t)
                d = self._dicts[t]
                if vals is None:
                    code = d.setdefault(b"", len(d))
                    self._tag_codes[t].extend([code] * n)
                    continue
                if hasattr(vals, "codes"):  # dictionary-encoded column
                    lut = np.fromiter(
                        (d.setdefault(v, len(d)) for v in vals.values),
                        dtype=np.int64,
                        count=len(vals.values),
                    )
                    self._tag_codes[t].extend(
                        lut[np.asarray(vals.codes, dtype=np.int64)].tolist()
                    )
                    continue
                arr = np.asarray(vals, dtype=object)
                uniq, inv = np.unique(arr, return_inverse=True)
                lut = np.fromiter(
                    (d.setdefault(v, len(d)) for v in uniq),
                    dtype=np.int64,
                    count=len(uniq),
                )
                self._tag_codes[t].extend(lut[inv].tolist())
            for f in self.field_names:
                vals = field_values.get(f)
                if vals is None:
                    self._fields[f].extend([0.0] * n)
                else:
                    self._fields[f].extend(
                        np.asarray(vals, dtype=np.float64).tolist()
                    )
            if self._payloads is not None:
                self._payloads.extend(payloads or [b""] * n)

    def drain(self) -> list[tuple[str, ColumnData, dict]]:
        """Flush protocol: [(part-name-suffix, columns, extra metadata)]."""
        return [("", self.snapshot_columns(), {})]

    def snapshot_columns(self) -> ColumnData:
        """Columnar view of the buffered rows (for hot-data queries/flush).

        Cached per row count AND built incrementally: the table is
        append-only between drains, so when rows grew since the last
        snapshot only the NEW tail converts from Python lists — the old
        prefix re-uses the previous snapshot's arrays via a memcpy
        concatenate.  Without this, sustained ingest makes every query
        that touches the memtable pay a full O(buffered-rows)
        list→numpy conversion (hundreds of ms at ~1M buffered rows, the
        dominant cost of the streamagg head/tail rescans under load);
        with it the per-query cost is O(rows since last query).  The
        cache_key ("mem", gen, count) is an honest immutable identity —
        dict codes are append-only, so prefix arrays stay valid as the
        dicts grow."""
        with self._lock:
            n = len(self._ts)
            cached = self._snapshot_cache
            if cached is not None and cached[0] == n:
                return cached[1]

            if cached is not None and 0 < cached[0] < n:
                n0, prev = cached
            else:
                n0, prev = 0, None

            def col(old, rows: list, dtype) -> np.ndarray:
                # grown table: convert only the appended tail and memcpy-
                # concat with the cached prefix; otherwise full convert
                if prev is None:
                    return np.asarray(rows, dtype=dtype)
                return np.concatenate(
                    [old, np.asarray(rows[n0:], dtype=dtype)]
                )

            snap = ColumnData(
                ts=col(prev.ts if prev else None, self._ts, np.int64),
                series=col(
                    prev.series if prev else None, self._series, np.int64
                ),
                version=col(
                    prev.version if prev else None, self._version, np.int64
                ),
                tags={
                    t: col(
                        prev.tags[t] if prev else None,
                        self._tag_codes[t], np.int32,
                    )
                    for t in self.tag_names
                },
                fields={
                    f: col(
                        prev.fields[f] if prev else None,
                        self._fields[f], np.float64,
                    )
                    for f in self.field_names
                },
                dicts=self._dicts_snapshot_locked(),
                payloads=(
                    list(self._payloads)
                    if self._payloads is not None
                    else None
                ),
                cache_key=("mem", self._gen, n),
            )
            self._snapshot_cache = (n, snap)
            return snap

    def _dicts_snapshot_locked(self) -> dict:
        """code -> value lists per tag (dict sizes are the distinct-value
        counts — small — so rebuilding per snapshot is cheap)."""
        return {
            t: [
                v
                for v, _ in sorted(
                    self._dicts[t].items(), key=lambda kv: kv[1]
                )
            ]
            for t in self.tag_names
        }
