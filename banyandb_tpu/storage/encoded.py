"""Compressed-column ship contract for device-side decode (ROADMAP item 3).

"When Is a Columnar Scan Bandwidth-Bound?" (PAPERS.md) shows columnar
scans go decode-throughput-bound long before compute-bound: the win is
not a faster kernel but fewer bytes crossing the PCIe boundary and less
host-side widening work.  This module is the L1 substrate half of that
contract — the width/packing helpers both the storage layer (Part.read's
narrow-code mode) and the query executors (the pad/ship stage feeding
``ops.decode``'s device kernels) resolve through:

- tag dictionary-code columns keep their *stored* narrow width
  (i8/i16/i32, utils/encoding.encode_dict_codes downcasts by value) all
  the way to the device; the widen-to-i32 plus the local->global
  dictionary remap run as the first stage INSIDE the fused per-chunk
  kernel (ops.decode.dict_remap) instead of as per-element host numpy;
- integer-valued field columns ship as the narrowest exact int dtype
  (i8/i16) and convert to f32 on device — bit-identical to the host
  f64 -> f32 cast because int -> f32 conversion of values within the
  narrow range is exact from either source width.

``BYDB_DEVICE_DECODE`` (default on) is the A/B flag with the same
contract as ``BYDB_FUSED``: flipping it live must be byte-identical on
partials bytes and result JSON (tests/test_fused_exec.py +
tests/test_decode.py pin this across every builtin plan signature).
``BYDB_ZONE_SKIP`` (default on) gates the zone-map block skipping half
of the same ROADMAP item (storage/part.select_blocks).
"""

from __future__ import annotations

import numpy as np

from banyandb_tpu.utils.envflag import env_flag

# source-ordinal column dtype: a part-batch never exceeds i16 sources
SRC_ORD_DTYPE = np.int16


def device_decode_enabled() -> bool:
    """The device-decode A/B flag; default on, read per call so tests
    and operators can flip it live (same contract as ``BYDB_FUSED``)."""
    return env_flag("BYDB_DEVICE_DECODE", default=True)


def zone_skip_enabled() -> bool:
    """Zone-map block skipping flag; default on.  Off = every block
    that survives time/series pruning is still read (the pre-zone-map
    behavior), which is the parity baseline decode_smoke A/Bs against."""
    return env_flag("BYDB_ZONE_SKIP", default=True)


def code_dtype(dict_len: int) -> np.dtype:
    """Smallest signed int dtype holding every local code of a
    ``dict_len``-entry dictionary (codes are 0..dict_len-1; -1/-2/-3
    sentinels used by the mask kernels also fit every signed width)."""
    if dict_len <= 1 << 7:
        return np.dtype(np.int8)
    if dict_len <= 1 << 15:
        return np.dtype(np.int16)
    return np.dtype(np.int32)


def narrow_int_dtype(values: np.ndarray):
    """Narrowest int dtype that round-trips ``values`` exactly through
    an int -> f32 device conversion, or None when the column must ship
    dense f32 (non-integral, non-finite, or too wide).

    i8/i16 only: an i32 ship would be the same 4 bytes/row as the dense
    f32 it replaces, so there is nothing to win past i16."""
    if values.size == 0:
        return np.dtype(np.int8)
    if not np.isfinite(values).all():
        return None
    if not (values == np.rint(values)).all():
        return None
    if np.signbit(values[values == 0.0]).any():
        # -0.0 passes the integrality check but would decode to +0.0f,
        # flipping the f32 sign bit vs the dense ship — not byte-safe
        return None
    lo, hi = float(values.min()), float(values.max())
    if -(1 << 7) <= lo and hi < 1 << 7:
        return np.dtype(np.int8)
    if -(1 << 15) <= lo and hi < 1 << 15:
        return np.dtype(np.int16)
    return None


def _pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length() if n > 1 else 1


def pack_luts(luts) -> np.ndarray:
    """Stack per-source local->global code LUTs into one ``[S, L]`` i32
    array with power-of-two padded axes (finite jit shape set).

    Row ``s`` holds source s's LUT; pad entries are 0 and are never
    indexed by construction (every row's local codes are < that row's
    real LUT length) — the device gather still clips defensively
    (ops.decode.dict_remap's OOB guard)."""
    luts = list(luts)
    if not luts:
        return np.zeros((1, 1), dtype=np.int32)
    s_pad = _pow2(len(luts))
    l_pad = _pow2(max(max(len(l) for l in luts), 1))
    out = np.zeros((s_pad, l_pad), dtype=np.int32)
    for i, lut in enumerate(luts):
        out[i, : len(lut)] = np.asarray(lut, dtype=np.int32)
    return out
