"""TSDB: group -> time segments -> shards -> (memtable + parts + snapshot).

Analog of banyand/internal/storage (TSDBOpts tsdb.go:55, segment naming
storage.go:46-50, snapshot MVCC snapshot.go, shard tree shard.go) rebuilt
host-side:

    <root>/<group>/
      seg-<YYYYMMDD[HH]>/
        shard-<i>/
          part-<016x>/...
          snapshot.snp        # JSON: {"epoch": N, "parts": [names]}

Readers only see parts listed in the shard's current snapshot; writers
flush memtables into new parts then atomically publish a new snapshot —
the same MVCC contract as the reference's .snp manifests.
"""

from __future__ import annotations

import datetime as dt
import os
import threading
import time
from pathlib import Path
from typing import Callable, Iterator, Optional

import numpy as np

from banyandb_tpu.api.schema import ResourceOpts
from banyandb_tpu.storage.memtable import MemTable
from banyandb_tpu.storage.part import ColumnData, Part, PartWriter
from banyandb_tpu.utils import fs

SNAPSHOT = "snapshot.snp"
# Segment-level marker: tier migration is shipping this segment's parts.
# Background merges skip marked segments (part names are the resumable
# progress keys, so compaction must not rewrite them mid-migration); the
# marker persists across crashes and leaves with the migrated segment.
MIGRATING_MARKER = ".migrating"


def segment_name(start_millis: int, interval_unit: str) -> str:
    t = dt.datetime.fromtimestamp(start_millis / 1000, tz=dt.timezone.utc)
    if interval_unit == "hour":
        return f"seg-{t:%Y%m%d%H}"
    return f"seg-{t:%Y%m%d}"


def segment_start(ts_millis: int, interval_millis: int) -> int:
    return ts_millis - (ts_millis % interval_millis)


class Shard:
    """One shard of one segment: a memtable + immutable parts + snapshot."""

    def __init__(
        self,
        root: Path,
        mem_factory: Callable[[], MemTable],
        merge_filter_provider: Optional[Callable] = None,
        part_built_provider: Optional[Callable] = None,
    ):
        self.root = root
        self.root.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        self._mem_factory = mem_factory
        self._merge_filter_provider = merge_filter_provider
        self._part_built_provider = part_built_provider
        self.mem = mem_factory()
        self._epoch = 0
        self._parts: dict[str, Part] = {}
        # in-flight flush snapshot ((resource name, ColumnData), ...):
        # drained memtable rows stay queryable while their part encodes
        # OUTSIDE the lock (see flush()).  Immutable tuple rebinds only.
        self._flushing: tuple = ()
        # serializes whole flush() invocations: the lifecycle loop and
        # the operator flush/snapshot surface may race, and _flushing is
        # a single slot — a second concurrent flush would overwrite the
        # first's in-flight snapshot and hide its rows mid-encode
        self._flush_mutex = threading.Lock()
        self._load_snapshot()

    def _notify_part_built(self, part_dir, extra_meta) -> None:
        """Engine hook (element-index/bloom sidecar builder): sidecars
        are a pruning optimization, so a failing builder must never fail
        the flush/merge that produced the part."""
        if self._part_built_provider is None:
            return
        cb = self._part_built_provider()
        if cb is None:
            return
        try:
            cb(part_dir, extra_meta)
        except Exception:  # noqa: BLE001
            import logging

            logging.getLogger(__name__).exception(
                "part index build failed; part serves unpruned"
            )

    FAILED_PARTS_DIR = "failed-parts"
    FAILED_PARTS_CAP = 16  # quarantined dirs kept (oldest evicted)

    def _load_snapshot(self) -> None:
        snp = self.root / SNAPSHOT
        listed: set[str] = set()
        quarantined = []
        if snp.exists():
            data = fs.read_json(snp)
            self._epoch = data["epoch"]
            listed = set(data["parts"])
            for name in data["parts"]:
                pdir = self.root / name
                if not pdir.exists():
                    continue
                try:
                    self._parts[name] = Part(pdir)
                except Exception:  # noqa: BLE001 - one bad part must not
                    # brick the shard: quarantine and keep serving
                    # (storage/failed_parts_handler.go analog)
                    quarantined.append(name)
        if quarantined:
            import shutil

            fp = self.root / self.FAILED_PARTS_DIR
            fp.mkdir(exist_ok=True)
            for name in quarantined:
                dest = fp / name
                if dest.exists():
                    shutil.rmtree(dest, ignore_errors=True)
                (self.root / name).rename(dest)
                listed.discard(name)
            # size cap: evict oldest quarantined dirs
            kept = sorted(fp.iterdir(), key=lambda p: p.name)
            for old in kept[: max(0, len(kept) - self.FAILED_PARTS_CAP)]:
                shutil.rmtree(old, ignore_errors=True)
            self._publish()
        # GC orphans: part dirs written but never published (crash between
        # PartWriter.write and _publish), and dirs dropped by a merge whose
        # deletion didn't complete.  Without this, a crash mid-flush would
        # permanently collide on the next epoch's part name.
        import shutil

        for pdir in self.root.glob("part-*"):
            if pdir.name not in listed:
                shutil.rmtree(pdir, ignore_errors=True)
        for pdir in self.root.glob(".tmp-merge-*"):
            shutil.rmtree(pdir, ignore_errors=True)
        for pdir in self.root.glob(".tmp-flush-*"):
            shutil.rmtree(pdir, ignore_errors=True)

    def _publish(self) -> None:
        fs.atomic_write_json(
            self.root / SNAPSHOT,
            {"epoch": self._epoch, "parts": sorted(self._parts.keys())},
        )

    @property
    def parts(self) -> list[Part]:
        with self._lock:
            return list(self._parts.values())

    def ingest(self, fn) -> None:
        """Run `fn(memtable)` under the shard lock.

        All writers MUST go through this: it excludes flush()'s memtable
        swap, which would otherwise strand a racing append in the drained
        table (write lost silently).
        """
        with self._lock:
            fn(self.mem)

    def flush(self) -> Optional[list[str]]:
        """Memtable -> new part(s) + snapshot publish. Returns part names.

        Multi-resource memtables (measure engines) drain to one part per
        resource.  The shard lock is held only for the two O(1) commit
        points — the memtable swap and the rename+publish — NEVER across
        the part encode/write: at sustained ingest a whole-memtable
        encode is hundreds of ms, and holding the lock there stalled
        every concurrent append AND every query's ``parts`` snapshot
        behind the flush (the streamagg load run measured multi-second
        query tails from exactly this).  Between the two commit points
        the drained rows stay queryable through the ``_flushing``
        snapshot (``hot_columns``); a reader racing the second commit
        may see a row in BOTH the flushing snapshot and the new part,
        which the (series, ts) max-version dedup every query path
        already applies collapses to one — rows are never invisible.
        """
        import shutil
        import uuid as _uuid

        with self._flush_mutex:
            return self._flush_serialized(shutil, _uuid)

    def _flush_serialized(self, shutil, _uuid) -> Optional[list[str]]:
        with self._lock:
            if len(self.mem) == 0:
                return None
            drained = self.mem.drain()
            # publish the flushing snapshot BEFORE swapping the memtable:
            # hot_columns reads (mem, _flushing) lock-free in that order,
            # so rows must appear in _flushing before they vanish from
            # mem — the transient double-expose dedups, a gap would not
            self._flushing = tuple(
                (name, cols) for name, cols, _m in drained
            )
            self.mem = self._mem_factory()
        tmp_dirs: list[tuple[Path, dict]] = []
        try:
            for _suffix, cols, extra_meta in drained:
                if cols.ts.size == 0:
                    continue
                tmp = self.root / f".tmp-flush-{_uuid.uuid4().hex}"
                PartWriter.write(
                    tmp,
                    ts=cols.ts,
                    series=cols.series,
                    version=cols.version,
                    tag_codes=dict(cols.tags),
                    tag_dicts=dict(cols.dicts),
                    fields=dict(cols.fields),
                    extra_meta=extra_meta,
                    payloads=cols.payloads,
                )
                tmp_dirs.append((tmp, extra_meta))
            names = []
            built = []
            with self._lock:
                for tmp, extra_meta in tmp_dirs:
                    self._epoch += 1
                    name = f"part-{self._epoch:016x}"
                    os.rename(tmp, self.root / name)
                    self._parts[name] = Part(self.root / name)
                    names.append(name)
                    built.append((self.root / name, extra_meta))
                self._publish()
                self._flushing = ()
        except BaseException:
            # failed encode: same contract as before (rows in a failed
            # flush are lost with the exception surfaced), but the
            # flushing snapshot must not keep serving rows that will
            # never become a part
            with self._lock:
                self._flushing = ()
            for tmp, _m in tmp_dirs:
                shutil.rmtree(tmp, ignore_errors=True)
            raise
        # sidecar builds decode whole parts — outside the lock so appends
        # and publishes don't stall (queries before sidecars exist simply
        # scan unpruned; pruning is optional)
        for part_dir, extra_meta in built:
            self._notify_part_built(part_dir, extra_meta)
        return names

    @property
    def has_unflushed(self) -> bool:
        """Rows not yet committed to a published part: live memtable OR
        an in-flight flush snapshot (tier migration's quiescence gate
        must count both, or it could drop a segment whose last rows are
        mid-encode)."""
        return len(self.mem) > 0 or bool(self._flushing)

    def hot_columns(self, resource: str) -> list:
        """Unflushed sources for one resource: the live memtable plus
        any in-flight flush snapshot (rows between flush's two commit
        points).  Read lock-free — ``mem`` and ``_flushing`` are
        immutable-snapshot rebinds, and the memtable-first read order
        plus version dedup downstream makes every interleaving with
        flush() exact (see flush())."""
        out = []
        mem_cols = self.mem.columns_for(resource)
        if mem_cols is not None and mem_cols.ts.size:
            out.append(mem_cols)
        for rname, cols in self._flushing:
            if rname == resource and cols.ts.size:
                out.append(cols)
        return out

    def merge(
        self,
        min_merge: Optional[int] = None,
        max_parts: Optional[int] = None,
    ) -> Optional[str]:
        """One merge round (merger.go:39 analog). Returns new part name.

        Column reads AND the merged-part encode/write happen outside the
        lock (victim parts are immutable; the merged part lands in a temp
        dir).  Under the lock only: re-check victims, rename temp dir to
        its epoch name, swap the part set, publish — the atomic commit
        (introducer.go:114 mergedIntroduction analog).  Old dirs are
        removed after publish — an in-flight reader that snapshotted the
        old part list can hit a vanished dir, a retryable snapshot miss
        (same contract as the reference's epoch-based part GC).
        """
        import shutil

        from banyandb_tpu.storage import merge as merge_mod

        kwargs = {}
        if min_merge is not None:
            kwargs["min_merge"] = min_merge
        if max_parts is not None:
            kwargs["max_parts"] = max_parts
        victims = merge_mod.pick_merge_victims(self.parts, **kwargs)
        if not victims:
            return None
        cols, extra_meta = merge_mod.merge_columns(victims)
        # Sampler-chain gating at merge (trace/merger.go:318-342 analog):
        # an engine-installed filter returns a keep-mask over merged rows.
        if self._merge_filter_provider is not None:
            fn = self._merge_filter_provider()
            if fn is not None:
                import numpy as _np

                kind, name = merge_mod.resource_key(victims[0])
                try:
                    keep = fn(kind, name, cols)
                    if keep is not None:
                        keep = _np.asarray(keep, dtype=bool)
                        if keep.shape != cols.ts.shape:
                            raise ValueError(
                                f"sampler mask {keep.shape} != rows {cols.ts.shape}"
                            )
                except Exception:  # noqa: BLE001 - a buggy plugin must
                    # degrade to keep-all, never wedge the merge loop
                    import logging

                    logging.getLogger(__name__).exception(
                        "merge filter failed; keeping all rows"
                    )
                    keep = None
                if keep is not None:
                    cols = ColumnData(
                        ts=cols.ts[keep],
                        series=cols.series[keep],
                        version=cols.version[keep],
                        tags={t: c[keep] for t, c in cols.tags.items()},
                        fields={f: v[keep] for f, v in cols.fields.items()},
                        dicts=cols.dicts,
                        payloads=(
                            [p for p, k in zip(cols.payloads, keep) if k]
                            if cols.payloads is not None
                            else None
                        ),
                    )
        tmp_dir = self.root / f".tmp-merge-{os.getpid()}-{id(cols):x}"
        PartWriter.write(
            tmp_dir,
            ts=cols.ts,
            series=cols.series,
            version=cols.version,
            tag_codes=dict(cols.tags),
            tag_dicts=dict(cols.dicts),
            fields=dict(cols.fields),
            extra_meta=extra_meta,
            payloads=cols.payloads,
        )
        self._notify_part_built(tmp_dir, extra_meta)
        with self._lock:
            if any(v.name not in self._parts for v in victims):
                shutil.rmtree(tmp_dir, ignore_errors=True)
                return None  # lost a race with another merge round
            self._epoch += 1
            name = f"part-{self._epoch:016x}"
            os.rename(tmp_dir, self.root / name)
            for v in victims:
                del self._parts[v.name]
            self._parts[name] = Part(self.root / name)
            self._publish()
        for v in victims:
            shutil.rmtree(v.dir, ignore_errors=True)
        return name


class Segment:
    """One time bucket: a shard list + [start, end) bounds + series index
    (the per-segment sidx of the reference, segment.go:540)."""

    def __init__(
        self,
        root: Path,
        start_millis: int,
        interval_millis: int,
        shard_num: int,
        mem_factory: Callable[[], MemTable],
        merge_filter_provider: Optional[Callable] = None,
        part_built_provider: Optional[Callable] = None,
        clock: Callable[[], float] = time.time,
    ):
        self.root = root
        self.start = start_millis
        self.end = start_millis + interval_millis
        self._clock = clock
        self.shards = [
            Shard(
                root / f"shard-{i}",
                mem_factory,
                merge_filter_provider=merge_filter_provider,
                part_built_provider=part_built_provider,
            )
            for i in range(shard_num)
        ]
        self._sidx = None
        self._sidx_lock = threading.Lock()
        # idle-reclaim clock (segment.go:81 lastAccessed analog): bumped by
        # real read/write touches, NOT by background loops walking segments
        self.last_accessed = clock()
        self._reclaimed = False

    def touch(self) -> None:
        self.last_accessed = self._clock()
        # caches may repopulate from here on: eligible for reclaim again
        self._reclaimed = False

    def reset_index(self) -> None:
        """Persist + release the series index's memory and per-part
        dictionary caches (segmentController.closeIdleSegments /
        segment.resetIndex analog, rotation.go:134, segment.go:334).

        The reference's motivation transfers directly: without reclaim,
        per-segment index writers accumulate across rotations.  `_sidx`
        keeps its identity (never reset to None) — concurrent holders see
        the same object, whose internal lock serializes reclaim against
        in-flight inserts/searches and lazily reloads on next use."""
        # flag FIRST: a touch() racing with the release below clears it,
        # keeping a just-repopulated segment eligible for the next idle
        # pass (flag-last would clobber the touch and exempt it forever)
        self._reclaimed = True
        with self._sidx_lock:
            sidx = self._sidx
        if sidx is not None:
            sidx.reclaim()
        for shard in self.shards:
            for part in shard.parts:
                part.release_cached()

    @property
    def series_index(self):
        if self._sidx is None:
            with self._sidx_lock:
                if self._sidx is None:
                    from banyandb_tpu.index.series import SeriesIndex

                    self._sidx = SeriesIndex(self.root / "sidx.idx")
        return self._sidx

    def persist_index(self) -> None:
        if self._sidx is not None:
            self._sidx.persist()

    def overlaps(self, begin: int, end: int) -> bool:
        return self.start < end and begin < self.end


class TSDB:
    """Per-(group, engine) database: segment map + routing (tsdb.go:145)."""

    def __init__(
        self,
        root: str | Path,
        group: str,
        opts: ResourceOpts,
        mem_factory: Callable[[], MemTable],
        clock: Callable[[], float] = time.time,
    ):
        self.root = Path(root) / group
        self.opts = opts
        self.mem_factory = mem_factory
        self._clock = clock
        self._lock = threading.Lock()
        self._segments: dict[int, Segment] = {}
        # Optional merge-time row filter: fn(kind, name, ColumnData) ->
        # keep-mask (bool array) or None.  The trace engine's sampler
        # pipeline hook (PIPELINE_EVENT_MERGE analog) — engines set it;
        # Shard.merge applies it after column combine.
        self.merge_filter = None
        # Optional engine hook: fn(part_dir, extra_meta) called after any
        # part is fully written (flush and merge) — the stream engine's
        # element-index/bloom sidecar builder (index/element.py).
        self.on_part_built = None
        # rotation scheduler state (rotation.go:31-47 analog): ticks are
        # throttled to one per snap window; pre-creation fires only inside
        # the creation gap before the latest segment's end.
        self.tick_snap_ms = 600_000  # timeEventSnapDuration (10 min)
        self.creation_gap_ms = 3_600_000  # creationGap (1 h)
        self._latest_tick_ms = 0
        # high-water mark of write-event timestamps: rotation ticks derive
        # from it (rotation.go Tick is fed by write events, NOT wall clock),
        # so a write-idle group stops pre-creating segments
        self.max_event_ms = 0
        self._reopen()

    def _reopen(self) -> None:
        """Rediscover existing segments from disk (restart path)."""
        if not self.root.exists():
            return
        iv = self.opts.segment_interval
        for seg_dir in sorted(self.root.glob("seg-*")):
            stamp = seg_dir.name[4:]
            if iv.unit == "hour":
                t = dt.datetime.strptime(stamp, "%Y%m%d%H")
            else:
                t = dt.datetime.strptime(stamp, "%Y%m%d")
            start = int(t.replace(tzinfo=dt.timezone.utc).timestamp() * 1000)
            self._segments[start] = Segment(
                seg_dir, start, iv.millis, self.opts.shard_num,
                self.mem_factory, lambda: self.merge_filter,
                lambda: self.on_part_built, clock=self._clock,
            )

    def segment_for(
        self, ts_millis: int, create: bool = True, event: bool = True
    ) -> Optional[Segment]:
        """event=False marks non-write callers (tick's own pre-creation):
        they must not advance the write high-water mark, or a pre-created
        segment's start would itself count as a "write" and chain into
        runaway pre-creation on hour-interval segments."""
        iv = self.opts.segment_interval
        start = segment_start(ts_millis, iv.millis)
        with self._lock:
            seg = self._segments.get(start)
            if seg is None and create:
                seg = Segment(
                    self.root / segment_name(start, iv.unit),
                    start,
                    iv.millis,
                    self.opts.shard_num,
                    self.mem_factory,
                    lambda: self.merge_filter,
                    lambda: self.on_part_built,
                    clock=self._clock,
                )
                self._segments[start] = seg
            if seg is not None:
                seg.touch()
                if create and event and ts_millis > self.max_event_ms:
                    self.max_event_ms = ts_millis
            return seg

    def tick(self, ts_millis: int) -> bool:
        """Rotation tick (rotation.go:36 Tick + :52 startRotationTask).

        Pre-creates the NEXT time segment once `ts` enters the creation
        gap before the latest segment's end, so the first write landing in
        a fresh time bucket never pays segment mkdir + shard + index-open
        latency inline.  Ticks are throttled to one per `tick_snap_ms`.
        Returns True when a segment was pre-created.
        """
        if ts_millis <= 0:
            return False
        if ts_millis - self.tick_snap_ms < self._latest_tick_ms:
            return False
        self._latest_tick_ms = ts_millis
        with self._lock:
            if not self._segments:
                return False
            latest = self._segments[max(self._segments)]
            gap = latest.end - ts_millis
        # gap <= 0: the event is from the future — the write path itself
        # creates that segment directly (rotation.go:115 comment).  Once a
        # pre-creation fires, `latest` advances to the new segment, so
        # follow-up ticks in the same window see gap > interval and are
        # no-ops: True really does mean "a segment was created".
        if gap <= 0 or gap > min(self.creation_gap_ms, self.opts.segment_interval.millis):
            return False
        self.segment_for(latest.end, event=False)
        return True

    def close_idle_segments(self, idle_timeout_s: float, now_s: Optional[float] = None) -> int:
        """Release index + cache memory of segments idle past the timeout
        (segmentController.closeIdleSegments, segment.go:334 analog).

        Reclaim is memory-only: parts and the persisted series index stay
        on disk and reopen lazily, so reclaiming a segment a query is
        about to touch costs a reload, never correctness."""
        if idle_timeout_s <= 0:
            return 0
        # same clock domain as Segment.touch — callers normally omit now_s
        now = self._clock() if now_s is None else now_s
        closed = 0
        for seg in self.segments:
            # _reclaimed: nothing repopulated since the last reclaim (only
            # a touch clears it) — skip, so a permanently idle segment is
            # neither re-walked nor re-counted every pass
            if not seg._reclaimed and now - seg.last_accessed >= idle_timeout_s:
                seg.reset_index()
                closed += 1
        return closed

    def close(self) -> None:
        """Deterministic shutdown: persist + release every segment's
        index memory and mmap'd files (the explicit analog of
        close_idle_segments — without it, sidx segment handles outlive
        the database and fail the bdsan fd-leak gate).  Reopen stays
        lazy, so a closed TSDB that is touched again just reloads."""
        for seg in self.segments:
            seg.reset_index()

    def select_segments(self, begin: int, end: int) -> list[Segment]:
        """Segments overlapping [begin, end) (storage.go:118 analog)."""
        with self._lock:
            hit = [
                s
                for _, s in sorted(self._segments.items())
                if s.overlaps(begin, end)
            ]
        for s in hit:
            s.touch()
        return hit

    @property
    def segments(self) -> list[Segment]:
        with self._lock:
            return [s for _, s in sorted(self._segments.items())]

    def flush_all(self) -> list[str]:
        flushed = []
        for seg in self.segments:
            for shard in seg.shards:
                names = shard.flush()
                for name in names or []:
                    flushed.append(f"{seg.root.name}/{shard.root.name}/{name}")
            seg.persist_index()
        return flushed

    def retention_sweep(self, now_millis: int) -> list[str]:
        """Delete segments past TTL (rotation.go retentionTask analog)."""
        import shutil

        cutoff = now_millis - self.opts.ttl.millis
        removed = []
        with self._lock:
            for start in list(self._segments.keys()):
                seg = self._segments[start]
                if seg.end <= cutoff:
                    shutil.rmtree(seg.root, ignore_errors=True)
                    removed.append(seg.root.name)
                    del self._segments[start]
        return removed
