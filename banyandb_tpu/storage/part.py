"""On-disk columnar part format.

Layout analog of the reference's measure part
(banyand/measure/part.go:48-52 — meta.bin, primary.bin, timestamps.bin,
fv.bin, per-family tag files, metadata.json) redesigned so decoded columns
land directly in device-feedable dense arrays:

    part-<id>/
      metadata.json        # part-level stats + column inventory
      primary.bin          # zstd(JSON block index: per-block column extents)
      timestamps.bin       # per-block encoded int64 columns, concatenated
      series.bin           # per-block encoded series ids
      versions.bin         # per-block encoded write versions
      tag_<name>.bin       # per-block encoded dictionary codes
      tag_<name>.dict      # part-level dictionary (string table)
      field_<name>.bin     # per-block encoded numeric values

Rows are sorted by (series_id, ts); blocks cap at 8192 rows
(ops.blocks.MAX_ROWS, mirroring banyand/measure/measure.go:46).  Every
block records (offset, size) per column plus min/max ts + series for
pruning, so a query reads only the byte ranges its time range needs.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, Mapping, Optional, Sequence

import numpy as np

from banyandb_tpu.ops.blocks import MAX_ROWS
from banyandb_tpu.utils import compress as zst
from banyandb_tpu.utils import encoding as enc
from banyandb_tpu.utils import fs

_TS = "timestamps"
_SERIES = "series"
_VERSIONS = "versions"


@dataclass(frozen=True)
class ColumnData:
    """Decoded columns for a run of selected blocks (host numpy)."""

    ts: np.ndarray  # int64 [n]
    series: np.ndarray  # int64 [n]
    version: np.ndarray  # int64 [n]
    tags: Mapping[str, np.ndarray]  # int codes [n] (i32; narrow i8/i16
    # at stored width when read with narrow_codes=True — device decode)
    fields: Mapping[str, np.ndarray]  # float64 [n]
    dicts: Mapping[str, list[bytes]]  # per-tag dictionary
    # opaque per-row payloads (stream element ids / trace span bytes,
    # spans.bin analog); None for measure parts
    payloads: "Optional[list[bytes]]" = None
    # immutable identity for serving-cache layers (set for part-backed
    # sources; None for memtable/index sources, which mutate)
    cache_key: "Optional[tuple]" = None


@dataclass(frozen=True)
class KeyInterval:
    """The (series, ts) key coverage of one block/source, used by the
    zone-skip dedup-safety check (see Part.select_blocks).

    Rows are sorted by (series, ts), so a block's true key set is a
    contiguous LEX range [``lo``, ``hi``]; every key also lies in the
    series x ts rect (series range = the lex endpoints' series,
    ``ts_lo``/``ts_hi`` the block-wide ts bounds).  Two sources can
    share a key only if BOTH the lex ranges and the rects intersect —
    the conjunction prunes the two common false-overlap shapes: blocks
    of one part (lex-disjoint but rect-overlapping) and time-disjoint
    parts (lex-overlapping via series order but ts-disjoint).
    Conservative endpoints (rect corners, used for memtable sources and
    pre-upgrade parts) only ever widen the interval — safe."""

    lo: tuple  # (series, ts) lex lower bound
    hi: tuple  # (series, ts) lex upper bound
    ts_lo: int
    ts_hi: int

    @staticmethod
    def conservative(
        min_series: int, max_series: int, min_ts: int, max_ts: int
    ) -> "KeyInterval":
        return KeyInterval(
            (int(min_series), int(min_ts)),
            (int(max_series), int(max_ts)),
            int(min_ts),
            int(max_ts),
        )

    def intersects(self, other: "KeyInterval") -> bool:
        lex = self.lo <= other.hi and other.lo <= self.hi
        rect = self.ts_lo <= other.ts_hi and other.ts_lo <= self.ts_hi
        return lex and rect


def _col_file(name: str) -> str:
    if name in (_TS, _SERIES, _VERSIONS):
        return f"{name}.bin"
    return f"{name}.bin"


class PartWriter:
    """Builds one immutable part from sorted columnar data."""

    @staticmethod
    def write(
        part_dir: str | Path,
        *,
        ts: np.ndarray,
        series: np.ndarray,
        version: np.ndarray,
        tag_codes: Mapping[str, np.ndarray],
        tag_dicts: Mapping[str, list[bytes]],
        fields: Mapping[str, np.ndarray],
        extra_meta: Optional[Mapping] = None,
        payloads: Optional[Sequence[bytes]] = None,
    ) -> None:
        part_dir = Path(part_dir)
        part_dir.mkdir(parents=True, exist_ok=False)
        n = len(ts)
        order = np.lexsort((ts, series))
        ts, series, version = ts[order], series[order], version[order]
        tag_codes = {k: v[order] for k, v in tag_codes.items()}
        fields = {k: v[order] for k, v in fields.items()}
        if payloads is not None:
            payloads = [payloads[i] for i in order]

        blocks = []
        buffers: dict[str, bytearray] = {}

        def append(col: str, blob: bytes) -> tuple[int, int]:
            buf = buffers.setdefault(col, bytearray())
            off = len(buf)
            buf.extend(blob)
            return off, len(blob)

        for start in range(0, max(n, 1), MAX_ROWS):
            end = min(start + MAX_ROWS, n)
            if end <= start:
                break
            sl = slice(start, end)
            extents = {
                _TS: append(_TS, enc.encode_int64(ts[sl])),
                _SERIES: append(_SERIES, enc.encode_int64(series[sl])),
                _VERSIONS: append(_VERSIONS, enc.encode_int64(version[sl])),
            }
            for name, codes in tag_codes.items():
                extents[f"tag_{name}"] = append(
                    f"tag_{name}", enc.encode_dict_codes(codes[sl])
                )
            for name, vals in fields.items():
                extents[f"field_{name}"] = append(
                    f"field_{name}", enc.encode_float64(vals[sl])
                )
            if payloads is not None:
                extents["payload"] = append(
                    "payload", enc.encode_strings(payloads[start:end])
                )
            # Per-block zone maps (provenance-style block skipping, arXiv
            # 2104.12815): local-code min/max per tag and value min/max
            # per field, written at flush AND merge (both go through this
            # writer).  The planner intersects query predicates with
            # these so non-matching blocks are skipped before any extent
            # read (select_blocks zone_preds).  Parts written before this
            # key existed simply never skip (back-compat).
            # `key_lo`/`key_hi` are the EXACT first/last (series, ts)
            # keys of the (sorted) block — the block's contiguous key
            # range, which the dedup-safety overlap check uses: a
            # non-matching block may only be skipped when it cannot
            # share a (series, ts) key with a kept source, else its
            # newer write-versions could be what supersedes a kept,
            # matching row.
            zones: dict[str, list] = {
                "key_lo": [int(series[start]), int(ts[start])],
                "key_hi": [int(series[end - 1]), int(ts[end - 1])],
            }
            for name, codes in tag_codes.items():
                zones[f"tag_{name}"] = [
                    int(codes[sl].min()),
                    int(codes[sl].max()),
                ]
            for name, vals in fields.items():
                blk_vals = vals[sl]
                finite = blk_vals[np.isfinite(blk_vals)]
                if finite.size:
                    zones[f"field_{name}"] = [
                        float(finite.min()),
                        float(finite.max()),
                    ]
            blocks.append(
                {
                    "count": end - start,
                    "min_ts": int(ts[sl].min()),
                    "max_ts": int(ts[sl].max()),
                    "min_series": int(series[sl].min()),
                    "max_series": int(series[sl].max()),
                    "zones": zones,
                    "extents": {k: list(v) for k, v in extents.items()},
                }
            )

        for col, buf in buffers.items():
            fs.atomic_write(part_dir / _col_file(col), bytes(buf))
        for name, d in tag_dicts.items():
            fs.atomic_write(part_dir / f"tag_{name}.dict", enc.encode_strings(d))
        fs.atomic_write(part_dir / "primary.bin", zst.compress(json.dumps(blocks).encode()))
        meta = {
            "total_count": int(n),
            "blocks": len(blocks),
            "min_ts": int(ts.min()) if n else 0,
            "max_ts": int(ts.max()) if n else 0,
            "tags": sorted(tag_codes.keys()),
            "fields": sorted(fields.keys()),
            "has_payload": payloads is not None,
        }
        if extra_meta:
            meta.update(extra_meta)
        fs.atomic_write_json(part_dir / "metadata.json", meta)


class Part:
    """Immutable on-disk part: block pruning + selective column reads."""

    def __init__(self, part_dir: str | Path):
        self.dir = Path(part_dir)
        self.meta = fs.read_json(self.dir / "metadata.json")
        with open(self.dir / "primary.bin", "rb") as f:
            self.blocks = json.loads(zst.decompress(f.read()))
        self._dicts: dict[str, list[bytes]] = {}
        self._dict_idx: dict[str, dict[bytes, int]] = {}

    @property
    def name(self) -> str:
        return self.dir.name

    @property
    def total_count(self) -> int:
        return self.meta["total_count"]

    @property
    def min_ts(self) -> int:
        return self.meta["min_ts"]

    @property
    def max_ts(self) -> int:
        return self.meta["max_ts"]

    def release_cached(self) -> None:
        """Drop lazily-decoded dictionaries (idle-segment reclaim).

        Decoded column blocks live in the byte-budgeted serving cache and
        age out on their own; the per-part dict cache is the only unbounded
        in-object state, so it is what segment reclaim releases."""
        self._dicts.clear()
        self._dict_idx.clear()

    def dict_for(self, tag: str) -> list[bytes]:
        # single dict.get / dict.set ops only (atomic under the GIL):
        # a concurrent release_cached() clear between them just costs a
        # reload, never a KeyError for the in-flight reader
        d = self._dicts.get(tag)
        if d is None:
            path = self.dir / f"tag_{tag}.dict"
            if not path.exists():
                d = []
            else:
                with open(path, "rb") as f:
                    d = enc.decode_strings(f.read())
            self._dicts[tag] = d
        return d

    def has_zone_maps(self) -> bool:
        """True when every block carries the per-column zone maps
        (`zones` block meta); pre-upgrade parts return False and are
        never zone-skipped."""
        return bool(self.blocks) and all("zones" in b for b in self.blocks)

    def block_interval(self, i: int) -> "KeyInterval":
        """The (series, ts) key coverage of block `i` — exact from the
        zone meta's first/last keys when present, else the conservative
        rect bounds (always available)."""
        b = self.blocks[i]
        z = b.get("zones", {})
        lo, hi = z.get("key_lo"), z.get("key_hi")
        if lo is not None and hi is not None:
            return KeyInterval(
                tuple(lo), tuple(hi), b["min_ts"], b["max_ts"]
            )
        return KeyInterval(
            (b["min_series"], b["min_ts"]),
            (b["max_series"], b["max_ts"]),
            b["min_ts"],
            b["max_ts"],
        )

    def dict_index(self, tag: str) -> Mapping[bytes, int]:
        """value -> local code reverse map, cached (the zone planner
        resolves a handful of predicate values per query; rebuilding the
        reverse map over a large dictionary each time is planner-path
        waste).  Same atomicity discipline as dict_for; released by
        release_cached."""
        idx = self._dict_idx.get(tag)
        if idx is None:
            idx = {v: i for i, v in enumerate(self.dict_for(tag))}
            self._dict_idx[tag] = idx
        return idx

    def zone_marked(
        self,
        block_ids: Sequence[int],
        zone_preds: Sequence[tuple[str, np.ndarray]],
    ) -> set[int]:
        """Blocks of `block_ids` whose zone maps prove NO row matches
        the conjunctive predicates (an empty allowed set = dictionary
        miss = every block).  Pure necessity check — dedup safety
        (select_blocks) decides which marked blocks actually skip."""
        out: set[int] = set()
        for i in block_ids:
            zones = self.blocks[i].get("zones")
            if not zones:
                continue
            for col, allowed in zone_preds:
                if not len(allowed):
                    out.add(i)
                    break
                z = zones.get(col)
                if z is None:
                    continue
                lo, hi = z
                j = int(np.searchsorted(allowed, lo))
                if j >= len(allowed) or allowed[j] > hi:
                    out.add(i)
                    break
        return out

    def select_blocks(
        self,
        begin_ms: int,
        end_ms: int,
        series_ids: Optional[np.ndarray] = None,
        zone_preds: Optional[Sequence[tuple[str, np.ndarray]]] = None,
        extra_intervals: Sequence["KeyInterval"] = (),
    ) -> list[int]:
        """Block ids overlapping the half-open [begin, end) time range.

        `series_ids` (sorted int64 candidates from the series index) prunes
        further: rows are part-sorted by series, so a block whose
        [min_series, max_series] contains no candidate cannot match.

        `zone_preds` ([(zone column key, sorted allowed int64 values)])
        prunes on the per-block zone maps: a block whose `zones[col]`
        [lo, hi] contains none of the allowed values cannot match a
        conjunctive eq/in predicate on that column (an EMPTY allowed set
        means "no value of this part can match" — dictionary miss — and
        marks every block).  Blocks without zone meta — pre-upgrade
        parts — are never marked.

        Marking is necessary but NOT sufficient to skip: version dedup
        is global over the gathered sources, so a non-matching block may
        hold the newest version of a (series, ts) row whose older,
        matching copy lives in a kept block — skipping it would
        resurrect the stale row.  A marked block is therefore dropped
        only when its key coverage (`block_interval`) cannot intersect
        any KEPT block of this part nor any of the caller's
        `extra_intervals` (other parts' kept blocks, the memtable).
        Marked blocks may freely overlap EACH OTHER: whichever version
        wins dedup among non-matching rows still fails the predicate.
        Actual skips increment ``blocks_skipped_total{reason=zone}``.
        """
        cands = []
        for i, b in enumerate(self.blocks):
            if not (b["min_ts"] < end_ms and begin_ms <= b["max_ts"]):
                continue
            if series_ids is not None:
                j = int(np.searchsorted(series_ids, b["min_series"]))
                if j >= len(series_ids) or series_ids[j] > b["max_series"]:
                    continue
            cands.append(i)
        if not zone_preds:
            return cands

        prunable = self.zone_marked(cands, zone_preds)
        kept_intervals = [
            self.block_interval(i) for i in cands if i not in prunable
        ]
        kept_intervals.extend(extra_intervals)
        return self.finalize_zone_skip(cands, prunable, kept_intervals)

    def finalize_zone_skip(
        self,
        cands: Sequence[int],
        marked: set[int],
        kept_intervals: Sequence["KeyInterval"],
    ) -> list[int]:
        """The dedup-safety drop (see select_blocks): marked blocks skip
        only when overlap-free against every kept interval.  Split out
        so the shard planner (models/measure) can reuse its pre-pass's
        candidate/marked sets instead of recomputing selection per
        part.  Increments ``blocks_skipped_total{reason=zone}``."""
        out = []
        zone_skipped = 0
        for i in cands:
            if i in marked:
                iv = self.block_interval(i)
                if not any(iv.intersects(k) for k in kept_intervals):
                    zone_skipped += 1
                    continue
            out.append(i)
        if zone_skipped:
            from banyandb_tpu.obs.metrics import global_meter

            global_meter().counter_add(
                "blocks_skipped",
                float(zone_skipped),
                labels={"reason": "zone"},
            )
        return out

    def read(
        self,
        block_ids: Sequence[int],
        *,
        tags: Iterable[str] = (),
        fields: Iterable[str] = (),
        want_payload: bool = False,
        cached: bool = True,
        narrow_codes: bool = False,
    ) -> ColumnData:
        """Decode the selected blocks' columns into host arrays.

        Served through the process serving cache
        (banyand/internal/storage/cache.go:125 analog): parts are
        immutable, so (part_dir, blocks, columns) fully identifies the
        decoded result.  Callers must not mutate returned arrays.
        One-shot bulk readers (merge, migration, sync) pass cached=False
        so their full-part sweeps don't evict the query working set.

        ``narrow_codes=True`` (the device-decode gather path,
        storage/encoded.py) keeps tag code columns at their STORED
        narrow width (i8/i16/i32) instead of widening to i32 — the
        widen + dictionary remap then run on device as the first stage
        of the plan kernel (ops.decode).  Code VALUES are identical
        either way; only the dtype differs.
        """
        from banyandb_tpu.storage.cache import global_cache

        key = (
            "part_read",
            str(self.dir),
            tuple(block_ids),
            tuple(tags),
            tuple(fields),
            bool(want_payload),
            bool(narrow_codes),
        )
        if not cached:
            return self._read_uncached(
                key, block_ids, tags=tags, fields=fields,
                want_payload=want_payload, narrow_codes=narrow_codes,
            )
        return global_cache().get_or_load(
            key,
            lambda: self._read_uncached(
                key, block_ids, tags=tags, fields=fields,
                want_payload=want_payload, narrow_codes=narrow_codes,
            ),
        )

    def _read_uncached(
        self,
        key: tuple,
        block_ids: Sequence[int],
        *,
        tags: Iterable[str] = (),
        fields: Iterable[str] = (),
        want_payload: bool = False,
        narrow_codes: bool = False,
    ) -> ColumnData:
        tags, fields = list(tags), list(fields)
        payloads: Optional[list[bytes]] = (
            [] if (want_payload and self.meta.get("has_payload")) else None
        )
        cols: dict[str, list[np.ndarray]] = {}
        handles: dict[str, object] = {}

        def read_extent(col: str, block: dict) -> bytes:
            off, size = block["extents"][col]
            f = handles.get(col)
            if f is None:
                # bdlint: disable=resource-hygiene -- per-column handle
                # cache for the block loop; closed in the finally below
                f = handles[col] = open(self.dir / _col_file(col), "rb")
            f.seek(off)
            return f.read(size)

        try:
            for bid in block_ids:
                blk = self.blocks[bid]
                cnt = blk["count"]
                cols.setdefault(_TS, []).append(
                    enc.decode_int64(read_extent(_TS, blk), cnt)
                )
                cols.setdefault(_SERIES, []).append(
                    enc.decode_int64(read_extent(_SERIES, blk), cnt)
                )
                cols.setdefault(_VERSIONS, []).append(
                    enc.decode_int64(read_extent(_VERSIONS, blk), cnt)
                )
                decode_codes = (
                    enc.decode_dict_codes_narrow
                    if narrow_codes
                    else enc.decode_dict_codes
                )
                for t in tags:
                    cols.setdefault(f"tag_{t}", []).append(
                        decode_codes(read_extent(f"tag_{t}", blk), cnt)
                    )
                for fl in fields:
                    cols.setdefault(f"field_{fl}", []).append(
                        enc.decode_float64(read_extent(f"field_{fl}", blk), cnt)
                    )
                if payloads is not None:
                    payloads.extend(
                        enc.decode_strings(read_extent("payload", blk))
                    )
        finally:
            for f in handles.values():
                f.close()

        def cat(key: str, dtype) -> np.ndarray:
            parts = cols.get(key, [])
            if not parts:
                return np.zeros(0, dtype=dtype)
            return np.concatenate(parts).astype(dtype, copy=False)

        def cat_codes(t: str) -> np.ndarray:
            if not narrow_codes:
                return cat(f"tag_{t}", np.int32)
            # keep the widest stored width across the selected blocks
            # (per-block downcast can differ within one part)
            parts = cols.get(f"tag_{t}", [])
            if not parts:
                return np.zeros(0, dtype=np.int8)
            return np.concatenate(parts)

        return ColumnData(
            ts=cat(_TS, np.int64),
            series=cat(_SERIES, np.int64),
            version=cat(_VERSIONS, np.int64),
            tags={t: cat_codes(t) for t in tags},
            fields={fl: cat(f"field_{fl}", np.float64) for fl in fields},
            dicts={t: self.dict_for(t) for t in tags},
            payloads=payloads,
            cache_key=key,
        )
