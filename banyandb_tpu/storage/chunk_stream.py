"""Prefetchable chunk/source streams: the host half of the gather/compute
pipeline.

The cold read path is a chain of host stages (disk read -> block decode ->
series filter -> remap -> pad -> H2D transfer) feeding device kernel
execution.  Run strictly serially, the device idles during every decode
and the host idles during every kernel.  This module provides the two
overlap primitives the query layers build on:

- ``prefetched(thunks)``: evaluate thunks IN ORDER on one background
  thread, a bounded ``depth`` ahead of the consumer — while the consumer
  processes item *k* (e.g. the device executes chunk *k*), the worker
  decodes item *k+1*.  Order, and therefore every downstream
  concatenation/accumulation, is identical to the serial loop, which is
  what makes pipelined and serial results byte-identical.
- ``parallel_map(thunks, workers)``: order-preserving concurrent map for
  INDEPENDENT units (per-node source gathers in the mesh plane) where
  pipelining alone leaves workers idle.

``BYDB_PIPELINE=0`` forces the strict-serial fallback everywhere (the
flag is read per call so tests and operators can flip it live), and a
thunk that raises mid-stream re-raises the original exception at the
consumer exactly where the serial loop would have.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Callable, Iterator, Sequence

from banyandb_tpu.utils.envflag import env_flag, env_int


def pipeline_enabled() -> bool:
    """Strict-serial fallback flag; default on."""
    return env_flag("BYDB_PIPELINE", default=True)


def default_depth() -> int:
    return max(1, env_int("BYDB_PREFETCH_DEPTH", 2))


class PrefetchIterator:
    """Evaluate ``thunks`` in order on ONE background thread, ``depth``
    items ahead of the consumer.

    Single-worker by design: evaluation order is the list order, so any
    order-sensitive consumer (concatenation, f64 accumulation) sees
    exactly the serial sequence.  A thunk exception is delivered to the
    consumer at that position and ends the stream; ``close()`` stops the
    worker early (the consumer broke out of the loop)."""

    _DONE = object()

    def __init__(
        self,
        thunks: Sequence[Callable[[], object]],
        depth: int = 2,
        name: str = "bydb-prefetch",
    ):
        self._q: queue.Queue = queue.Queue(maxsize=max(int(depth), 1))
        self._stop = threading.Event()
        self._thunks = list(thunks)
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        for t in self._thunks:
            if self._stop.is_set():
                return
            try:
                item = (None, t())
            except BaseException as e:  # noqa: BLE001 — delivered to consumer
                self._put((e, None))
                return
            if not self._put(item):
                return
        self._put((None, self._DONE))

    def _put(self, item) -> bool:
        # bounded-blocking put that still honors close(): the consumer
        # may stop reading mid-stream, and the worker must not wedge on
        # a full queue forever
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def __iter__(self) -> "PrefetchIterator":
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        exc, value = self._q.get()
        if exc is not None:
            self.close()
            raise exc
        if value is self._DONE:
            self._stop.set()
            raise StopIteration
        return value

    def close(self) -> None:
        """Stop the worker (early consumer exit / error)."""
        self._stop.set()
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=10)


def prefetched(
    thunks: Sequence[Callable[[], object]],
    depth: int | None = None,
    enabled: bool | None = None,
    name: str = "bydb-prefetch",
) -> Iterator:
    """Yield ``t()`` for each thunk in order, prefetching in the
    background when pipelining is on and there is more than one thunk;
    plain serial evaluation otherwise (no thread for the common
    single-source case)."""
    thunks = list(thunks)
    if enabled is None:
        enabled = pipeline_enabled()
    if not enabled or len(thunks) <= 1:
        for t in thunks:
            yield t()
        return
    it = PrefetchIterator(thunks, depth=depth or default_depth(), name=name)
    try:
        yield from it
    finally:
        it.close()


def parallel_map(
    thunks: Sequence[Callable[[], object]],
    workers: int | None = None,
    enabled: bool | None = None,
) -> list:
    """Evaluate independent thunks concurrently, results in list order.

    For units with no shared mutable state between them (per-node source
    gathers); falls back to the serial loop under ``BYDB_PIPELINE=0`` or
    when there is nothing to overlap.  The first exception (by position,
    matching the serial loop) propagates after all workers finish."""
    thunks = list(thunks)
    if enabled is None:
        enabled = pipeline_enabled()
    if not enabled or len(thunks) <= 1:
        return [t() for t in thunks]
    from concurrent.futures import ThreadPoolExecutor

    w = workers or min(4, len(thunks))
    with ThreadPoolExecutor(max_workers=w, thread_name_prefix="bydb-pmap") as ex:
        futures = [ex.submit(t) for t in thunks]
        out = []
        first_exc = None
        for f in futures:
            try:
                out.append(f.result())
            except BaseException as e:  # noqa: BLE001 — re-raised below
                if first_exc is None:
                    first_exc = e
                out.append(None)
        if first_exc is not None:
            raise first_exc
        return out
