"""Part merging (the reference's merger loop, banyand/measure/merger.go:39
+ merger_policy.go, rebuilt host-side).

A merge reads the victim parts' full columns, re-sorts by (series, ts),
drops superseded versions (max write-version wins — the same contract the
device dedup applies at query time), re-encodes into one new part, and
swaps the part set under the shard's snapshot lock.  Merged parts make
query-time dedup cheap: within one part, (series, ts) is unique.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from banyandb_tpu.storage.part import ColumnData, Part
from banyandb_tpu.utils import hostops

# Reference merge trigger shape: wait until enough small parts accumulate.
DEFAULT_MIN_MERGE_PARTS = 4
DEFAULT_MAX_PARTS = 8


_RESOURCE_KINDS = ("measure", "stream", "trace")


def resource_key(p: Part) -> tuple[str, str]:
    """(kind, name) identity of a part — parts of different resources (or
    different kinds sharing a name) must never cross-merge."""
    for kind in _RESOURCE_KINDS:
        name = p.meta.get(kind)
        if name:
            return (kind, name)
    return ("", "")


def pick_merge_victims(
    parts: Sequence[Part],
    *,
    min_merge: int = DEFAULT_MIN_MERGE_PARTS,
    max_parts: int = DEFAULT_MAX_PARTS,
) -> list[Part]:
    """Size-tiered selection: when a resource's part count passes
    max_parts, merge its min_merge smallest parts (merger_policy.go
    analog)."""
    by_resource: dict[tuple[str, str], list[Part]] = {}
    for p in parts:
        by_resource.setdefault(resource_key(p), []).append(p)
    for group in by_resource.values():
        if len(group) >= max_parts:
            group.sort(key=lambda p: p.total_count)
            return group[:min_merge]
    return []


def merge_columns(parts: Sequence[Part]) -> tuple[ColumnData, dict]:
    """Read + combine the victims' rows with version dedup.

    Tag sets are unioned (schema evolution: a part written before a tag
    existed contributes the empty value for it).
    """
    all_tags = sorted({t for p in parts for t in p.meta["tags"]})
    all_fields = sorted({f for p in parts for f in p.meta["fields"]})

    ts_l, series_l, ver_l = [], [], []
    codes_l: dict[str, list[np.ndarray]] = {t: [] for t in all_tags}
    fields_l: dict[str, list[np.ndarray]] = {f: [] for f in all_fields}
    merged_dicts: dict[str, dict[bytes, int]] = {t: {} for t in all_tags}

    want_payload = any(p.meta.get("has_payload") for p in parts)
    payloads_l: list[bytes] = []
    for p in parts:
        cols = p.read(
            range(len(p.blocks)),
            tags=[t for t in all_tags if t in p.meta["tags"]],
            fields=[f for f in all_fields if f in p.meta["fields"]],
            want_payload=want_payload,
            cached=False,  # one-shot merge sweep: keep the query working set
        )
        n = cols.ts.size
        if want_payload:
            payloads_l.extend(cols.payloads or [b""] * n)
        ts_l.append(cols.ts)
        series_l.append(cols.series)
        ver_l.append(cols.version)
        for t in all_tags:
            md = merged_dicts[t]
            if t in cols.tags:
                lut = np.empty(max(len(cols.dicts[t]), 1), dtype=np.int32)
                for i, v in enumerate(cols.dicts[t]):
                    lut[i] = md.setdefault(v, len(md))
                codes_l[t].append(
                    lut[cols.tags[t]] if len(cols.dicts[t]) else np.full(n, md.setdefault(b"", len(md)), np.int32)
                )
            else:
                codes_l[t].append(
                    np.full(n, md.setdefault(b"", len(md)), dtype=np.int32)
                )
        for f in all_fields:
            fields_l[f].append(
                cols.fields.get(f, np.zeros(n, dtype=np.float64))
            )

    ts = np.concatenate(ts_l)
    series = np.concatenate(series_l)
    version = np.concatenate(ver_l)
    if want_payload:
        # Stream/trace rows are immutable appends with no version
        # semantics; (series, ts) is NOT unique (spans of one trace in the
        # same millisecond) — dedup here would destroy data.
        keep = np.arange(len(ts))
    else:
        keep = hostops.dedup_max_version(series, ts, version)

    dicts = {
        t: [v for v, _ in sorted(md.items(), key=lambda kv: kv[1])]
        for t, md in merged_dicts.items()
    }
    out = ColumnData(
        ts=ts[keep],
        series=series[keep],
        version=version[keep],
        tags={t: np.concatenate(codes_l[t])[keep] for t in all_tags},
        fields={f: np.concatenate(fields_l[f])[keep] for f in all_fields},
        dicts=dicts,
        payloads=[payloads_l[i] for i in keep] if want_payload else None,
    )
    kind, name = resource_key(parts[0])
    extra_meta = {kind: name} if kind else {}
    return out, extra_meta
