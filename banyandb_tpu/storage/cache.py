"""Serving cache: decoded columns for repeat queries.

Analog of the reference's serving cache
(banyand/internal/storage/cache.go:125), redesigned around this repo's
query pipeline: the expensive host work on the read path is (1) reading
+ decoding part blocks into ColumnData and (2) gathering sources into
one deduplicated global-code chunk for the device.  Both layers cache
here, keyed on immutable identities (part directories never mutate —
merges write NEW part dirs — so entries never go stale; deleted parts
simply age out of the LRU).

One process-global cache with a byte budget (BYDB_SERVING_CACHE_BYTES,
default 256 MiB), LRU eviction, and hit/miss counters that the query
trace spans and /metrics surface.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Callable

import numpy as np

DEFAULT_BUDGET = int(os.environ.get("BYDB_SERVING_CACHE_BYTES", 256 << 20))


def _sizeof(obj) -> int:
    """Approximate retained bytes of cached values (arrays dominate;
    covers numpy and jax arrays via nbytes)."""
    if isinstance(obj, np.ndarray) or hasattr(obj, "nbytes"):
        return int(obj.nbytes)
    if isinstance(obj, dict):
        return 64 + sum(_sizeof(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return 64 + sum(_sizeof(v) for v in obj)
    if isinstance(obj, (bytes, bytearray)):
        return len(obj)
    if hasattr(obj, "__dict__"):
        return 64 + sum(_sizeof(v) for v in vars(obj).values())
    return 64


class ServingCache:
    """LRU byte-budget cache; values must be treated as immutable."""

    def __init__(self, budget_bytes: int = DEFAULT_BUDGET):
        self.budget = budget_bytes
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, tuple[object, int]] = OrderedDict()
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get_or_load(self, key: tuple, loader: Callable[[], object]):
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return hit[0]
            self.misses += 1
        # Load outside the lock (disk reads can be slow); racing loaders
        # compute the same immutable value, last-insert wins harmlessly.
        value = loader()
        size = _sizeof(value)
        if size > self.budget:
            return value  # too large to retain; serve uncached
        with self._lock:
            prev = self._entries.pop(key, None)
            if prev is not None:
                self.bytes -= prev[1]
            self._entries[key] = (value, size)
            self.bytes += size
            while self.bytes > self.budget and self._entries:
                _, (_, evicted) = self._entries.popitem(last=False)
                self.bytes -= evicted
                self.evictions += 1
        return value

    def invalidate_prefix(self, prefix: tuple) -> int:
        """Drop entries whose key starts with `prefix` (rarely needed —
        part identities are immutable — but retention tests use it)."""
        with self._lock:
            doomed = [
                k for k in self._entries if k[: len(prefix)] == prefix
            ]
            for k in doomed:
                _, size = self._entries.pop(k)
                self.bytes -= size
            return len(doomed)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self.bytes,
                "budget": self.budget,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }


_global = ServingCache()

# Device-resident chunk cache (padded jnp arrays keyed by gather identity)
# — its own budget so HBM residency is bounded independently of the host
# cache (default 1 GiB: a deliberate slice of the chip's 16-32 GiB HBM,
# since resident chunks save both decode AND host->device transfer).
DEVICE_BUDGET = int(os.environ.get("BYDB_DEVICE_CACHE_BYTES", 1 << 30))
_device = ServingCache(DEVICE_BUDGET)


def global_cache() -> ServingCache:
    return _global


def device_cache() -> ServingCache:
    return _device


def reset_global_cache(budget_bytes: int = DEFAULT_BUDGET) -> ServingCache:
    """Test hook / server reconfiguration."""
    global _global, _device
    _global = ServingCache(budget_bytes)
    _device = ServingCache(DEVICE_BUDGET)
    return _global
