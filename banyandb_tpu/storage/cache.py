"""Serving cache: decoded columns for repeat queries.

Analog of the reference's serving cache
(banyand/internal/storage/cache.go:125), redesigned around this repo's
query pipeline: the expensive host work on the read path is (1) reading
+ decoding part blocks into ColumnData and (2) gathering sources into
one deduplicated global-code chunk for the device.  Both layers cache
here, keyed on immutable identities (part directories never mutate —
merges write NEW part dirs — so entries never go stale; deleted parts
simply age out of the LRU).

One process-global cache with a byte budget (BYDB_SERVING_CACHE_BYTES,
default 256 MiB), LRU eviction, and hit/miss counters that the query
trace spans and /metrics surface.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from banyandb_tpu.qos import tenancy
from banyandb_tpu.utils.envflag import env_int

DEFAULT_BUDGET = env_int("BYDB_SERVING_CACHE_BYTES", 256 << 20)


def default_cap() -> int:
    """Optional ENTRY capacity on top of the byte budget: the load
    harness showed a 916-entry squeeze churning 18k evictions in 10
    minutes (docs/load_r06.json) — operators size the entry population
    explicitly with BYDB_SERVING_CACHE_CAP / --serving-cache-cap
    (0 = bytes-only).  Read at CONSTRUCTION time, matching the other
    envflag call sites, so a post-import env change or late server flag
    takes effect without re-import (tests/test_serving_cache.py pins)."""
    return env_int("BYDB_SERVING_CACHE_CAP", 0)


def _sizeof(obj) -> int:
    """Approximate retained bytes of cached values (arrays dominate;
    covers numpy and jax arrays via nbytes)."""
    if isinstance(obj, np.ndarray) or hasattr(obj, "nbytes"):
        return int(obj.nbytes)
    if isinstance(obj, dict):
        return 64 + sum(_sizeof(v) for v in obj.values())
    if isinstance(obj, (list, tuple)):
        return 64 + sum(_sizeof(v) for v in obj)
    if isinstance(obj, (bytes, bytearray)):
        return len(obj)
    if hasattr(obj, "__dict__"):
        return 64 + sum(_sizeof(v) for v in vars(obj).values())
    return 64


class ServingCache:
    """LRU byte-budget cache; values must be treated as immutable."""

    def __init__(
        self,
        budget_bytes: int = DEFAULT_BUDGET,
        max_entries: Optional[int] = None,
    ):
        self.budget = budget_bytes
        # entry cap: 0 = unlimited (byte budget only); None inherits the
        # BYDB_SERVING_CACHE_CAP env default, read now (construction)
        self.cap = default_cap() if max_entries is None else int(max_entries)
        self._lock = threading.Lock()
        self._entries: OrderedDict[tuple, tuple[object, int]] = OrderedDict()
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def set_cap(self, max_entries: int) -> None:
        """Reconfigure the entry cap live (server flag); evicts down to
        the new bound immediately."""
        with self._lock:
            self.cap = int(max_entries)
            self._evict_locked()

    def _evict_locked(self) -> None:
        while self._entries and (
            self.bytes > self.budget
            or (self.cap and len(self._entries) > self.cap)
        ):
            _, (_, evicted) = self._entries.popitem(last=False)
            self.bytes -= evicted
            self.evictions += 1

    def get_or_load(self, key: tuple, loader: Callable[[], object]):
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return hit[0]
            self.misses += 1
        # Load outside the lock (disk reads can be slow); racing loaders
        # compute the same immutable value, last-insert wins harmlessly.
        value = loader()
        size = _sizeof(value)
        if size > self.budget:
            return value  # too large to retain; serve uncached
        with self._lock:
            prev = self._entries.pop(key, None)
            if prev is not None:
                self.bytes -= prev[1]
            self._entries[key] = (value, size)
            self.bytes += size
            self._evict_locked()
        return value

    def invalidate_prefix(self, prefix: tuple) -> int:
        """Drop entries whose key starts with `prefix` (rarely needed —
        part identities are immutable — but retention tests use it)."""
        with self._lock:
            doomed = [
                k for k in self._entries if k[: len(prefix)] == prefix
            ]
            for k in doomed:
                _, size = self._entries.pop(k)
                self.bytes -= size
            return len(doomed)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.bytes = 0

    def stats(self) -> dict:
        with self._lock:
            lookups = self.hits + self.misses
            return {
                "entries": len(self._entries),
                "bytes": self.bytes,
                "budget": self.budget,
                "cap": self.cap,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                # eviction churn: evictions per lookup — the r06 squeeze
                # signal (18102 evictions / 76k lookups) as one number
                "churn": round(self.evictions / lookups, 4)
                if lookups
                else 0.0,
            }


_global = ServingCache()

# Device-resident chunk cache (padded jnp arrays keyed by gather identity)
# — its own budget so HBM residency is bounded independently of the host
# cache (default 1 GiB: a deliberate slice of the chip's 16-32 GiB HBM,
# since resident chunks save both decode AND host->device transfer).
# max_entries=0: the serving-cache ENTRY cap (BYDB_SERVING_CACHE_CAP) is
# a host-cache knob and must not silently bound HBM residency too.
DEVICE_BUDGET = env_int("BYDB_DEVICE_CACHE_BYTES", 1 << 30)
_device = ServingCache(DEVICE_BUDGET, max_entries=0)

# Per-tenant serving-cache partitions (docs/robustness.md "Multi-tenant
# QoS"): queries running under a non-default tenant scope (qos/tenancy
# contextvar, bound by the serving roles) read/write their tenant's OWN
# LRU, so one tenant's churn cannot evict another's entries.  The
# default tenant keeps the original process-global instance — untenanted
# deployments are byte-identical to pre-QoS behavior.  Each partition
# gets the tenant's configured budget (qos limits `cache_bytes`) or the
# process default, and the same entry-cap knob.
_partitions: dict[str, ServingCache] = {}
_partitions_lock = threading.Lock()


def _tenant_partition(tenant: str) -> ServingCache:
    part = _partitions.get(tenant)
    if part is None:
        with _partitions_lock:
            part = _partitions.get(tenant)
            if part is None:
                from banyandb_tpu.qos.plane import global_qos

                budget = (
                    global_qos().limits(tenant).cache_bytes or DEFAULT_BUDGET
                )
                part = _partitions[tenant] = ServingCache(budget)
    return part


def global_cache() -> ServingCache:
    tenant = tenancy.current_tenant()
    if tenant == tenancy.DEFAULT_TENANT:
        return _global
    return _tenant_partition(tenant)


def partition_stats() -> dict[str, dict]:
    """Per-tenant partition stats for /metrics (`tenant`-labeled rows);
    the default tenant's cache keeps its original unlabeled series."""
    with _partitions_lock:
        parts = dict(_partitions)
    return {t: c.stats() for t, c in sorted(parts.items())}


def device_cache() -> ServingCache:
    return _device


def reset_global_cache(budget_bytes: int = DEFAULT_BUDGET) -> ServingCache:
    """Test hook / server reconfiguration."""
    global _global, _device
    _global = ServingCache(budget_bytes)
    _device = ServingCache(DEVICE_BUDGET, max_entries=0)
    with _partitions_lock:
        _partitions.clear()
    return _global
