"""Storage substrate: columnar parts, memtables, time-segmented shards,
snapshot MVCC (the reference's banyand/internal/storage analog)."""
