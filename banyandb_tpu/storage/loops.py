"""Background lifecycle loops.

The reference runs four channel-connected goroutine loops per shard
(introducer/flusher/merger/syncer, banyand/measure/tstable.go:250).  The
introducer's role (snapshot epoch ownership) is folded into the shard lock
here; this module provides the periodic driver for the remaining three:

  flush tick   -> memtable -> parts       (flusher.go:28)
  merge tick   -> size-tiered compaction  (merger.go:39)
  retention    -> drop expired segments   (rotation.go retentionTask)
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from banyandb_tpu.storage.tsdb import TSDB


class LifecycleLoops:
    """One daemon thread driving flush/merge/retention for a set of TSDBs."""

    def __init__(
        self,
        tsdbs: Callable[[], list[TSDB]],
        *,
        flush_interval_s: float = 1.0,
        flush_min_rows: int = 1,
        retention_interval_s: float = 60.0,
        clock: Callable[[], float] = time.time,
        extra_tick: Optional[Callable[[], None]] = None,
    ):
        self._tsdbs = tsdbs
        self.flush_interval_s = flush_interval_s
        self.flush_min_rows = flush_min_rows
        self.retention_interval_s = retention_interval_s
        self._clock = clock
        self._extra_tick = extra_tick
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_retention = 0.0

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()  # allow stop() -> start() restart
        self._thread = threading.Thread(
            target=self._run, name="bydb-lifecycle", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None

    def tick(self) -> dict:
        """One round of flush+merge(+retention). Exposed for tests/manual."""
        stats = {"flushed": 0, "merged": 0, "retired": 0}
        now = self._clock()
        for db in self._tsdbs():
            for seg in db.segments:
                for shard in seg.shards:
                    if len(shard.mem) >= self.flush_min_rows:
                        names = shard.flush()
                        stats["flushed"] += len(names or [])
                    while True:
                        merged = shard.merge()
                        if not merged:
                            break
                        stats["merged"] += 1
                # Series/index-mode docs must survive restarts too — the
                # sidx file is the only store for index-mode measures.
                seg.persist_index()
            if now - self._last_retention >= self.retention_interval_s:
                stats["retired"] += len(
                    db.retention_sweep(int(now * 1000))
                )
        if now - self._last_retention >= self.retention_interval_s:
            self._last_retention = now
        if self._extra_tick is not None:
            self._extra_tick()
        return stats

    def _run(self) -> None:
        while not self._stop.wait(self.flush_interval_s):
            try:
                self.tick()
            except Exception:  # pragma: no cover - keep the loop alive
                import logging

                logging.getLogger(__name__).exception("lifecycle tick failed")
