"""Background lifecycle loops.

The reference runs four channel-connected goroutine loops per shard
(introducer/flusher/merger/syncer, banyand/measure/tstable.go:250).  The
introducer's role (snapshot epoch ownership) is folded into the shard
lock here; the remaining stages run as CONCURRENT daemon threads wired
by a queue, so a long merge never delays flushes (and vice versa):

  flusher thread   memtable -> parts; enqueues flushed shards (flusher.go:28)
  merger thread    drains the queue: size-tiered compaction of exactly
                   the shards that grew, plus a periodic full sweep
                   (merger.go:39)
  retention thread retention sweeps + index persistence + engine extras
                   (rotation.go retentionTask)

``tick()`` still runs every stage once synchronously — the test/manual
entry point and the unit of each thread's work.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Optional

from banyandb_tpu.obs import metrics as obs_metrics
from banyandb_tpu.storage.tsdb import TSDB

# per-stage lifecycle latency (flush/merge/merge-sweep/retention/
# rotation), observed in _guard AFTER the stage returns — no instrument
# lock is ever taken while storage locks are held
_H_LIFECYCLE: dict[str, obs_metrics.Histogram] = {
    stage: obs_metrics.global_meter().histogram(
        "lifecycle_stage_ms", {"stage": stage}
    )
    for stage in ("flush", "merge", "merge-sweep", "retention", "rotation")
}


class _RWLock:
    """Tiny readers-writer lock: flush/merge stages run concurrently
    (readers), retention's segment deletion is exclusive (writer) — a
    sweep must never rmtree a segment an in-flight flush/merge is about
    to write into (zombie seg-* dirs resurrected on restart)."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self):
        with self._cond:
            # writer preference: new readers queue behind a waiting
            # writer, or the 1s flusher/merger cadence starves retention
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    def acquire_write(self):
        with self._cond:
            self._writers_waiting += 1
            try:
                while self._writer or self._readers:
                    self._cond.wait()
            finally:
                self._writers_waiting -= 1
            self._writer = True

    def release_write(self):
        with self._cond:
            self._writer = False
            self._cond.notify_all()


class LifecycleLoops:
    """Concurrent stage threads driving flush/merge/retention."""

    def __init__(
        self,
        tsdbs: Callable[[], list[TSDB]],
        *,
        flush_interval_s: float = 1.0,
        flush_min_rows: int = 1,
        retention_interval_s: float = 60.0,
        merge_sweep_interval_s: float = 10.0,
        idle_timeout_s: float = 600.0,
        clock: Callable[[], float] = time.time,
        extra_tick: Optional[Callable[[], None]] = None,
        pre_flush: Optional[Callable[[], None]] = None,
    ):
        self._tsdbs = tsdbs
        self._pre_flush = pre_flush
        self.flush_interval_s = flush_interval_s
        self.flush_min_rows = flush_min_rows
        self.retention_interval_s = retention_interval_s
        self.merge_sweep_interval_s = merge_sweep_interval_s
        self.idle_timeout_s = idle_timeout_s
        self._last_idle_check = 0.0
        self._clock = clock
        self._extra_tick = extra_tick
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._merge_q: "queue.Queue" = queue.Queue()
        self._last_retention = 0.0
        self._rw = _RWLock()

    # -- stage bodies (each also usable synchronously via tick()) -----------
    def flush_stage(self) -> int:
        flushed = 0
        self._rw.acquire_read()
        try:
            if self._pre_flush is not None:
                # ordering hook: e.g. trace sidx ordered keys must publish
                # BEFORE span memtables flush (trace._flush_sidx_first).
                # Inside the read lock: sidx part writes must not
                # interleave with retention's exclusive segment rmtree.
                self._pre_flush()
            for db in self._tsdbs():
                for seg in db.segments:
                    for shard in seg.shards:
                        if len(shard.mem) >= self.flush_min_rows:
                            names = shard.flush()
                            if names:
                                flushed += len(names)
                                self._merge_q.put(shard)
                    # the sidx file is the only store for index-mode
                    # measures: persist at FLUSH cadence (a crash loses at
                    # most one flush interval of docs, not a retention one)
                    seg.persist_index()
        finally:
            self._rw.release_read()
        if self._extra_tick is not None:  # e.g. property-lease GC: same
            # tight cadence the single-thread loop gave it
            self._extra_tick()
        return flushed

    def merge_shard(self, shard) -> int:
        merged = 0
        self._rw.acquire_read()
        try:
            # a queued shard may belong to a segment retention deleted
            # between enqueue and dequeue: merging it would recreate the
            # deleted directory (zombie segment) — skip dead shards
            if not shard.root.exists():
                return 0
            # segments under tier migration are merge-frozen: compaction
            # would rewrite the part names migration uses as resume keys,
            # re-shipping already-installed rows under new names
            from banyandb_tpu.storage.tsdb import MIGRATING_MARKER

            if (shard.root.parent / MIGRATING_MARKER).exists():
                return 0
            while True:
                if not shard.merge():
                    break
                merged += 1
        finally:
            self._rw.release_read()
        return merged

    def merge_sweep(self) -> int:
        merged = 0
        for db in self._tsdbs():
            for seg in db.segments:
                for shard in seg.shards:
                    merged += self.merge_shard(shard)
        return merged

    def retention_stage(self, force: bool = False) -> int:
        retired = 0
        now = self._clock()
        due = force or (now - self._last_retention >= self.retention_interval_s)
        if not due:
            return 0
        # exclusive: segment deletion must not interleave with in-flight
        # flush/merge writes (zombie segment dirs)
        self._rw.acquire_write()
        try:
            for db in self._tsdbs():
                retired += len(db.retention_sweep(int(now * 1000)))
        finally:
            self._rw.release_write()
        self._last_retention = now
        return retired

    def rotation_stage(self) -> int:
        """Pre-create upcoming segments + reclaim idle ones
        (rotation.go:52 startRotationTask body).

        Runs on the retainer thread each pass: ticks are driven by each
        TSDB's write-event high-water mark — NOT wall clock — matching the
        reference (rotation.go Tick fires from write timestamps), so a
        write-idle group stops accreting empty segments.  TSDB.tick
        throttles itself (tick_snap); the idle check fires at most once
        per timeout interval (the 10-minute idleCheckTicker analog)."""
        now = self._clock()
        created = 0
        for db in self._tsdbs():
            if db.tick(db.max_event_ms):
                created += 1
        if self.idle_timeout_s > 0 and (
            now - self._last_idle_check >= self.idle_timeout_s
        ):
            self._last_idle_check = now
            for db in self._tsdbs():
                # no now_s: each TSDB compares against its own clock, the
                # same domain its segments' touch() timestamps come from
                db.close_idle_segments(self.idle_timeout_s)
        return created

    def tick(self) -> dict:
        """One synchronous round of every stage (tests/manual driving)."""
        stats = {"flushed": 0, "merged": 0, "retired": 0}
        stats["flushed"] = self.flush_stage()
        # drain what the flush enqueued, then sweep for anything else
        while True:
            try:
                shard = self._merge_q.get_nowait()
            except queue.Empty:
                break
            stats["merged"] += self.merge_shard(shard)
        stats["merged"] += self.merge_sweep()
        stats["retired"] = self.retention_stage(force=False)
        stats["precreated"] = self.rotation_stage()
        return stats

    # -- threads ------------------------------------------------------------
    def _guard(self, fn: Callable[[], None], name: str) -> None:
        t0 = time.perf_counter()
        try:
            fn()
        except Exception:  # pragma: no cover - keep the loop alive
            import logging

            logging.getLogger(__name__).exception("%s stage failed", name)
        finally:
            h = _H_LIFECYCLE.get(name)
            if h is not None:
                h.observe((time.perf_counter() - t0) * 1000)

    def _flusher(self) -> None:
        while not self._stop.wait(self.flush_interval_s):
            self._guard(self.flush_stage, "flush")

    def _merger(self) -> None:
        last_sweep = 0.0
        while not self._stop.is_set():
            try:
                shard = self._merge_q.get(timeout=self.flush_interval_s)
                self._guard(lambda: self.merge_shard(shard), "merge")
            except queue.Empty:
                pass
            now = self._clock()
            if now - last_sweep >= self.merge_sweep_interval_s:
                last_sweep = now
                self._guard(lambda: self.merge_sweep(), "merge-sweep")

    def _retainer(self) -> None:
        while not self._stop.wait(min(self.retention_interval_s, 5.0)):
            self._guard(lambda: self.retention_stage(False), "retention")
            self._guard(lambda: self.rotation_stage(), "rotation")

    def start(self) -> None:
        if self._threads:
            return
        self._stop.clear()  # allow stop() -> start() restart
        # first retention waits a FULL interval (an immediate first-fire
        # would race fresh test/startup data whose timestamps predate TTL)
        self._last_retention = self._clock()
        for target, name in (
            (self._flusher, "bydb-flusher"),
            (self._merger, "bydb-merger"),
            (self._retainer, "bydb-retention"),
        ):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=30)
        self._threads = []
