"""Phased unit-group run lifecycle.

Analog of the reference's pkg/run group (run.Group with PreRun / Serve /
GracefulStop phases, banyand/pkg/cmdsetup wiring): units register in
dependency order; startup runs PreRun then Serve forward, and ANY
failure (or a stop signal) tears the started units down in reverse with
a bounded grace period — so a half-started process never leaks
listeners or daemon loops.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional

log = logging.getLogger("banyandb.run")


class Unit:
    """One lifecycle participant.  Subclass or wrap callables via
    FuncUnit.  serve() must RETURN after starting background work (the
    group owns the foreground wait)."""

    name = "unit"

    def pre_run(self) -> None:  # validation / directory prep
        pass

    def serve(self) -> None:  # start listeners / daemons, then return
        pass

    def graceful_stop(self) -> None:
        pass


class FuncUnit(Unit):
    def __init__(
        self,
        name: str,
        pre_run: Optional[Callable] = None,
        serve: Optional[Callable] = None,
        stop: Optional[Callable] = None,
    ):
        self.name = name
        self._pre = pre_run
        self._serve = serve
        self._stop = stop

    def pre_run(self) -> None:
        if self._pre:
            self._pre()

    def serve(self) -> None:
        if self._serve:
            self._serve()

    def graceful_stop(self) -> None:
        if self._stop:
            self._stop()


class Group:
    def __init__(self, name: str = "banyandb"):
        self.name = name
        self._units: list[Unit] = []
        self._started: list[Unit] = []
        self._stop_evt = threading.Event()

    def add(self, unit: Unit) -> None:
        self._units.append(unit)

    def start(self) -> None:
        """PreRun then Serve, forward order; on any failure stop every
        unit whose serve() RAN — including the failing one, which may
        have bound listeners before raising (graceful_stop must
        therefore tolerate partial starts) — in reverse, and re-raise."""
        try:
            for u in self._units:
                u.pre_run()
            for u in self._units:
                self._started.append(u)  # before serve: partial starts
                # (a listener bound, then a later bind fails) still unwind
                u.serve()
        except Exception:
            log.exception("startup failed; unwinding started units")
            self.stop()
            raise

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until trigger_stop() (or a signal handler calls it)."""
        return self._stop_evt.wait(timeout)

    def trigger_stop(self) -> None:
        self._stop_evt.set()

    def stop(self) -> None:
        """GracefulStop in reverse start order; a failing unit never
        blocks the remaining teardown."""
        for u in reversed(self._started):
            try:
                u.graceful_stop()
            except Exception:  # noqa: BLE001
                log.exception("graceful_stop failed for %s", u.name)
        self._started.clear()

    def run(self) -> None:
        """start + wait-for-signal + stop (the main() shape)."""
        import signal

        self.start()
        signal.signal(signal.SIGTERM, lambda *a: self.trigger_stop())
        signal.signal(signal.SIGINT, lambda *a: self.trigger_stop())
        self.wait()
        self.stop()
