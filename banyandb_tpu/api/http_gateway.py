"""HTTP/JSON gateway over the wire services.

Analog of the reference's grpc-gateway liaison HTTP tier
(banyand/liaison/http/server.go:105): the google.api.http annotations in
the upstream protos define these routes; requests/responses are the same
proto messages in protobuf-JSON form (google.protobuf.json_format, the
encoding grpc-gateway itself uses).

Routes (base path /api as upstream):
    POST /api/v1/measure/data          MeasureService.Query
    POST /api/v1/measure/topn          MeasureService.TopN
    POST /api/v1/stream/data           StreamService.Query
    POST /api/v1/bydbql/query          BydbQLService.Query
    POST /api/v1/group/schema          GroupRegistryService.Create
    GET  /api/v1/group/schema/{g}      GroupRegistryService.Get
    GET  /api/v1/group/schema/lists    GroupRegistryService.List
    POST /api/v1/measure/schema        MeasureRegistryService.Create
    GET  /api/v1/measure/schema/{g}/{n}   MeasureRegistryService.Get
    GET  /api/v1/measure/schema/lists/{g} MeasureRegistryService.List
    POST /api/v1/stream/schema         StreamRegistryService.Create
    GET  /api/v1/stream/schema/{g}/{n}    StreamRegistryService.Get
    GET  /api/healthz
    GET  /metrics                      Prometheus exposition (obs plane)
    GET  /api/v1/slowlog?limit=N       slow-query flight recorder
    GET  /api/v1/trace/search?group=&name=&where=&order_by=&desc=&limit=&offset=
                                       trace search via BydbQL
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from google.protobuf import json_format

from banyandb_tpu.api import pb


class _GatewayAbort(Exception):
    def __init__(self, code, details: str):
        self.code = code
        self.details = details
        super().__init__(details)


class _HTTPContext:
    """grpc.ServicerContext stand-in for gateway-invoked handlers."""

    def abort(self, code, details):
        raise _GatewayAbort(code, details)


_GRPC_TO_HTTP = {
    "NOT_FOUND": 404,
    "INVALID_ARGUMENT": 400,
    "ALREADY_EXISTS": 409,
    "UNIMPLEMENTED": 501,
    "INTERNAL": 500,
    # load shedding (QoS quota / ServerBusy / DiskFull): the HTTP
    # retryable rejection — clients back off, never silently dropped
    "RESOURCE_EXHAUSTED": 429,
}

# resource kinds -> registry service stems, keyed by their upstream route
# segment (database/v1/rpc.proto google.api.http paths)
_KIND_SERVICES = {
    "measure": "Measure",
    "stream": "Stream",
    "trace": "Trace",
    "property": "Property",
    "index-rule": "IndexRule",
    "index-rule-binding": "IndexRuleBinding",
    "topn-agg": "TopNAggregation",
}


class HttpGateway:
    def __init__(
        self,
        services,
        host: str = "127.0.0.1",
        port: int = 17913,
        auth=None,
        slowlog=None,
    ):
        """auth: optional banyandb_tpu.api.auth.AuthReloader — when set,
        every API route (healthz excepted) requires HTTP Basic credentials
        from the same hot-reloaded users file as the gRPC surface.

        slowlog: optional obs.SlowQueryRecorder — enables
        GET /api/v1/slowlog (the flight recorder's HTTP surface)."""
        self.services = services
        self.auth = auth
        self.slowlog = slowlog
        gateway = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, status: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _check_auth(self) -> bool:
                if gateway.auth is None:
                    return True
                import base64

                hdr = self.headers.get("Authorization", "")
                if hdr.startswith("Basic "):
                    try:
                        user, _, pw = (
                            base64.b64decode(hdr[6:]).decode().partition(":")
                        )
                    except (ValueError, UnicodeDecodeError):
                        user = pw = ""
                    if user and gateway.auth.check(user, pw):
                        return True
                body = json.dumps({"error": "Invalid credentials"}).encode()
                self.send_response(401)
                self.send_header("WWW-Authenticate", 'Basic realm="banyandb"')
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return False

            def _dispatch(self, method: str):
                if not self._check_auth():
                    return
                try:
                    route = gateway._route(method, self.path.rstrip("/"))
                    if route is None:
                        return self._send(404, {"error": "no such route"})
                    handler, req_msg = route
                    if method == "POST":
                        n = int(self.headers.get("Content-Length") or 0)
                        raw = self.rfile.read(n) if n else b"{}"
                        json_format.Parse(raw, req_msg, ignore_unknown_fields=True)
                    resp = handler(req_msg, _HTTPContext())
                    self._send(
                        200,
                        json_format.MessageToDict(
                            resp, preserving_proto_field_name=True
                        ),
                    )
                except _GatewayAbort as e:
                    self._send(
                        _GRPC_TO_HTTP.get(e.code.name, 500), {"error": e.details}
                    )
                except json_format.ParseError as e:
                    self._send(400, {"error": str(e)})
                except Exception as e:  # noqa: BLE001
                    self._send(500, {"error": str(e)})

            def do_POST(self):
                self._dispatch("POST")

            def do_DELETE(self):
                self._dispatch("DELETE")

            def do_GET(self):
                if self.path == "/api/healthz":
                    return self._send(200, {"status": "ok"})
                if self.path == "/metrics":
                    # Prometheus scrape surface: the process-global meter
                    # (stage histograms, rpc, lifecycle, caches)
                    from banyandb_tpu.obs.metrics import global_meter

                    if not self._check_auth():
                        return
                    body = global_meter().prometheus_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path.split("?")[0] == "/api/v1/slowlog":
                    if not self._check_auth():
                        return
                    if gateway.slowlog is None:
                        return self._send(
                            404, {"error": "slow-query recorder not wired"}
                        )
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    limit = None
                    if q.get("limit"):
                        try:
                            limit = int(q["limit"][0])
                        except ValueError:
                            limit = None
                    return self._send(
                        200,
                        {"entries": gateway.slowlog.entries(limit=limit)},
                    )
                if self.path.split("?")[0] == "/api/v1/trace/search":
                    # search params compose into one BydbQL trace query
                    # through the same builder cli.py uses (lazy import:
                    # the server package is fully loaded at request time)
                    if not self._check_auth():
                        return
                    from urllib.parse import parse_qs, urlsplit

                    from banyandb_tpu.cli import trace_search_ql

                    q = parse_qs(urlsplit(self.path).query)

                    def one(k, d=""):
                        return q.get(k, [d])[0]

                    if not one("group") or not one("name"):
                        return self._send(
                            400, {"error": "group and name params required"}
                        )
                    try:
                        limit = int(one("limit", "20"))
                        offset = int(one("offset", "0"))
                    except ValueError:
                        return self._send(
                            400, {"error": "limit/offset must be integers"}
                        )
                    ql = trace_search_ql(
                        one("group"), one("name"),
                        tags=one("tags", "*"),
                        where=q.get("where", []),
                        order_by=one("order_by"),
                        desc=one("desc").lower() in ("1", "true", "yes", "on"),
                        limit=limit, offset=offset,
                        from_ms=int(one("from_ms")) if one("from_ms") else None,
                        to_ms=int(one("to_ms")) if one("to_ms") else None,
                    )
                    try:
                        req = pb.bydbql_query_pb2.QueryRequest(query=ql)
                        resp = gateway.services.bydbql_query(
                            req, _HTTPContext()
                        )
                        return self._send(
                            200,
                            json_format.MessageToDict(
                                resp, preserving_proto_field_name=True
                            ),
                        )
                    except _GatewayAbort as e:
                        return self._send(
                            _GRPC_TO_HTTP.get(e.code.name, 500),
                            {"error": e.details},
                        )
                if self.path in ("/", "/console"):
                    page = gateway._console_page
                    if page is None:
                        return self._send(500, {"error": "console.html missing"})
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html; charset=utf-8")
                    self.send_header("Content-Length", str(len(page)))
                    self.end_headers()
                    self.wfile.write(page)
                    return
                self._dispatch("GET")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_port
        self._thread: threading.Thread | None = None
        # console page read once at startup (missing file -> 500, not a
        # per-request OSError escaping the handler)
        try:
            import os

            with open(
                os.path.join(os.path.dirname(__file__), "console.html"), "rb"
            ) as f:
                self._console_page: bytes | None = f.read()
        except OSError:
            self._console_page = None

        # static route tables (registry handler dicts are built once; the
        # request message is instantiated per request at dispatch time)
        s = services
        rpc = pb.database_rpc_pb2
        self._reg = {
            kind: s._registry_handlers(kind)
            for kind in ("group", "measure", "stream")
        }
        self._post = {
            ("v1", "measure", "data"): (s.measure_query, pb.measure_query_pb2.QueryRequest),
            ("v1", "measure", "topn"): (s.measure_topn, pb.measure_topn_pb2.TopNRequest),
            ("v1", "stream", "data"): (s.stream_query, pb.stream_query_pb2.QueryRequest),
            ("v1", "bydbql", "query"): (s.bydbql_query, pb.bydbql_query_pb2.QueryRequest),
            ("v1", "group", "schema"): (
                self._reg["group"]["Create"].unary_unary,
                rpc.GroupRegistryServiceCreateRequest,
            ),
            ("v1", "measure", "schema"): (
                self._reg["measure"]["Create"].unary_unary,
                rpc.MeasureRegistryServiceCreateRequest,
            ),
            ("v1", "stream", "schema"): (
                self._reg["stream"]["Create"].unary_unary,
                rpc.StreamRegistryServiceCreateRequest,
            ),
        }
        if getattr(s, "property", None) is not None:
            self._post[("v1", "property", "data", "query")] = (
                s.property_query,
                pb.property_rpc_pb2.QueryRequest,
            )
        if getattr(s, "trace", None) is not None:
            self._post[("v1", "trace", "data")] = (
                s.trace_query,
                pb.trace_query_pb2.QueryRequest,
            )
        from banyandb_tpu.api import wire as _wire

        self._reg["trace"] = s._spec_registry_handlers(
            "TraceRegistryService", "trace", "trace",
            _wire.trace_to_internal, _wire.trace_to_pb,
        )
        self._reg["property"] = s._spec_registry_handlers(
            "PropertyRegistryService", "property", "property_schema",
            _wire.property_schema_to_internal, _wire.property_schema_to_pb,
        )
        # spec registries under their upstream route segments
        # (rpc.proto:261 /v1/index-rule, :175 /v1/index-rule-binding,
        # :701 /v1/topn-agg)
        self._reg["index-rule"] = s._spec_registry_handlers(
            "IndexRuleRegistryService", "index_rule", "index_rule",
            _wire.index_rule_to_internal, _wire.index_rule_to_pb,
        )
        self._reg["index-rule-binding"] = s._spec_registry_handlers(
            "IndexRuleBindingRegistryService", "index_rule_binding",
            "index_rule_binding",
            _wire.index_rule_binding_to_internal,
            _wire.index_rule_binding_to_pb,
        )
        self._reg["topn-agg"] = s._spec_registry_handlers(
            "TopNAggregationRegistryService", "top_n_aggregation", "topn",
            _wire.topn_to_internal, _wire.topn_to_pb,
            reg_list="list_topn",
        )
        for seg, svc in (
            ("index-rule", "IndexRule"),
            ("index-rule-binding", "IndexRuleBinding"),
            ("topn-agg", "TopNAggregation"),
        ):
            self._post[("v1", seg, "schema")] = (
                self._reg[seg]["Create"].unary_unary,
                getattr(rpc, f"{svc}RegistryServiceCreateRequest"),
            )
        self._post[("v1", "trace", "schema")] = (
            self._reg["trace"]["Create"].unary_unary,
            rpc.TraceRegistryServiceCreateRequest,
        )
        self._post[("v1", "property", "schema")] = (
            self._reg["property"]["Create"].unary_unary,
            rpc.PropertyRegistryServiceCreateRequest,
        )
        # parameterless GET endpoints (rpc.proto:952 /v1/cluster/state,
        # common/v1/rpc.proto /v1/common/api/version)
        self._get_plain = {
            ("v1", "cluster", "state"): (
                s.get_cluster_state,
                pb.database_rpc_pb2.GetClusterStateRequest,
            ),
            ("v1", "common", "api", "version"): (
                s.get_api_version,
                pb.common_rpc_pb2.GetAPIVersionRequest,
            ),
        }

    # -- routing -----------------------------------------------------------
    def _route(self, method: str, path: str):
        rpc = pb.database_rpc_pb2
        parts = [p for p in path.split("/") if p]
        if not parts or parts[0] != "api":
            return None
        parts = parts[1:]
        if method == "POST":
            hit = self._post.get(tuple(parts))
            return (hit[0], hit[1]()) if hit else None
        if method == "GET":  # read-only endpoints never answer DELETE
            hit = self._get_plain.get(tuple(parts))
            if hit:
                return (hit[0], hit[1]())
        # routes with path params
        if len(parts) == 4 and parts[:3] == ["v1", "group", "schema"]:
            if method == "DELETE":
                return (
                    self._reg["group"]["Delete"].unary_unary,
                    rpc.GroupRegistryServiceDeleteRequest(group=parts[3]),
                )
            if parts[3] == "lists":
                return (
                    self._reg["group"]["List"].unary_unary,
                    rpc.GroupRegistryServiceListRequest(),
                )
            return (
                self._reg["group"]["Get"].unary_unary,
                rpc.GroupRegistryServiceGetRequest(group=parts[3]),
            )
        for kind, svc in _KIND_SERVICES.items():
            if len(parts) == 5 and parts[:3] == ["v1", kind, "schema"]:
                P = f"{svc}RegistryService"
                if method == "DELETE":
                    req = getattr(rpc, f"{P}DeleteRequest")()
                    req.metadata.group, req.metadata.name = parts[3], parts[4]
                    return (self._reg[kind]["Delete"].unary_unary, req)
                if parts[3] == "lists":
                    return (
                        self._reg[kind]["List"].unary_unary,
                        getattr(rpc, f"{P}ListRequest")(group=parts[4]),
                    )
                req = getattr(rpc, f"{P}GetRequest")()
                req.metadata.group, req.metadata.name = parts[3], parts[4]
                return (self._reg[kind]["Get"].unary_unary, req)
        return None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        # shutdown() blocks on serve_forever's loop flag; calling it when
        # start() never ran would deadlock (partial StandaloneServer start)
        if self._thread is not None:
            self.httpd.shutdown()
        self.httpd.server_close()
