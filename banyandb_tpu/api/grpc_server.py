"""Wire-compatible gRPC server: the reference's proto services.

Serves the upstream service surface (banyand/liaison/grpc/server.go:448
registers the same set) on real protobuf so any client generated from
the BanyanDB protos can connect:

- banyandb.measure.v1.MeasureService      Query / Write (bidi) / TopN
- banyandb.stream.v1.StreamService        Query / Write (bidi)
- banyandb.database.v1.GroupRegistryService    CRUD
- banyandb.database.v1.MeasureRegistryService  CRUD
- banyandb.database.v1.StreamRegistryService   CRUD
- banyandb.database.v1.SnapshotService         Snapshot
- banyandb.bydbql.v1.BydbQLService             Query

grpc_tools is not in this image, so services are wired with
grpc.method_handlers_generic_handler + the generated message classes —
the wire behavior is identical to codegen'd servicers.
"""

from __future__ import annotations

import logging
import time
from concurrent import futures
from typing import Callable

import grpc

from banyandb_tpu.api import pb, wire

log = logging.getLogger("banyandb.grpc")


def _unary(fn: Callable, req_cls):
    return grpc.unary_unary_rpc_method_handler(
        fn,
        request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString(),
    )


def _stream_stream(fn: Callable, req_cls):
    return grpc.stream_stream_rpc_method_handler(
        fn,
        request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString(),
    )


def _unary_stream(fn: Callable, req_cls):
    return grpc.unary_stream_rpc_method_handler(
        fn,
        request_deserializer=req_cls.FromString,
        response_serializer=lambda m: m.SerializeToString(),
    )


def _abort(context, e: Exception):
    # lazy boundary (layering): the shed exceptions live in admin/
    from banyandb_tpu.admin.diskmonitor import DiskFull
    from banyandb_tpu.admin.protector import ServerBusy

    if isinstance(e, (ServerBusy, DiskFull)):
        # load shedding (QoS quota / memory gate / disk watermark) is an
        # explicit RETRYABLE rejection on the proto wire — the
        # ErrServerBusy contract, never a silent drop or a plain 500
        context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
    if isinstance(e, KeyError):
        context.abort(grpc.StatusCode.NOT_FOUND, str(e))
    if isinstance(e, NotImplementedError):
        context.abort(grpc.StatusCode.UNIMPLEMENTED, str(e))
    if isinstance(e, FileExistsError):
        context.abort(grpc.StatusCode.ALREADY_EXISTS, str(e))
    if isinstance(e, (ValueError, TypeError)):
        context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
    log.exception("internal error")
    context.abort(grpc.StatusCode.INTERNAL, str(e))


API_VERSION = "0.10"  # upstream api/proto/banyandb/version.go:22
API_REVISION = "banyandb-tpu"

# SchemaBarrierService key kinds (schema/v1/barrier.proto:46) -> registry kinds
_BARRIER_KINDS = {
    "measure": "measure",
    "stream": "stream",
    "trace": "trace",
    "property": "property_schema",
    "index_rule": "index_rule",
    "index_rule_binding": "index_rule_binding",
    "group": "group",
    "top_n_aggregation": "topn",
}


class RegistryBarrier:
    """Standalone SchemaBarrierService backend: the only 'cluster member'
    is this process's registry (barrier.proto:30 — the standalone
    implementation).  Cluster deployments pass a liaison-backed object
    with the same three methods instead."""

    def __init__(self, registry, node_name: str = "standalone"):
        self.registry = registry
        self.node = node_name

    def _poll(self, deadline: float, check):
        import time as _time

        while True:
            laggards = check()
            if not laggards or _time.monotonic() >= deadline:
                return (not laggards), laggards
            _time.sleep(0.02)

    def await_revision(self, min_revision: int, timeout_s: float):
        import time as _time

        def check():
            rev = self.registry.revision
            if rev >= min_revision:
                return []
            return [{"node": self.node, "current_mod_revision": rev}]

        return self._poll(_time.monotonic() + timeout_s, check)

    def await_applied(self, keys, min_revisions, timeout_s: float):
        import time as _time

        def check():
            missing = []
            for (kind, group, name), min_rev in zip(keys, min_revisions):
                rkind = _BARRIER_KINDS.get(kind)
                if rkind is None:
                    raise ValueError(f"unknown schema kind {kind!r}")
                key = name if rkind == "group" else f"{group}/{name}"
                st = self.registry.stored_object_hash(rkind, key)
                present = st["hash"] is not None
                # rev 0 means "just present"; local revs reset on restart,
                # so a present object always satisfies rev 0
                if not present or (min_rev and st["rev"] < min_rev):
                    missing.append((kind, group, name))
            if missing:
                return [
                    {
                        "node": self.node,
                        "current_mod_revision": self.registry.revision,
                        "missing_keys": missing,
                    }
                ]
            return []

        return self._poll(_time.monotonic() + timeout_s, check)

    def await_deleted(self, keys, timeout_s: float):
        import time as _time

        def check():
            present = []
            for kind, group, name in keys:
                rkind = _BARRIER_KINDS.get(kind)
                if rkind is None:
                    raise ValueError(f"unknown schema kind {kind!r}")
                key = name if rkind == "group" else f"{group}/{name}"
                if self.registry.stored_object_hash(rkind, key)["hash"] is not None:
                    present.append((kind, group, name))
            if present:
                return [
                    {
                        "node": self.node,
                        "current_mod_revision": self.registry.revision,
                        "still_present_keys": present,
                    }
                ]
            return []

        return self._poll(_time.monotonic() + timeout_s, check)


class WireServices:
    """Service handlers bound to the engines (StandaloneServer-compatible:
    any object exposing .registry/.measure/.stream works)."""

    def __init__(
        self,
        registry,
        measure_engine,
        stream_engine,
        bydbql_fn=None,
        property_engine=None,
        trace_engine=None,
        node_info: dict | None = None,
        cluster_view_fn=None,
        barrier=None,
        schema_store=None,
        watch_stream_cap: int = 4,
    ):
        self.registry = registry
        self.measure = measure_engine
        self.stream = stream_engine
        self.bydbql_fn = bydbql_fn
        self.property = property_engine
        self.trace = trace_engine
        # NodeQuery/ClusterState context: standalone defaults report this
        # single node as the whole (healthy) cluster
        self.node_info = node_info or {"name": "standalone", "roles": ("data", "liaison")}
        self.cluster_view_fn = cluster_view_fn or (
            lambda: {
                "tire2": {
                    "registered": [dict(self.node_info)],
                    "active": [self.node_info.get("name", "standalone")],
                    "evictable": [],
                }
            }
        )
        self.schema_store = schema_store
        self.barrier = barrier or RegistryBarrier(registry)
        # Barrier RPCs hold a worker thread for their whole wait; cap the
        # concurrent waiters so they can never exhaust the server pool and
        # starve the very writes that would satisfy them.
        import threading as _threading

        self._barrier_slots = _threading.BoundedSemaphore(4)
        # WatchSchemas streams hold a worker for their whole life; cap
        # them so watchers can never exhaust the server pool (WireServer
        # passes a cap proportional to its max_workers)
        self._watch_slots = _threading.BoundedSemaphore(watch_stream_cap)

    @staticmethod
    def _one_group(ireq) -> str:
        """Raises ValueError (-> INVALID_ARGUMENT) rather than aborting so
        the surrounding except/_abort flow stays single-shot."""
        if not ireq.groups:
            raise ValueError("groups must be non-empty")
        if len(ireq.groups) > 1:
            raise ValueError("multi-group queries are not supported yet")
        return ireq.groups[0]

    def _resolve_order(self, group: str, ireq):
        """order_by from the wire names an INDEX RULE; resolve it to the
        rule's tag (falling back to direct tag naming when no rule
        matches — both forms order correctly)."""
        if not ireq.order_by_tag:
            return ireq
        import dataclasses

        for r in self.registry.list_index_rules(group):
            if r.name == ireq.order_by_tag and r.tags:
                return dataclasses.replace(ireq, order_by_tag=r.tags[0])
        return ireq

    @staticmethod
    def _admit(group: str):
        """Per-tenant weighted query admission on the proto wire
        (docs/robustness.md "Multi-tenant QoS"); a shed maps to
        RESOURCE_EXHAUSTED in _abort.  Returns a context manager that
        also binds the tenant scope (serving-cache partitions)."""
        import contextlib

        from banyandb_tpu.qos import tenant_scope
        from banyandb_tpu.qos.plane import global_qos

        adm = global_qos().admit_query(group)

        @contextlib.contextmanager
        def scoped():
            with adm, tenant_scope(adm.tenant):
                yield adm

        return scoped()

    # -- MeasureService ----------------------------------------------------
    def measure_query(self, req, context):
        try:
            ireq = wire.measure_query_to_internal(req)
            if len(ireq.groups) > 1:
                return self._measure_query_multi_group(ireq)
            group = self._one_group(ireq)
            m = self.registry.get_measure(group, ireq.name)
            # projection names are schema errors, not silent drops
            # (ref WantErr project_non_existent_{tag,field} cases)
            for t in ireq.tag_projection:
                m.tag(t)
            for f in ireq.field_projection:
                m.field(f)
            ireq = self._resolve_order(group, ireq)
            with self._admit(group):
                res = self.measure.query(ireq)
            return wire.measure_result_to_pb(m, ireq, res)
        except Exception as e:  # noqa: BLE001 - mapped to gRPC status
            _abort(context, e)

    def _measure_query_multi_group(self, ireq):
        """Cross-group union (ref pkg/query/logical/measure/
        cross_group_merge.go): run the query per group against that
        group's OWN schema revision (tag/field sets may differ across
        groups — that is the feature's point), merge data points by
        timestamp in the requested time order."""
        import dataclasses as _dc

        merged = None
        for group in ireq.groups:
            m = self.registry.get_measure(group, ireq.name)
            known_tags = {t.name for t in m.tags}
            known_fields = {f.name for f in m.fields}
            sub = _dc.replace(
                ireq,
                groups=(group,),
                offset=0,  # offset applies ONCE, on the merged stream
                # the merged page [offset, offset+limit) may come wholly
                # from one group, so every sub-query must return
                # offset+limit rows — the original limit alone breaks
                # pages past the first
                limit=(ireq.offset or 0) + (ireq.limit or 100),
                tag_projection=tuple(
                    t for t in ireq.tag_projection if t in known_tags
                ),
                tag_families_projection=tuple(
                    (fam, tuple(t for t in tags if t in known_tags))
                    for fam, tags in ireq.tag_families_projection
                ),
                field_projection=tuple(
                    f for f in ireq.field_projection if f in known_fields
                ),
            )
            sub = self._resolve_order(group, sub)
            out = wire.measure_result_to_pb(
                m, sub, self.measure.query(sub)
            )
            # union projection: rows from a group lacking a projected
            # tag/field carry an explicit null in projection position
            # (ref cross-group merge emits the merged schema)
            for dp in out.data_points:
                for (fam_name, fam_tags), fam in zip(
                    ireq.tag_families_projection
                    or (("default", ireq.tag_projection),),
                    dp.tag_families,
                ):
                    have = [t.key for t in fam.tags]
                    for pos, tname in enumerate(fam_tags):
                        if tname not in have:
                            tag = pb.model_query_pb2.Tag(key=tname)
                            tag.value.null = 0
                            fam.tags.insert(pos, tag)
                            have.insert(pos, tname)
                have_f = [f.name for f in dp.fields]
                for pos, fname in enumerate(ireq.field_projection):
                    if fname not in have_f:
                        fv = pb.measure_query_pb2.DataPoint.Field(name=fname)
                        fv.value.null = 0
                        dp.fields.insert(pos, fv)
                        have_f.insert(pos, fname)
            if merged is None:
                merged = out
            else:
                merged.data_points.extend(out.data_points)
        desc = ireq.order_by_ts == "desc"
        pts = sorted(
            merged.data_points,
            key=lambda dp: (dp.timestamp.seconds, dp.timestamp.nanos),
            reverse=desc,
        )
        off = ireq.offset or 0
        del merged.data_points[:]
        merged.data_points.extend(pts[off : off + (ireq.limit or 100)])
        return merged

    _WRITE_BATCH = 256

    def measure_write(self, request_iterator, context):
        """Bidi stream with write batching: consecutive requests for the
        same measure accumulate into columnar batches committed through
        the bulk path (write_points_bulk), preserving the reference's
        one-WriteResponse-per-WriteRequest contract — responses emit
        after their batch commits.  A 1ms idle flush keeps strict
        ping-pong clients (that wait for each response) from
        deadlocking against the batcher; a failed bulk batch replays
        point-by-point so per-point statuses stay accurate."""
        import queue as _queue
        import threading as _threading

        from banyandb_tpu.api import model as im

        pending: list = []  # [(wreq, decoded point), ...] one-measure run
        cur: tuple | None = None  # (group, name) of the pending run

        def _resp(wreq, status):
            r = pb.measure_write_pb2.WriteResponse(message_id=wreq.message_id)
            r.status = status
            r.metadata.CopyFrom(wreq.metadata)
            return r

        def commit():
            nonlocal pending, cur
            if not pending:
                return []
            group, name = cur
            from banyandb_tpu.admin.diskmonitor import DiskFull
            from banyandb_tpu.admin.protector import ServerBusy
            from banyandb_tpu.qos.plane import global_qos

            try:
                # per-tenant ingest quota (QoS): the whole batch is one
                # admission charge; over-quota rejects the batch with
                # the shed-class wire status below — explicit and
                # retryable, never a silent drop
                global_qos().admit_write(group, len(pending))
                self.measure.write_points_bulk(
                    im.WriteRequest(
                        group, name, tuple(p for _, p in pending)
                    )
                )
                statuses = ["STATUS_SUCCEED"] * len(pending)
            except (ServerBusy, DiskFull):
                # the wire enum's only shed-class value (model/v1
                # Status): clients treat it as back-off-and-retry
                statuses = ["STATUS_DISK_FULL"] * len(pending)
            except Exception:  # noqa: BLE001 — replay for per-point status
                statuses = []
                for _, p in pending:
                    try:
                        self.measure.write(im.WriteRequest(group, name, (p,)))
                        statuses.append("STATUS_SUCCEED")
                    except KeyError:
                        statuses.append("STATUS_NOT_FOUND")
                    except (ServerBusy, DiskFull):
                        statuses.append("STATUS_DISK_FULL")
                    except Exception:  # noqa: BLE001
                        log.exception("measure write failed")
                        statuses.append("STATUS_INTERNAL_ERROR")
            out = [_resp(w, st) for (w, _), st in zip(pending, statuses)]
            pending, cur = [], None
            return out

        # Bounded queue restores HTTP/2 backpressure: the feeder blocks
        # once the batcher falls behind, so a client that never reads
        # responses cannot grow server memory with its whole stream.
        # `dead` unblocks the feeder if the response generator is torn
        # down early (client cancel) — a plain blocking put would leak
        # the thread.
        q: _queue.Queue = _queue.Queue(maxsize=2 * self._WRITE_BATCH)
        _DONE = object()
        dead = _threading.Event()

        def _put(item) -> bool:
            while not dead.is_set():
                try:
                    q.put(item, timeout=0.25)
                    return True
                except _queue.Full:
                    continue
            return False

        def feeder():
            try:
                for r in request_iterator:
                    if not _put(r):
                        return
            except Exception:  # noqa: BLE001 — stream cancel/reset
                pass
            finally:
                _put(_DONE)

        _threading.Thread(target=feeder, daemon=True).start()
        try:
            while True:
                try:
                    item = q.get(timeout=0.001 if pending else None)
                except _queue.Empty:
                    yield from commit()  # idle: client is waiting on us
                    continue
                if item is _DONE:
                    yield from commit()
                    return
                wreq = item
                key = (wreq.metadata.group, wreq.metadata.name)
                if cur is not None and (
                    key != cur or len(pending) >= self._WRITE_BATCH
                ):
                    yield from commit()
                try:
                    m = self.registry.get_measure(*key)
                    point = wire.write_request_to_point(m, wreq)
                except KeyError:
                    yield from commit()  # keep response ordering
                    yield _resp(wreq, "STATUS_NOT_FOUND")
                    continue
                except Exception:  # noqa: BLE001
                    yield from commit()
                    log.exception("measure write decode failed")
                    yield _resp(wreq, "STATUS_INTERNAL_ERROR")
                    continue
                cur = key
                pending.append((wreq, point))
        finally:
            dead.set()

    def measure_topn(self, req, context):
        try:
            from banyandb_tpu.api.model import TimeRange
            from banyandb_tpu.models import topn as topn_mod

            if not req.groups:
                raise ValueError("groups must be non-empty")
            # multi-group TopN (reference cross-group rank merge): the
            # rule must exist in EVERY named group; per-group ranked
            # lists merge distinct-best per entity below
            groups = list(req.groups)
            group = groups[0]
            rules_by_group = {}
            for g in groups:
                r = next(
                    (
                        r
                        for r in self.registry.list_topn(g)
                        if r.name == req.name
                    ),
                    None,
                )
                if r is None:
                    raise KeyError(
                        f"topn rule {req.name} not found in group {g}"
                    )
                rules_by_group[g] = r
            rule = rules_by_group[group]
            # ranked entities display the SOURCE measure's entity tuple
            # (reference TopNList item shape); conditions filter over
            # entity + rule group-by dims inside query_topn
            src_m = self.registry.get_measure(
                rule.source_group or group, rule.source_measure
            )
            group_tags = tuple(src_m.entity.tag_names)
            conds = []
            for c in req.conditions:
                if c.op not in wire._COND_OP:
                    # an unknown wire op must be INVALID_ARGUMENT, never
                    # silently filtered with eq semantics
                    raise ValueError(
                        f"unknown TopN condition op {c.op} on {c.name!r}"
                    )
                op = wire._COND_OP[c.op]
                if op not in ("eq", "ne", "in", "not_in"):
                    raise ValueError(f"TopN condition op {op} not supported")
                conds.append((c.name, op, wire.tag_value_to_py(c.value)))

            begin = wire.ts_to_millis(req.time_range.begin)
            end = wire.ts_to_millis(req.time_range.end)
            direction = "asc" if req.field_value_sort == 2 else "desc"
            agg = wire._AGG_FN.get(req.agg, "sum")
            n_top = req.top_n or 10

            # degraded markers accumulate across EVERY group's scatter
            # (a down worker in any leg makes the merged ranking partial)
            degraded_nodes: set = set()
            any_degraded = [False]

            def ranked_for(g: str) -> list:
                if hasattr(self.measure, "topn_scatter"):
                    # worker-pool facade: result-measure rows are worker-
                    # local, so TopN scatters per-node ranked lists and
                    # concat re-ranks (never a shard-routed query_measure,
                    # which would silently miss rows)
                    scatter = self.measure.topn_scatter({
                        "group": g,
                        "name": req.name,
                        "time_range": [begin, end],
                        "n": n_top,
                        "direction": direction,
                        "agg": agg,
                        "conditions": [list(c) for c in conds],
                    })
                    if scatter.get("degraded"):
                        any_degraded[0] = True
                        degraded_nodes.update(
                            scatter.get("unavailable_nodes", [])
                        )
                    return [
                        (tuple(it["entity"]), it["value"])
                        for it in scatter["items"]
                    ]
                return topn_mod.query_topn(
                    self.measure,
                    g,
                    req.name,
                    TimeRange(begin, end),
                    n=n_top,
                    direction=direction,
                    agg=agg,
                    conditions=tuple(conds),
                )

            if len(groups) == 1:
                ranked = ranked_for(group)
            else:
                # cross-group rank merge: distinct-best per displayed
                # entity across groups, then one re-rank with the same
                # (value, entity) tie-break the per-group path uses
                best: dict[tuple, float] = {}
                for g in groups:
                    for entity, value in ranked_for(g):
                        cur = best.get(entity)
                        if cur is None or (
                            value > cur
                            if direction == "desc"
                            else value < cur
                        ):
                            best[entity] = value
                ranked = sorted(
                    best.items(),
                    key=lambda kv: (kv[1], kv[0]),
                    reverse=(direction == "desc"),
                )[:n_top]
            # the output value is typed like the SOURCE measure's field
            # (int64 aggregation stays integral, mean truncates)
            as_int = False
            try:
                as_int = src_m.field(rule.field_name).type.name == "INT"
            except KeyError:
                pass
            out = pb.measure_topn_pb2.TopNResponse()
            lst = out.lists.add()
            for entity, value in ranked:
                item = lst.items.add()
                for name, v in zip(group_tags, entity):
                    t = item.entity.add(key=name)
                    # the empty value renders as null (a row written
                    # without the tag)
                    t.value.CopyFrom(wire.py_to_tag_value(v or None))
                item.value.CopyFrom(
                    wire.py_to_field_value(
                        int(value) if as_int else float(value)
                    )
                )
            if any_degraded[0]:
                # a down worker leg in ANY group makes the ranking
                # partial: surface it in-band like every degraded query
                from types import SimpleNamespace

                wire.fill_degraded(out, SimpleNamespace(
                    degraded=True,
                    unavailable_nodes=sorted(degraded_nodes),
                ))
            return out
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    # -- StreamService -----------------------------------------------------
    def stream_query(self, req, context):
        try:
            ireq = wire.stream_query_to_internal(req)
            group = self._one_group(ireq)
            ireq = self._resolve_order(group, ireq)
            with self._admit(group):
                res = self.stream.query(ireq)
            return wire.stream_result_to_pb(res)
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def stream_write(self, request_iterator, context):
        from banyandb_tpu.admin.diskmonitor import DiskFull
        from banyandb_tpu.admin.protector import ServerBusy
        from banyandb_tpu.qos.plane import global_qos

        for wreq in request_iterator:
            resp = pb.stream_write_pb2.WriteResponse(message_id=wreq.message_id)
            try:
                s = self.registry.get_stream(
                    wreq.metadata.group, wreq.metadata.name
                )
                el = wire.element_value_from_pb(s, wreq)
                global_qos().admit_write(wreq.metadata.group, 1)
                self.stream.write(wreq.metadata.group, wreq.metadata.name, [el])
                resp.status = "STATUS_SUCCEED"
            except KeyError:
                resp.status = "STATUS_NOT_FOUND"
            except (ServerBusy, DiskFull):
                resp.status = "STATUS_DISK_FULL"  # shed-class: retryable
            except Exception:  # noqa: BLE001
                log.exception("stream write failed")
                resp.status = "STATUS_INTERNAL_ERROR"
            resp.metadata.CopyFrom(wreq.metadata)
            yield resp

    # -- PropertyService ---------------------------------------------------
    def property_apply(self, req, context):
        try:
            if self.property is None:
                raise ValueError("property engine not wired")
            from banyandb_tpu.models.property import Property

            p = req.property
            tags = {t.key: wire.tag_value_to_py(t.value) for t in p.tags}
            stored = self.property.apply(
                Property(
                    group=p.metadata.group,
                    name=p.metadata.name,
                    id=p.id,
                    tags=tags,
                ),
                strategy="replace" if req.strategy == 2 else "merge",
            )
            return pb.property_rpc_pb2.ApplyResponse(
                created=stored.create_revision == stored.mod_revision,
                tags_num=len(stored.tags),
            )
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def property_delete(self, req, context):
        try:
            if self.property is None:
                raise ValueError("property engine not wired")
            ok = self.property.delete(req.group, req.name, req.id)
            return pb.property_rpc_pb2.DeleteResponse(deleted=ok)
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def property_query(self, req, context):
        try:
            if self.property is None:
                raise ValueError("property engine not wired")
            self._one_group(req)
            tag_filters = {}
            if req.HasField("criteria"):
                crit = wire.criteria_to_internal(req.criteria)
                from banyandb_tpu.query.measure_exec import _lower_criteria

                leaves, expr = _lower_criteria(crit)
                if expr:
                    raise ValueError("property queries take AND criteria only")
                for c in leaves:
                    if c.op != "eq":
                        raise ValueError("property criteria support eq only")
                    tag_filters[c.name] = c.value
            props = self.property.query(
                req.groups[0],
                req.name,
                tag_filters=tag_filters or None,
                ids=list(req.ids) or None,
                limit=int(req.limit) or 100,
            )
            out = pb.property_rpc_pb2.QueryResponse()
            proj = set(req.tag_projection)
            for p in props:
                wire.fill_property_pb(
                    out.properties.add(), p.group, p.name, p.id, p.tags,
                    p.mod_revision, proj,
                )
            return out
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    # -- TraceService ------------------------------------------------------
    def trace_query(self, req, context):
        """trace/v1 Query: the full surface — general AND criteria
        (bloom/zone pruned), tag projection, sidx order-by with
        limit+offset pushed into the walk.  Plan selection lives in
        models.trace.classify_plan; this handler only converts wire
        shapes."""
        try:
            if self.trace is None:
                raise ValueError("trace engine not wired")
            self._one_group(req)  # validates single-group addressing
            ireq = wire.trace_query_to_internal(req)
            res = self.trace.query(ireq)
            return self._trace_result_to_pb(ireq, res)
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def _ordered_tags(self, group: str, t_schema) -> tuple[str, ...]:
        """Tree-rule tags applying to this trace schema, cached per
        (group, trace) and invalidated by registry revision — the rule
        scan must not run once per streamed span write."""
        cache = getattr(self, "_ordered_tags_cache", None)
        if cache is None:
            cache = self._ordered_tags_cache = {}
        key = (group, t_schema.name)
        rev = self.registry.revision
        hit = cache.get(key)
        if hit is not None and hit[0] == rev:
            return hit[1]
        schema_tags = {t.name for t in t_schema.tags}
        ordered = tuple(
            tag
            for r in self.registry.list_index_rules(group)
            if r.type == "tree"
            for tag in r.tags
            if tag in schema_tags
        )
        cache[key] = (rev, ordered)
        return ordered

    def trace_write(self, request_iterator, context):
        """Bidi stream: tag values ride positionally per tag_spec (or the
        schema's tag order)."""
        from banyandb_tpu.models.trace import SpanValue

        for wreq in request_iterator:
            resp = pb.trace_write_pb2.WriteResponse(version=wreq.version)
            try:
                if self.trace is None:
                    raise ValueError("trace engine not wired")
                t_schema = self.registry.get_trace(
                    wreq.metadata.group, wreq.metadata.name
                )
                names = (
                    list(wreq.tag_spec.tag_names)
                    if wreq.HasField("tag_spec") and wreq.tag_spec.tag_names
                    else [t.name for t in t_schema.tags]
                )
                if len(wreq.tags) > len(names):
                    raise ValueError(
                        f"write carries {len(wreq.tags)} tags, spec has {len(names)}"
                    )
                tags = {
                    n: wire.tag_value_to_py(tv)
                    for n, tv in zip(names, wreq.tags)
                }
                ts_tag = t_schema.timestamp_tag
                ts_millis = int(tags.get(ts_tag, 0)) if ts_tag else 0
                if not ts_millis:
                    import time as _time

                    ts_millis = int(_time.time() * 1000)
                ordered = self._ordered_tags(wreq.metadata.group, t_schema)
                self.trace.write(
                    wreq.metadata.group,
                    wreq.metadata.name,
                    [SpanValue(ts_millis=ts_millis, tags=tags, span=wreq.span)],
                    ordered_tags=ordered,
                )
                resp.status = "STATUS_SUCCEED"
            except KeyError:
                resp.status = "STATUS_NOT_FOUND"
            except Exception:  # noqa: BLE001
                log.exception("trace write failed")
                resp.status = "STATUS_INTERNAL_ERROR"
            resp.metadata.CopyFrom(wreq.metadata)
            yield resp

    # -- registries --------------------------------------------------------
    def _registry_handlers(self, kind: str):
        """CRUD handlers for one registry service; kind in
        {group, measure, stream}.  Non-group kinds ride the shared
        spec-registry generator (same shapes); group has its own request
        forms (string-keyed, SchemaInfo delete response, has_group-only
        exist)."""
        if kind != "group":
            return self._spec_registry_handlers(
                f"{kind.capitalize()}RegistryService",
                kind,
                kind,
                getattr(wire, f"{kind}_to_internal"),
                getattr(wire, f"{kind}_to_pb"),
            )
        rpcpb = pb.database_rpc_pb2
        P = "GroupRegistryService"

        def create(req, context):
            try:
                rev = self.registry.create_group(wire.group_to_internal(req.group))
                return rpcpb.GroupRegistryServiceCreateResponse(mod_revision=rev or 1)
            except Exception as e:  # noqa: BLE001
                _abort(context, e)

        def update(req, context):
            # registry _put is an upsert with mod-revision bump, matching
            # the reference's Update semantics
            try:
                rev = self.registry.create_group(wire.group_to_internal(req.group))
                return rpcpb.GroupRegistryServiceUpdateResponse(mod_revision=rev or 1)
            except Exception as e:  # noqa: BLE001
                _abort(context, e)

        def delete(req, context):
            try:
                self.registry.delete_group(req.group)
                return rpcpb.GroupRegistryServiceDeleteResponse()
            except Exception as e:  # noqa: BLE001
                _abort(context, e)

        def get(req, context):
            try:
                g = self.registry.get_group(req.group)
                return rpcpb.GroupRegistryServiceGetResponse(group=wire.group_to_pb(g))
            except Exception as e:  # noqa: BLE001
                _abort(context, e)

        def list_(req, context):
            try:
                # internal groups (_schema backing store) stay off the
                # public surface
                gs = [
                    g
                    for g in self.registry.list_groups()
                    if not g.name.startswith("_")
                ]
                return rpcpb.GroupRegistryServiceListResponse(
                    group=[wire.group_to_pb(g) for g in gs]
                )
            except Exception as e:  # noqa: BLE001
                _abort(context, e)

        def exist(req, context):
            try:
                try:
                    self.registry.get_group(req.group)
                    return rpcpb.GroupRegistryServiceExistResponse(has_group=True)
                except KeyError:
                    return rpcpb.GroupRegistryServiceExistResponse(has_group=False)
            except Exception as e:  # noqa: BLE001
                _abort(context, e)

        return {
            "Create": _unary(create, getattr(rpcpb, f"{P}CreateRequest")),
            "Update": _unary(update, getattr(rpcpb, f"{P}UpdateRequest")),
            "Delete": _unary(delete, getattr(rpcpb, f"{P}DeleteRequest")),
            "Get": _unary(get, getattr(rpcpb, f"{P}GetRequest")),
            "List": _unary(list_, getattr(rpcpb, f"{P}ListRequest")),
            "Exist": _unary(exist, getattr(rpcpb, f"{P}ExistRequest")),
        }

    def _spec_registry_handlers(
        self,
        service: str,
        pb_field: str,
        reg_suffix: str,
        to_internal,
        to_pb,
        reg_list: str = "",
    ):
        """CRUD handlers for the spec registries (IndexRule / Binding /
        TopNAggregation) — same shapes as the resource registries but
        keyed by metadata and named by their proto field."""
        rpcpb = pb.database_rpc_pb2
        reg = self.registry

        def create(req, context):
            try:
                rev = getattr(reg, f"create_{reg_suffix}")(
                    to_internal(getattr(req, pb_field))
                )
                return getattr(rpcpb, f"{service}CreateResponse")(
                    mod_revision=rev or 1
                )
            except Exception as e:  # noqa: BLE001
                _abort(context, e)

        def update(req, context):
            try:
                rev = getattr(reg, f"create_{reg_suffix}")(
                    to_internal(getattr(req, pb_field))
                )
                return getattr(rpcpb, f"{service}UpdateResponse")(
                    mod_revision=rev or 1
                )
            except Exception as e:  # noqa: BLE001
                _abort(context, e)

        def delete(req, context):
            try:
                getattr(reg, f"delete_{reg_suffix}")(
                    req.metadata.group, req.metadata.name
                )
                return getattr(rpcpb, f"{service}DeleteResponse")(deleted=True)
            except Exception as e:  # noqa: BLE001
                _abort(context, e)

        def get(req, context):
            try:
                obj = getattr(reg, f"get_{reg_suffix}")(
                    req.metadata.group, req.metadata.name
                )
                return getattr(rpcpb, f"{service}GetResponse")(
                    **{pb_field: to_pb(obj)}
                )
            except Exception as e:  # noqa: BLE001
                _abort(context, e)

        def list_(req, context):
            try:
                objs = getattr(reg, reg_list or f"list_{reg_suffix}s")(req.group)
                return getattr(rpcpb, f"{service}ListResponse")(
                    **{pb_field: [to_pb(o) for o in objs]}
                )
            except Exception as e:  # noqa: BLE001
                _abort(context, e)

        def exist(req, context):
            try:
                has_group = True
                try:
                    reg.get_group(req.metadata.group)
                except KeyError:
                    has_group = False
                has = True
                try:
                    getattr(reg, f"get_{reg_suffix}")(
                        req.metadata.group, req.metadata.name
                    )
                except KeyError:
                    has = False
                return getattr(rpcpb, f"{service}ExistResponse")(
                    has_group=has_group, **{f"has_{pb_field}": has}
                )
            except Exception as e:  # noqa: BLE001
                _abort(context, e)

        return {
            "Create": _unary(create, getattr(rpcpb, f"{service}CreateRequest")),
            "Update": _unary(update, getattr(rpcpb, f"{service}UpdateRequest")),
            "Delete": _unary(delete, getattr(rpcpb, f"{service}DeleteRequest")),
            "Get": _unary(get, getattr(rpcpb, f"{service}GetRequest")),
            "List": _unary(list_, getattr(rpcpb, f"{service}ListRequest")),
            "Exist": _unary(exist, getattr(rpcpb, f"{service}ExistRequest")),
        }

    # -- misc services -----------------------------------------------------
    def snapshot(self, req, context):
        try:
            out = pb.database_rpc_pb2.SnapshotResponse()
            if hasattr(self.measure, "flush"):
                self.measure.flush()
            for g in self.registry.list_groups():
                snp = out.snapshots.add()
                snp.name = g.name
                snp.catalog = wire._CATALOG_INV.get(g.catalog, 2)
            return out
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def get_api_version(self, req, context):
        """common/v1 Service.GetAPIVersion (api_version.go analog):
        clients negotiate compatibility from this before issuing calls."""
        out = pb.common_rpc_pb2.GetAPIVersionResponse()
        out.version.version = API_VERSION
        out.version.revision = API_REVISION
        return out

    _ROLE = {"meta": 1, "data": 2, "liaison": 3}

    def _node_to_pb(self, node_pb, info: dict) -> None:
        node_pb.metadata.name = info.get("name", "")
        node_pb.grpc_address = info.get("grpc_address", "")
        node_pb.http_address = info.get("http_address", "")
        for r in info.get("roles", ()):
            node_pb.roles.append(self._ROLE.get(r, 0))
        for k, v in (info.get("labels") or {}).items():
            node_pb.labels[k] = v

    def get_current_node(self, req, context):
        """database/v1 NodeQueryService.GetCurrentNode (rpc.proto:928)."""
        out = pb.database_rpc_pb2.GetCurrentNodeResponse()
        self._node_to_pb(out.node, self.node_info)
        return out

    def get_cluster_state(self, req, context):
        """database/v1 ClusterStateService (rpc.proto:952): route tables
        of registered/active/evictable members per tier."""
        try:
            out = pb.database_rpc_pb2.GetClusterStateResponse()
            for tier, table in self.cluster_view_fn().items():
                rt = out.route_tables[tier]
                for info in table.get("registered", ()):
                    self._node_to_pb(rt.registered.add(), info)
                rt.active.extend(table.get("active", ()))
                rt.evictable.extend(table.get("evictable", ()))
            return out
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    # -- schema barrier (schema/v1/barrier.proto:30) -----------------------
    @staticmethod
    def _barrier_timeout(req) -> float:
        d = req.timeout
        s = d.seconds + d.nanos / 1e9
        return s if s > 0 else 10.0

    @staticmethod
    def _laggards_to_pb(resp, laggards) -> None:
        for lag in laggards:
            lpb = resp.laggards.add(
                node=lag.get("node", ""),
                current_mod_revision=lag.get("current_mod_revision", 0),
                reason=lag.get("reason", ""),
            )
            for kind, group, name in lag.get("missing_keys", ()):
                lpb.missing_keys.add(kind=kind, group=group, name=name)
            for kind, group, name in lag.get("still_present_keys", ()):
                lpb.still_present_keys.add(kind=kind, group=group, name=name)

    def _barrier_slot(self, context):
        if not self._barrier_slots.acquire(blocking=False):
            context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                "too many concurrent schema barrier waits",
            )

    def barrier_await_revision(self, req, context):
        self._barrier_slot(context)
        try:
            applied, laggards = self.barrier.await_revision(
                req.min_revision, self._barrier_timeout(req)
            )
            out = pb.schema_barrier_pb2.AwaitRevisionAppliedResponse(applied=applied)
            self._laggards_to_pb(out, laggards)
            return out
        except Exception as e:  # noqa: BLE001
            _abort(context, e)
        finally:
            self._barrier_slots.release()

    def barrier_await_applied(self, req, context):
        self._barrier_slot(context)
        try:
            if len(req.keys) > 10000:
                raise ValueError("keys capped at 10000")
            keys = [(k.kind, k.group, k.name) for k in req.keys]
            revs = list(req.min_revisions) + [0] * (len(keys) - len(req.min_revisions))
            applied, laggards = self.barrier.await_applied(
                keys, revs, self._barrier_timeout(req)
            )
            out = pb.schema_barrier_pb2.AwaitSchemaAppliedResponse(applied=applied)
            self._laggards_to_pb(out, laggards)
            return out
        except Exception as e:  # noqa: BLE001
            _abort(context, e)
        finally:
            self._barrier_slots.release()

    def barrier_await_deleted(self, req, context):
        self._barrier_slot(context)
        try:
            keys = [(k.kind, k.group, k.name) for k in req.keys]
            applied, laggards = self.barrier.await_deleted(
                keys, self._barrier_timeout(req)
            )
            out = pb.schema_barrier_pb2.AwaitSchemaDeletedResponse(applied=applied)
            self._laggards_to_pb(out, laggards)
            return out
        except Exception as e:  # noqa: BLE001
            _abort(context, e)
        finally:
            self._barrier_slots.release()

    # -- node schema status (cluster/v1/node_schema_status.proto:29) -------
    def _schema_key_lookup(self, key) -> dict:
        kind = _BARRIER_KINDS.get(key.kind)
        if kind is None:
            raise ValueError(f"unknown schema kind {key.kind!r}")
        k = key.name if kind == "group" else f"{key.group}/{key.name}"
        return self.registry.stored_object_hash(kind, k)

    def node_schema_max_revision(self, req, context):
        return pb.cluster_node_schema_status_pb2.GetMaxRevisionResponse(
            max_mod_revision=self.registry.revision
        )

    def node_schema_key_revisions(self, req, context):
        try:
            out = pb.cluster_node_schema_status_pb2.GetKeyRevisionsResponse()
            for key in req.keys:  # response order mirrors request order
                st = self._schema_key_lookup(key)
                kr = out.revisions.add()
                kr.key.CopyFrom(key)
                kr.mod_revision = st["rev"]
                kr.present = st["hash"] is not None
            return out
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def node_schema_absent_keys(self, req, context):
        try:
            out = pb.cluster_node_schema_status_pb2.GetAbsentKeysResponse()
            for key in req.keys:
                st = self._schema_key_lookup(key)
                (
                    out.still_present_keys
                    if st["hash"] is not None
                    else out.absent_keys
                ).add().CopyFrom(key)
            return out
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    # -- trace pipeline registry (pipeline/v1/trace_pipeline.proto:87) -----
    # The shipped proto's TracePipelineConfig carries no identity (the
    # design doc's metadata.group field was dropped: "group-scoped,
    # name-less", common.proto:156), yet Create/Update requests carry only
    # the config.  Callers therefore scope Create/Update with an
    # 'x-banyandb-group' gRPC metadata header; Get/Delete/Exist/List use
    # the group from their request as specified.
    def _tp_group_from_md(self, context) -> str:
        for k, v in context.invocation_metadata():
            if k == "x-banyandb-group":
                return v
        raise ValueError(
            "TracePipelineConfig carries no identity; pass the target "
            "group in 'x-banyandb-group' request metadata"
        )

    def _tp_upsert(self, req, context, create: bool):
        from google.protobuf import json_format

        from banyandb_tpu.api.schema import TracePipelineConfig

        group = self._tp_group_from_md(context)
        self.registry.get_group(group)  # admission: group must exist
        cfg_json = json_format.MessageToJson(req.trace_pipeline_config)
        # one config per group: Create is an atomic create-if-absent
        # (the check lives under the registry lock)
        return self.registry.create_trace_pipeline(
            TracePipelineConfig(group=group, config_json=cfg_json),
            exclusive=create,
        )

    def trace_pipeline_create(self, req, context):
        try:
            rev = self._tp_upsert(req, context, create=True)
            return pb.pipeline_trace_pipeline_pb2.TracePipelineRegistryServiceCreateResponse(
                mod_revision=rev
            )
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def trace_pipeline_update(self, req, context):
        try:
            rev = self._tp_upsert(req, context, create=False)
            return pb.pipeline_trace_pipeline_pb2.TracePipelineRegistryServiceUpdateResponse(
                mod_revision=rev
            )
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def trace_pipeline_get(self, req, context):
        from google.protobuf import json_format

        try:
            c = self.registry.get_trace_pipeline(req.metadata.group)
            out = pb.pipeline_trace_pipeline_pb2.TracePipelineRegistryServiceGetResponse()
            json_format.Parse(c.config_json, out.trace_pipeline_config)
            return out
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def trace_pipeline_delete(self, req, context):
        import time as _time

        try:
            self.registry.delete_trace_pipeline(req.metadata.group)
            return pb.pipeline_trace_pipeline_pb2.TracePipelineRegistryServiceDeleteResponse(
                deleted=True,
                delete_time=_time.time_ns(),
                mod_revision=self.registry.revision,
            )
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def trace_pipeline_list(self, req, context):
        from google.protobuf import json_format

        try:
            out = pb.pipeline_trace_pipeline_pb2.TracePipelineRegistryServiceListResponse()
            for c in self.registry.list_trace_pipelines(req.group):
                json_format.Parse(c.config_json, out.trace_pipeline_config.add())
            return out
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def trace_pipeline_exist(self, req, context):
        try:
            has_group = True
            try:
                self.registry.get_group(req.metadata.group)
            except KeyError:
                has_group = False
            return pb.pipeline_trace_pipeline_pb2.TracePipelineRegistryServiceExistResponse(
                has_group=has_group,
                has_trace_pipeline_config=bool(
                    self.registry.list_trace_pipelines(req.metadata.group)
                ),
            )
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    # -- fodc group lifecycle (fodc/v1/rpc.proto:257) ----------------------
    def group_lifecycle_inspect_all(self, req, context):
        try:
            out = pb.fodc_rpc_pb2.InspectAllResponse()
            for g in self.registry.list_groups():
                if g.name.startswith("_"):
                    continue  # same public surface as GroupRegistry.List
                info = out.groups.add()
                gpb = wire.group_to_pb(g)
                info.name = g.name
                info.catalog = pb.common_common_pb2.Catalog.Name(gpb.catalog)
                info.resource_opts.CopyFrom(gpb.resource_opts)
            return out
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    # -- schema plane (schema/v1/internal.proto) ---------------------------
    @staticmethod
    def _fill_schema_doc(prop_msg, kind: str, key: str, payload: str) -> None:
        """One place encodes a schema doc as a property/v1.Property —
        WatchSchemas replay and ListSchemas must never diverge."""
        from banyandb_tpu.cluster import schema_plane

        prop_msg.metadata.group = schema_plane.SCHEMA_GROUP
        prop_msg.metadata.name = kind
        prop_msg.id = key
        tag = prop_msg.tags.add(key="payload")
        tag.value.str.value = payload

    @classmethod
    def _schema_event_to_pb(cls, ev: dict):
        from banyandb_tpu.cluster import schema_plane

        ipb = pb.schema_internal_pb2
        out = ipb.WatchSchemasResponse(event_type=ev["type"])
        if ev["type"] != schema_plane.EVENT_REPLAY_DONE:
            cls._fill_schema_doc(
                out.property, ev["kind"], ev["key"], ev.get("payload", "")
            )
        return out

    def _require_schema_store(self):
        if self.schema_store is None:
            raise NotImplementedError(
                "schema plane not enabled (no PropertySchemaStore)"
            )
        return self.schema_store

    def watch_schemas(self, request_iterator, context):
        """SchemaUpdateService.WatchSchemas (internal.proto:79): replay
        the current schema set, mark REPLAY_DONE, then stream live
        events until the client goes away."""
        store = self._require_schema_store()
        if not self._watch_slots.acquire(blocking=False):
            context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                "too many concurrent schema watch streams",
            )
        try:
            yield from self._watch_schemas_inner(request_iterator, context, store)
        finally:
            self._watch_slots.release()

    def _watch_schemas_inner(self, request_iterator, context, store):
        import queue as _queue

        # half-close without a subscribe request ends the stream cleanly
        # (bare next() would raise StopIteration -> PEP 479 RuntimeError)
        if next(iter(request_iterator), None) is None:
            return
        sid, q = store.hub.subscribe()
        try:
            for ev in store.replay_events():
                yield self._schema_event_to_pb(ev)
            while context.is_active():
                if store.hub.is_dead(sid):
                    # this subscriber lost events (queue overflow); end
                    # the stream so the client reconnects and re-syncs
                    break
                try:
                    ev = q.get(timeout=0.2)
                except _queue.Empty:
                    continue
                yield self._schema_event_to_pb(ev)
        finally:
            store.hub.unsubscribe(sid)

    def _schema_doc_apply(self, prop_msg) -> None:
        """Insert/Update/Repair: a property doc whose metadata.name is
        the schema kind and whose payload tag is the schema json."""
        import json as _json

        from banyandb_tpu.api import schema as schema_mod

        kind = prop_msg.metadata.name
        cls = schema_mod._KINDS.get(kind)
        if cls is None:
            raise ValueError(f"unknown schema kind {kind!r}")
        payload = ""
        for tag in prop_msg.tags:
            if tag.key == "payload":
                payload = tag.value.str.value
        if not payload:
            raise ValueError("schema doc missing payload tag")
        obj = schema_mod._from_jsonable(cls, _json.loads(payload))
        self.registry._put(kind, obj)

    def schema_insert(self, req, context):
        try:
            self._require_schema_store()
            self._schema_doc_apply(req.property)
            return pb.schema_internal_pb2.InsertSchemaResponse()
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def schema_update(self, req, context):
        try:
            self._require_schema_store()
            self._schema_doc_apply(req.property)
            return pb.schema_internal_pb2.UpdateSchemaResponse()
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def schema_delete(self, req, context):
        try:
            from banyandb_tpu.api import schema as schema_mod

            self._require_schema_store()
            kind = req.delete.name
            key = req.delete.id
            if kind not in schema_mod._KINDS:
                raise ValueError(f"unknown schema kind {kind!r}")
            found = True
            try:
                self.registry._delete(kind, key)
            except KeyError:
                found = False
            return pb.schema_internal_pb2.DeleteSchemaResponse(found=found)
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def schema_list(self, req, context):
        """ListSchemas: server-streamed pages of the current docs."""
        try:
            store = self._require_schema_store()
            from banyandb_tpu.cluster import schema_plane

            for ev in store.replay_events():
                if ev["type"] == schema_plane.EVENT_REPLAY_DONE:
                    continue
                out = pb.schema_internal_pb2.ListSchemasResponse()
                self._fill_schema_doc(
                    out.properties.add(), ev["kind"], ev["key"], ev["payload"]
                )
                out.delete_times.append(0)
                yield out
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def schema_repair(self, req, context):
        try:
            self._require_schema_store()
            if req.delete_time > 0:
                from banyandb_tpu.api import schema as schema_mod

                kind = req.property.metadata.name
                if kind not in schema_mod._KINDS:
                    raise ValueError(f"unknown schema kind {kind!r}")
                try:
                    self.registry._delete(kind, req.property.id)
                except KeyError:
                    pass
            else:
                self._schema_doc_apply(req.property)
            return pb.schema_internal_pb2.RepairSchemaResponse()
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def bydbql_query(self, req, context):
        """bydbql/v1 Query: parse QL, dispatch by catalog, return the
        catalog-typed result in the response oneof."""
        try:
            from banyandb_tpu import bydbql

            params = [wire.tag_value_to_py(tv) for tv in req.params]
            catalog, ireq = bydbql.parse_with_catalog(req.query, params)
            out = pb.bydbql_query_pb2.QueryResponse()
            with self._admit(ireq.groups[0] if ireq.groups else ""):
                if catalog == "measure":
                    m = self.registry.get_measure(ireq.groups[0], ireq.name)
                    res = self.measure.query(ireq)
                    out.measure_result.CopyFrom(
                        wire.measure_result_to_pb(m, ireq, res)
                    )
                elif catalog == "stream":
                    res = self.stream.query(ireq)
                    out.stream_result.CopyFrom(wire.stream_result_to_pb(res))
                elif catalog == "trace":
                    if self.trace is None:
                        raise ValueError("trace engine not wired")
                    from banyandb_tpu.query import ql_exec

                    res = ql_exec.execute_trace_ql(self.trace, ireq)
                    out.trace_result.CopyFrom(
                        self._trace_result_to_pb(ireq, res)
                    )
                elif catalog == "property":
                    if self.property is None:
                        raise ValueError("property engine not wired")
                    from banyandb_tpu.query import ql_exec

                    res = ql_exec.execute_property_ql(self.property, ireq)
                    out.property_result.CopyFrom(
                        self._property_result_to_pb(ireq, res)
                    )
                else:
                    # NotImplementedError maps to UNIMPLEMENTED in _abort;
                    # aborting inside the try would be re-caught and
                    # re-aborted as INTERNAL with a spurious stack trace
                    raise NotImplementedError(
                        f"BydbQL catalog {catalog} not yet wired"
                    )
            return out
        except Exception as e:  # noqa: BLE001
            _abort(context, e)

    def _trace_result_to_pb(self, ireq, res):
        """ql_exec trace QueryResult -> trace/v1 QueryResponse: span dicts
        (already projection-filtered by the executor) group into one
        trace per their 'trace_id' key."""
        out = pb.trace_query_pb2.QueryResponse()
        try:
            t_schema = self.registry.get_trace(ireq.groups[0], ireq.name)
        except KeyError:
            t_schema = None
        by_tid: dict[str, list] = {}
        for dp in res.data_points:
            by_tid.setdefault(str(dp.get("trace_id")), []).append(dp)
        for tid, dps in by_tid.items():
            tr = out.traces.add()
            tr.trace_id = tid
            for dp in dps:
                if "span" not in dp and "tags" not in dp:
                    continue  # ordered-query id rows carry no span body
                wire.fill_trace_span_pb(tr.spans.add(), dp, t_schema)
        return out

    def _property_result_to_pb(self, ireq, res):
        """ql_exec property QueryResult (already projection-filtered) ->
        property/v1 QueryResponse."""
        out = pb.property_rpc_pb2.QueryResponse()
        for dp in res.data_points:
            wire.fill_property_pb(
                out.properties.add(), ireq.groups[0], ireq.name,
                dp.get("id", ""), dp.get("tags", {}),
                dp.get("mod_revision", 0),
            )
        return out


class WireServer:
    """The listening gRPC server hosting WireServices."""

    def __init__(
        self,
        services: WireServices,
        port: int = 17912,
        host: str = "127.0.0.1",
        max_workers: int = 16,
        auth_file: str | None = None,
        health_auth: bool = False,
    ):
        self.services = services
        # long-lived watch streams may hold at most a quarter of the pool
        import threading as _threading

        services._watch_slots = _threading.BoundedSemaphore(
            max(2, max_workers // 4)
        )  # rebound to THIS server's pool size (services default is 4)
        interceptors = ()
        self.auth = None
        if auth_file:
            from banyandb_tpu.api.auth import AuthReloader, BasicAuthInterceptor

            self.auth = AuthReloader(auth_file, health_auth=health_auth)
            interceptors = (BasicAuthInterceptor(self.auth),)
        # owned pool, joined in stop(): grpc never shuts down a
        # caller-provided executor, and leaked idle workers fail the
        # bdsan thread-parity check
        self._pool = futures.ThreadPoolExecutor(max_workers=max_workers)
        self.server = grpc.server(
            self._pool,
            interceptors=interceptors,
        )
        s = services
        mq = pb.measure_query_pb2
        mw = pb.measure_write_pb2
        mt = pb.measure_topn_pb2
        sq = pb.stream_query_pb2
        sw = pb.stream_write_pb2
        generic = [
            (
                "banyandb.measure.v1.MeasureService",
                {
                    "Query": _unary(s.measure_query, mq.QueryRequest),
                    "Write": _stream_stream(s.measure_write, mw.WriteRequest),
                    "TopN": _unary(s.measure_topn, mt.TopNRequest),
                },
            ),
            (
                "banyandb.stream.v1.StreamService",
                {
                    "Query": _unary(s.stream_query, sq.QueryRequest),
                    "Write": _stream_stream(s.stream_write, sw.WriteRequest),
                },
            ),
            (
                "banyandb.database.v1.GroupRegistryService",
                s._registry_handlers("group"),
            ),
            (
                "banyandb.database.v1.MeasureRegistryService",
                s._registry_handlers("measure"),
            ),
            (
                "banyandb.database.v1.StreamRegistryService",
                s._registry_handlers("stream"),
            ),
            (
                "banyandb.database.v1.IndexRuleRegistryService",
                s._spec_registry_handlers(
                    "IndexRuleRegistryService",
                    "index_rule",
                    "index_rule",
                    wire.index_rule_to_internal,
                    wire.index_rule_to_pb,
                ),
            ),
            (
                "banyandb.database.v1.IndexRuleBindingRegistryService",
                s._spec_registry_handlers(
                    "IndexRuleBindingRegistryService",
                    "index_rule_binding",
                    "index_rule_binding",
                    wire.index_rule_binding_to_internal,
                    wire.index_rule_binding_to_pb,
                ),
            ),
            (
                "banyandb.database.v1.TopNAggregationRegistryService",
                s._spec_registry_handlers(
                    "TopNAggregationRegistryService",
                    "top_n_aggregation",
                    "topn",
                    wire.topn_to_internal,
                    wire.topn_to_pb,
                    reg_list="list_topn",
                ),
            ),
        ]
        if hasattr(pb.database_rpc_pb2, "SnapshotRequest"):
            generic.append(
                (
                    "banyandb.database.v1.SnapshotService",
                    {"Snapshot": _unary(s.snapshot, pb.database_rpc_pb2.SnapshotRequest)},
                )
            )
        generic.append(
            (
                "banyandb.bydbql.v1.BydbQLService",
                {"Query": _unary(s.bydbql_query, pb.bydbql_query_pb2.QueryRequest)},
            )
        )
        if s.property is not None:
            pr = pb.property_rpc_pb2
            generic.append(
                (
                    "banyandb.property.v1.PropertyService",
                    {
                        "Apply": _unary(s.property_apply, pr.ApplyRequest),
                        "Delete": _unary(s.property_delete, pr.DeleteRequest),
                        "Query": _unary(s.property_query, pr.QueryRequest),
                    },
                )
            )
        if s.trace is not None:
            generic.append(
                (
                    "banyandb.trace.v1.TraceService",
                    {
                        "Query": _unary(s.trace_query, pb.trace_query_pb2.QueryRequest),
                        "Write": _stream_stream(
                            s.trace_write, pb.trace_write_pb2.WriteRequest
                        ),
                    },
                )
            )
        generic += [
            (
                "banyandb.database.v1.TraceRegistryService",
                s._spec_registry_handlers(
                    "TraceRegistryService",
                    "trace",
                    "trace",
                    wire.trace_to_internal,
                    wire.trace_to_pb,
                ),
            ),
            (
                "banyandb.database.v1.PropertyRegistryService",
                s._spec_registry_handlers(
                    "PropertyRegistryService",
                    "property",
                    "property_schema",
                    wire.property_schema_to_internal,
                    wire.property_schema_to_pb,
                ),
            ),
            (
                "banyandb.common.v1.Service",
                {
                    "GetAPIVersion": _unary(
                        s.get_api_version, pb.common_rpc_pb2.GetAPIVersionRequest
                    )
                },
            ),
            (
                "banyandb.database.v1.NodeQueryService",
                {
                    "GetCurrentNode": _unary(
                        s.get_current_node,
                        pb.database_rpc_pb2.GetCurrentNodeRequest,
                    )
                },
            ),
            (
                "banyandb.database.v1.ClusterStateService",
                {
                    "GetClusterState": _unary(
                        s.get_cluster_state,
                        pb.database_rpc_pb2.GetClusterStateRequest,
                    )
                },
            ),
            (
                "banyandb.schema.v1.SchemaManagementService",
                {
                    "InsertSchema": _unary(
                        s.schema_insert, pb.schema_internal_pb2.InsertSchemaRequest
                    ),
                    "UpdateSchema": _unary(
                        s.schema_update, pb.schema_internal_pb2.UpdateSchemaRequest
                    ),
                    "ListSchemas": _unary_stream(
                        s.schema_list, pb.schema_internal_pb2.ListSchemasRequest
                    ),
                    "DeleteSchema": _unary(
                        s.schema_delete, pb.schema_internal_pb2.DeleteSchemaRequest
                    ),
                    "RepairSchema": _unary(
                        s.schema_repair, pb.schema_internal_pb2.RepairSchemaRequest
                    ),
                },
            ),
            (
                "banyandb.schema.v1.SchemaUpdateService",
                {
                    "WatchSchemas": _stream_stream(
                        s.watch_schemas,
                        pb.schema_internal_pb2.WatchSchemasRequest,
                    )
                },
            ),
            (
                "banyandb.schema.v1.SchemaBarrierService",
                {
                    "AwaitRevisionApplied": _unary(
                        s.barrier_await_revision,
                        pb.schema_barrier_pb2.AwaitRevisionAppliedRequest,
                    ),
                    "AwaitSchemaApplied": _unary(
                        s.barrier_await_applied,
                        pb.schema_barrier_pb2.AwaitSchemaAppliedRequest,
                    ),
                    "AwaitSchemaDeleted": _unary(
                        s.barrier_await_deleted,
                        pb.schema_barrier_pb2.AwaitSchemaDeletedRequest,
                    ),
                },
            ),
            (
                "banyandb.cluster.v1.NodeSchemaStatusService",
                {
                    "GetMaxRevision": _unary(
                        s.node_schema_max_revision,
                        pb.cluster_node_schema_status_pb2.GetMaxRevisionRequest,
                    ),
                    "GetKeyRevisions": _unary(
                        s.node_schema_key_revisions,
                        pb.cluster_node_schema_status_pb2.GetKeyRevisionsRequest,
                    ),
                    "GetAbsentKeys": _unary(
                        s.node_schema_absent_keys,
                        pb.cluster_node_schema_status_pb2.GetAbsentKeysRequest,
                    ),
                },
            ),
            (
                "banyandb.pipeline.v1.TracePipelineRegistryService",
                {
                    "Create": _unary(
                        s.trace_pipeline_create,
                        pb.pipeline_trace_pipeline_pb2.TracePipelineRegistryServiceCreateRequest,
                    ),
                    "Update": _unary(
                        s.trace_pipeline_update,
                        pb.pipeline_trace_pipeline_pb2.TracePipelineRegistryServiceUpdateRequest,
                    ),
                    "Delete": _unary(
                        s.trace_pipeline_delete,
                        pb.pipeline_trace_pipeline_pb2.TracePipelineRegistryServiceDeleteRequest,
                    ),
                    "Get": _unary(
                        s.trace_pipeline_get,
                        pb.pipeline_trace_pipeline_pb2.TracePipelineRegistryServiceGetRequest,
                    ),
                    "List": _unary(
                        s.trace_pipeline_list,
                        pb.pipeline_trace_pipeline_pb2.TracePipelineRegistryServiceListRequest,
                    ),
                    "Exist": _unary(
                        s.trace_pipeline_exist,
                        pb.pipeline_trace_pipeline_pb2.TracePipelineRegistryServiceExistRequest,
                    ),
                },
            ),
            (
                "banyandb.fodc.v1.GroupLifecycleService",
                {
                    "InspectAll": _unary(
                        s.group_lifecycle_inspect_all,
                        pb.fodc_rpc_pb2.InspectAllRequest,
                    ),
                },
            ),
        ]
        self.server.add_generic_rpc_handlers(
            tuple(
                grpc.method_handlers_generic_handler(name, hs)
                for name, hs in generic
            )
        )
        self.port = self.server.add_insecure_port(f"{host}:{port}")

    def start(self):
        from banyandb_tpu.cluster.rpc import prespawn_pool

        # workers exist from second one, not first-request time: no lazy
        # thread-spawn latency, deterministic thread population (bdsan)
        prespawn_pool(self._pool)
        self.server.start()
        return self

    def stop(self, grace: float = 0.5):
        self.server.stop(grace).wait()
        self._pool.shutdown(wait=True)


def serve_standalone(root, port: int = 17912):
    """Convenience: wire-compatible server over fresh standalone engines."""
    from banyandb_tpu.api.schema import SchemaRegistry
    from banyandb_tpu.models.measure import MeasureEngine
    from banyandb_tpu.models.stream import StreamEngine
    from pathlib import Path

    root = Path(root)
    registry = SchemaRegistry(root)
    measure = MeasureEngine(registry, root / "data")
    stream = StreamEngine(registry, root / "data")
    svcs = WireServices(registry, measure, stream)
    return WireServer(svcs, port=port).start()


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--root", required=True)
    ap.add_argument("--port", type=int, default=17912)
    args = ap.parse_args()
    srv = serve_standalone(args.root, args.port)
    print(f"wire server on :{srv.port}")
    srv.server.wait_for_termination()
