"""Query & write request/response models.

Parity with measure/v1 QueryRequest + model/v1 Criteria/Condition/TimeRange
(api/proto/banyandb/measure/v1/query.proto, model/v1/query.proto), plus a
first-class ``percentile`` aggregate (SURVEY.md §7 step 1 — the reference
only post-processes percentiles client-side).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence, Union


@dataclass(frozen=True)
class TimeRange:
    """Half-open [begin, end) in epoch millis (model/v1 TimeRange analog)."""

    begin_millis: int
    end_millis: int

    def overlaps(self, lo: int, hi: int) -> bool:
        return self.begin_millis < hi and lo < self.end_millis


@dataclass(frozen=True)
class Condition:
    """model/v1 Condition: one tag predicate."""

    name: str
    op: str  # eq | ne | lt | le | gt | ge | in | not_in | having | match
    value: object
    # MATCH options (model/v1 Condition.MatchOption): "and" requires
    # every analyzed query token to hit; analyzer overrides the index
    # rule's analyzer
    match_op: str = "or"
    match_analyzer: str = ""


@dataclass(frozen=True)
class LogicalExpression:
    op: str  # and | or
    left: "Criteria"
    right: "Criteria"


Criteria = Union[Condition, LogicalExpression]


@dataclass(frozen=True)
class GroupBy:
    tag_names: tuple[str, ...]
    field_name: str = ""


@dataclass(frozen=True)
class Aggregation:
    function: str  # sum | count | min | max | mean | percentile
    field_name: str
    # percentile-only extras
    quantiles: tuple[float, ...] = ()


@dataclass(frozen=True)
class Top:
    number: int
    field_name: str
    field_value_sort: str = "desc"  # desc | asc


@dataclass(frozen=True)
class QueryRequest:
    """measure/v1 QueryRequest analog."""

    groups: tuple[str, ...]
    name: str
    time_range: TimeRange
    criteria: Optional[Criteria] = None
    tag_projection: tuple[str, ...] = ()
    field_projection: tuple[str, ...] = ()
    group_by: Optional[GroupBy] = None
    agg: Optional[Aggregation] = None
    top: Optional[Top] = None
    limit: int = 100
    offset: int = 0
    order_by_ts: str = ""  # "" | asc | desc
    # order-by-index for retrieval paths (model/v1 QueryOrder with an
    # index rule naming a tag): sort rows by this tag's value instead of
    # the timestamp; direction in order_by_dir
    order_by_tag: str = ""
    order_by_dir: str = "asc"  # asc | desc (applies to order_by_tag)
    trace: bool = False  # in-band query tracing
    stages: tuple[str, ...] = ()
    # family-structured tag projection from the wire ((family, (tags..)),
    # ...): responses group projected tags under the REQUESTED family
    # names (a measure can declare non-"default" families, e.g.
    # storage_only in service_latency_minute)
    tag_families_projection: tuple = ()


@dataclass(frozen=True)
class DataPointValue:
    """One ingested data point (measure/v1 DataPointValue analog)."""

    ts_millis: int
    tags: dict[str, object]
    fields: dict[str, object]
    version: int = 0


@dataclass(frozen=True)
class WriteRequest:
    group: str
    name: str
    points: tuple[DataPointValue, ...]


@dataclass
class QueryResult:
    """Aggregated response: either grouped aggregates or raw data points."""

    # group tuples (tag values) aligned with per-agg value arrays
    groups: list[tuple] = field(default_factory=list)
    values: dict[str, list] = field(default_factory=dict)
    data_points: list[dict] = field(default_factory=list)
    # representative values for projected-but-not-grouped tags: each
    # grouped output row carries the tag values of the group's FIRST row
    # in scan order (the reference's aggGroupIterator copies the first
    # fed data point's TagFamilies, measure_plan_aggregation.go:286)
    rep_tags: dict[str, list] = field(default_factory=dict)
    trace: Optional[dict] = None
    # graceful degradation markers (docs/robustness.md): True when the
    # liaison answered from a PARTIAL replica set — rows covered by the
    # named unreachable nodes are missing, everything present is exact
    degraded: bool = False
    unavailable_nodes: list = field(default_factory=list)
