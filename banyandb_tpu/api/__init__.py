"""API surface: schema objects, query/write models, and the registry.

Dataclass mirror of the reference's proto API (api/proto/banyandb/**) —
same vocabulary (Group/ResourceOpts, Measure/TagSpec/FieldSpec/Entity,
IndexRule, QueryRequest/Criteria/Condition), new wire (in-process now,
gRPC liaison later).
"""

from banyandb_tpu.api.schema import (
    Catalog,
    TagType,
    FieldType,
    TagSpec,
    FieldSpec,
    Entity,
    Group,
    ResourceOpts,
    IntervalRule,
    Measure,
    Stream,
    Trace,
    PropertySchema,
    IndexRule,
    TopNAggregation,
    SchemaRegistry,
)
from banyandb_tpu.api.model import (
    TimeRange,
    Condition,
    Criteria,
    LogicalExpression,
    QueryRequest,
    Aggregation,
    GroupBy,
    Top,
    DataPointValue,
    WriteRequest,
)
