"""Basic auth with hot-reloaded users file for the wire surface.

Reference: banyand/liaison/pkg/auth/reloader.go (yaml users file,
0600-permission enforcement, fsnotify hot reload with debounce) and
banyand/liaison/grpc/auth.go (username/password gRPC metadata check on
every unary + stream call; health checks optionally exempt).

This implementation polls the file's (mtime, size) signature on access
with a small interval instead of inotify — same convergence contract
(changes apply without restart), no extra thread or dependency.
Credential comparison is constant-time over sha256 digests, as upstream
compares sha256 via crypto/subtle.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import threading
import time
from pathlib import Path

import grpc

_RECHECK_S = 0.2  # stat() at most this often


class AuthReloader:
    """users.yaml loader: {"users": [{"username","password"}, ...]}."""

    def __init__(self, config_file: str | Path, health_auth: bool = False):
        self.config_file = Path(config_file)
        self.health_auth_enabled = health_auth
        self._lock = threading.Lock()
        self._users: dict[str, bytes] = {}
        self._sig: tuple | None = None
        self._next_check = 0.0
        self._load(required=True)

    @property
    def enabled(self) -> bool:
        return True

    def _load(self, required: bool = False) -> None:
        import yaml

        try:
            st = self.config_file.stat()
        except OSError:
            if required:
                raise
            return  # keep last-good users if the file blinks away
        mode = st.st_mode & 0o777
        if mode != 0o600:
            # same contract as the reference loader: refuse world/group
            # readable credential files
            err = PermissionError(
                f"auth config {self.config_file} has unsafe permissions "
                f"{oct(mode)} (expected 0o600)"
            )
            if required:
                raise err
            return
        sig = (st.st_mtime_ns, st.st_size)
        if sig == self._sig:
            return
        data = yaml.safe_load(self.config_file.read_text()) or {}
        users = {}
        for u in data.get("users") or []:
            name, pw = u.get("username"), u.get("password")
            if name and pw is not None:
                users[name] = hashlib.sha256(str(pw).encode()).digest()
        with self._lock:
            self._users = users
            self._sig = sig

    def _maybe_reload(self) -> None:
        now = time.monotonic()
        if now < self._next_check:
            return
        self._next_check = now + _RECHECK_S
        try:
            self._load()
        except Exception as e:  # noqa: BLE001 - keep last-good config,
            # but tell the operator the rotation did NOT apply
            import logging

            logging.getLogger("banyandb.auth").warning(
                "auth config reload failed; keeping previous users: %s", e
            )

    def check(self, username: str, password: str) -> bool:
        self._maybe_reload()
        with self._lock:
            want = self._users.get(username)
        if want is None:
            # constant-time shape even for unknown users
            hmac.compare_digest(hashlib.sha256(password.encode()).digest(), b"\0" * 32)
            return False
        return hmac.compare_digest(
            hashlib.sha256(password.encode()).digest(), want
        )

    def touch_for_test(self) -> None:
        """Force the next check() to re-stat immediately (tests)."""
        self._next_check = 0.0
        self._sig = None


class BasicAuthInterceptor(grpc.ServerInterceptor):
    """Rejects calls without valid username/password metadata pairs
    (auth.go:validateUser analog).  Health checks pass unless
    health_auth_enabled."""

    _HEALTH = "/grpc.health.v1.Health/Check"

    def __init__(self, reloader: AuthReloader):
        self.reloader = reloader

        def deny(request, context):
            context.abort(grpc.StatusCode.UNAUTHENTICATED, "Invalid credentials")

        self._deny_unary = grpc.unary_unary_rpc_method_handler(deny)

    def intercept_service(self, continuation, handler_call_details):
        if (
            handler_call_details.method == self._HEALTH
            and not self.reloader.health_auth_enabled
        ):
            return continuation(handler_call_details)
        md = dict(handler_call_details.invocation_metadata or ())
        user = md.get("username", "")
        pw = md.get("password", "")
        if user and self.reloader.check(user, pw):
            return continuation(handler_call_details)
        return self._deny_handler(continuation, handler_call_details)

    def _deny_handler(self, continuation, handler_call_details):
        """Return a handler of the RIGHT arity that aborts UNAUTHENTICATED
        (a unary handler for a stream method breaks the server)."""
        real = continuation(handler_call_details)

        def deny(request_or_iterator, context):
            context.abort(
                grpc.StatusCode.UNAUTHENTICATED, "Invalid credentials"
            )

        if real is None:
            return self._deny_unary
        if real.request_streaming and real.response_streaming:
            return grpc.stream_stream_rpc_method_handler(
                deny,
                request_deserializer=real.request_deserializer,
                response_serializer=real.response_serializer,
            )
        if real.request_streaming:
            return grpc.stream_unary_rpc_method_handler(
                deny,
                request_deserializer=real.request_deserializer,
                response_serializer=real.response_serializer,
            )
        if real.response_streaming:
            return grpc.unary_stream_rpc_method_handler(
                deny,
                request_deserializer=real.request_deserializer,
                response_serializer=real.response_serializer,
            )
        return grpc.unary_unary_rpc_method_handler(
            deny,
            request_deserializer=real.request_deserializer,
            response_serializer=real.response_serializer,
        )


def write_users_file(path: str | Path, users: dict[str, str]) -> None:
    """Write a users.yaml with the required 0600 permissions (test +
    provisioning helper)."""
    import yaml

    p = Path(path)
    body = yaml.safe_dump(
        {"users": [{"username": u, "password": pw} for u, pw in users.items()]}
    ).encode()
    # create 0600 from the first byte — never a world-readable window
    fd = os.open(p, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    try:
        os.write(fd, body)
    finally:
        os.close(fd)
    os.chmod(p, 0o600)  # O_CREAT mode is masked by umask; re-assert
