"""Schema objects + registry.

Vocabulary parity with the reference's database/v1 schema protos
(api/proto/banyandb/database/v1/schema.proto: Measure, TagSpec, FieldSpec,
Entity, IndexRule, TopNAggregation; common/v1/common.proto: Group,
ResourceOpts, IntervalRule).  The registry is the analog of the
property-backed schema server (banyand/metadata/schema/schemaserver/) in
single-process form: in-memory maps with mod-revision semantics, persisted
as JSON files under <root>/schema/ via atomic writes.
"""

from __future__ import annotations

import collections
import dataclasses
import enum
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

from banyandb_tpu.utils import fs


class Catalog(enum.Enum):
    MEASURE = "measure"
    STREAM = "stream"
    TRACE = "trace"
    PROPERTY = "property"


class TagType(enum.Enum):
    STRING = "string"
    INT = "int"
    STRING_ARRAY = "string_array"
    INT_ARRAY = "int_array"
    DATA_BINARY = "data_binary"
    TIMESTAMP = "timestamp"


class FieldType(enum.Enum):
    STRING = "string"
    INT = "int"
    FLOAT = "float"
    DATA_BINARY = "data_binary"


@dataclass(frozen=True)
class TagSpec:
    name: str
    type: TagType


@dataclass(frozen=True)
class FieldSpec:
    name: str
    type: FieldType
    # encoding/compression method knobs from the reference are implied by
    # type here: INT -> delta+zstd, FLOAT -> decimal-mantissa+delta+zstd.


@dataclass(frozen=True)
class Entity:
    """Which tags form the series identity (database/v1 Entity)."""

    tag_names: tuple[str, ...]


@dataclass(frozen=True)
class IntervalRule:
    """common/v1 IntervalRule: a duration expressed as <num><unit>."""

    num: int
    unit: str  # "hour" | "day"

    @property
    def millis(self) -> int:
        return self.num * (3_600_000 if self.unit == "hour" else 86_400_000)


@dataclass(frozen=True)
class ResourceOpts:
    """common/v1 ResourceOpts: sharding/replication/retention per group."""

    shard_num: int = 1
    replicas: int = 0
    segment_interval: IntervalRule = IntervalRule(1, "day")
    ttl: IntervalRule = IntervalRule(7, "day")
    stages: tuple[str, ...] = ()  # hot/warm/cold tier names


@dataclass(frozen=True)
class Group:
    name: str
    catalog: Catalog
    resource_opts: ResourceOpts = ResourceOpts()


@dataclass(frozen=True)
class Measure:
    """database/v1 Measure: tag families + fields keyed by entity."""

    group: str
    name: str
    tags: tuple[TagSpec, ...]
    fields: tuple[FieldSpec, ...]
    entity: Entity
    interval: str = ""  # data-point interval hint (e.g. "1m")
    index_mode: bool = False  # index-mode measures live in the series index
    # wire-API family layout: ordered (family_name, tag_count) runs over
    # the flat `tags` tuple (database/v1 TagFamilySpec); empty = one
    # implicit "default" family
    tag_families: tuple[tuple[str, int], ...] = ()

    def tag(self, name: str) -> TagSpec:
        for t in self.tags:
            if t.name == name:
                return t
        raise KeyError(f"tag {name} not in measure {self.name}")

    def field(self, name: str) -> FieldSpec:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(f"field {name} not in measure {self.name}")


@dataclass(frozen=True)
class Stream:
    """database/v1 Stream schema: tagged append-only elements, no fields."""

    group: str
    name: str
    tags: tuple[TagSpec, ...]
    entity: tuple[str, ...]
    tag_families: tuple[tuple[str, int], ...] = ()  # see Measure.tag_families

    def tag(self, name: str) -> TagSpec:
        for t in self.tags:
            if t.name == name:
                return t
        raise KeyError(f"tag {name} not in stream {self.name}")


@dataclass(frozen=True)
class Trace:
    """database/v1 Trace schema: spans keyed by a trace-id tag."""

    group: str
    name: str
    tags: tuple[TagSpec, ...]
    trace_id_tag: str
    timestamp_tag: str = ""
    span_id_tag: str = ""  # schema.proto Trace.span_id_tag_name

    def tag(self, name: str) -> TagSpec:
        for t in self.tags:
            if t.name == name:
                return t
        raise KeyError(f"tag {name} not in trace {self.name}")


@dataclass(frozen=True)
class PropertySchema:
    """database/v1 Property schema (schema.proto:224): the declared tag
    set of a property namespace — registered via PropertyRegistryService,
    distinct from property VALUES (property/v1 Apply/Query)."""

    group: str
    name: str
    tags: tuple[TagSpec, ...]


@dataclass(frozen=True)
class IndexRule:
    """database/v1 IndexRule: which tags get inverted/skipping/tree index."""

    group: str
    name: str
    tags: tuple[str, ...]
    type: str = "inverted"  # inverted | skipping | tree
    analyzer: str = ""


@dataclass(frozen=True)
class IndexRuleBinding:
    """database/v1 IndexRuleBinding: which rules apply to which subject
    over a validity window."""

    group: str
    name: str
    rules: tuple[str, ...]
    subject_catalog: str  # stream | measure | trace
    subject_name: str
    begin_at_millis: int = 0
    expire_at_millis: int = 0


@dataclass(frozen=True)
class TopNAggregation:
    """database/v1 TopNAggregation: ingest-time streaming top-N source."""

    group: str
    name: str
    source_measure: str
    field_name: str
    field_value_sort: str = "desc"  # desc | asc | all
    group_by_tag_names: tuple[str, ...] = ()
    counters_number: int = 1000
    lru_size: int = 10
    # group of the source measure when it differs from the rule's group
    # ("" = same group); wire Get/List must round-trip this faithfully
    source_group: str = ""
    # optional ingest-time filter (database/v1 TopNAggregation.criteria):
    # only source rows matching it feed the windows.  Stored as the
    # protobuf-JSON dict of the model/v1 Criteria (registry persistence
    # stays plain JSON); None = no filter.
    criteria: Optional[dict] = None


@dataclasses.dataclass(frozen=True)
class TracePipelineConfig:
    """pipeline/v1 TracePipelineConfig: group-scoped, name-less tail-
    sampling config (one per group by construction — common.proto:156).
    The proto body is stored as canonical protobuf-JSON: the registry
    versions/persists/gossips it; the trace engine interprets it."""

    group: str
    config_json: str = "{}"

    @property
    def name(self) -> str:  # registry key: one config per group
        return "_pipeline"


_KINDS = {
    "group": Group,
    "measure": Measure,
    "stream": Stream,
    "trace": Trace,
    "property_schema": PropertySchema,
    "index_rule": IndexRule,
    "index_rule_binding": IndexRuleBinding,
    "topn": TopNAggregation,
    "trace_pipeline": TracePipelineConfig,
}


def _to_jsonable(obj):
    if dataclasses.is_dataclass(obj):
        return {
            f.name: _to_jsonable(getattr(obj, f.name))
            for f in dataclasses.fields(obj)
        }
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, tuple):
        return [_to_jsonable(x) for x in obj]
    return obj


def _from_jsonable(cls, data):
    if dataclasses.is_dataclass(cls):
        kwargs = {}
        for f in dataclasses.fields(cls):
            if f.name in data:
                kwargs[f.name] = _from_jsonable_field(f.type, data[f.name])
        return cls(**kwargs)
    return data


_FIELD_TYPES = {
    "Catalog": Catalog,
    "TagType": TagType,
    "FieldType": FieldType,
    "tuple[TagSpec, ...]": (tuple, "TagSpec"),
    "tuple[FieldSpec, ...]": (tuple, "FieldSpec"),
    "tuple[str, ...]": (tuple, None),
    "tuple[tuple[str, int], ...]": (tuple, "pair"),
    "Entity": Entity,
    "IntervalRule": IntervalRule,
    "ResourceOpts": ResourceOpts,
}
_CLASSES = {
    "TagSpec": TagSpec,
    "FieldSpec": FieldSpec,
}


def _from_jsonable_field(type_str, value):
    spec = _FIELD_TYPES.get(type_str)
    if spec is None:
        return value
    if isinstance(spec, tuple):
        _, inner = spec
        if inner is None:
            return tuple(value)
        if inner == "pair":
            return tuple(tuple(v) for v in value)
        return tuple(_from_jsonable(_CLASSES[inner], v) for v in value)
    if isinstance(spec, type) and issubclass(spec, enum.Enum):
        return spec(value)
    return _from_jsonable(spec, value)


class SchemaRegistry:
    """Mod-revisioned schema store with optional file persistence.

    CRUD semantics mirror the reference's registry services
    (banyand/liaison/grpc/registry.go): create/update bump a global
    revision; deletes are recorded; watchers (engines) are notified
    synchronously in-process.
    """

    def __init__(self, root: Optional[str | Path] = None):
        self._lock = threading.RLock()
        self._root = Path(root) / "schema" if root else None
        self._revision = 0
        self._store: dict[str, dict[str, object]] = {k: {} for k in _KINDS}
        # per-object local revisions (barrier freshness checks); persisted
        # alongside the objects so min_revision barriers remain truthful
        # across restarts (pre-persistence files load as rev 0, and the
        # cluster barrier additionally matches by content hash)
        self._obj_revs: dict[tuple[str, str], int] = {}
        # content hashes cached at put/load time (objects are frozen
        # dataclasses) so digests() is a dict copy, not an O(n) hash
        # pass under the lock
        self._obj_hashes: dict[tuple[str, str], str] = {}
        # delete tombstones (key -> buried content hash), PERSISTED:
        # gossip must propagate deletions instead of resurrecting deleted
        # objects from lagging peers; the hash scopes the grave to the
        # EXACT deleted content, so a recreate with different content
        # gossips normally
        self._tombstones: dict[str, dict[str, str]] = {k: {} for k in _KINDS}
        self._watchers: list = []
        self._delete_watchers: list = []
        # watcher callbacks run OUTSIDE self._lock (they may persist to
        # disk / broadcast); events queue under the lock and drain FIFO
        # under _notify_lock, so observers still see revision order
        self._pending_events: collections.deque = collections.deque()
        self._notify_lock = threading.Lock()
        if self._root and self._root.exists():
            self._load()

    # -- internals ---------------------------------------------------------
    def _key(self, obj) -> str:
        group = getattr(obj, "group", None)
        return f"{group}/{obj.name}" if group else obj.name

    def _persist(self, kind: str) -> None:
        if not self._root:
            return
        payload = {k: _to_jsonable(v) for k, v in self._store[kind].items()}
        fs.atomic_write_json(
            self._root / f"{kind}.json",
            {
                "revision": self._revision,
                "items": payload,
                # per-object revisions persist so barrier min_revision
                # checks stay truthful across restarts
                "revs": {
                    k: self._obj_revs.get((kind, k), 0)
                    for k in self._store[kind]
                },
            },
        )

    def _load(self) -> None:
        for kind, cls in _KINDS.items():
            path = self._root / f"{kind}.json"
            if not path.exists():
                continue
            data = fs.read_json(path)
            self._revision = max(self._revision, data.get("revision", 0))
            revs = data.get("revs", {})
            for key, item in data.get("items", {}).items():
                obj = _from_jsonable(cls, item)
                self._store[kind][key] = obj
                self._obj_hashes[(kind, key)] = self.object_hash(obj)
                if revs.get(key):
                    self._obj_revs[(kind, key)] = revs[key]
        tpath = self._root / "tombstones.json"
        if tpath.exists():
            data = fs.read_json(tpath)
            for kind, graves in data.items():
                if kind in self._tombstones and isinstance(graves, dict):
                    self._tombstones[kind] = dict(graves)

    def _persist_tombstones(self) -> None:
        if self._root:
            fs.atomic_write_json(
                self._root / "tombstones.json", self._tombstones
            )

    def _drain_events(self) -> None:
        """Deliver queued watcher events in FIFO order.  Whoever holds
        _notify_lock drains everything pending; a mutator returning from
        _put/_delete is guaranteed its own event has been delivered
        (by itself or by a concurrent drainer)."""
        with self._notify_lock:
            while True:
                try:
                    op, kind, payload, rev = self._pending_events.popleft()
                except IndexError:
                    return
                targets = (
                    self._watchers if op == "put" else self._delete_watchers
                )
                for w in targets:
                    w(kind, payload, rev)

    def _put(self, kind: str, obj, *, exclusive: bool = False) -> int:
        with self._lock:
            key = self._key(obj)
            if exclusive and key in self._store[kind]:
                # atomic create-if-absent: the existence check must live
                # under the same lock as the insert (concurrent Creates)
                raise FileExistsError(f"{kind} {key} already exists")
            self._revision += 1
            self._store[kind][key] = obj
            self._obj_revs[(kind, key)] = self._revision
            self._obj_hashes[(kind, key)] = self.object_hash(obj)
            if self._tombstones[kind].pop(key, None) is not None:
                # recreate clears the grave
                self._persist_tombstones()
            self._persist(kind)
            rev = self._revision
            self._pending_events.append(("put", kind, obj, rev))
        self._drain_events()
        return rev

    def _get(self, kind: str, key: str):
        with self._lock:
            obj = self._store[kind].get(key)
            if obj is None:
                raise KeyError(f"{kind} {key} not found")
            return obj

    def _delete(self, kind: str, key: str) -> None:
        with self._lock:
            if key not in self._store[kind]:
                raise KeyError(f"{kind} {key} not found")
            self._revision += 1
            buried = self._obj_hashes.pop((kind, key), None) or self.object_hash(
                self._store[kind][key]
            )
            del self._store[kind][key]
            self._tombstones[kind][key] = buried
            self._persist(kind)
            self._persist_tombstones()
            self._pending_events.append(("delete", kind, key, self._revision))
        self._drain_events()

    # -- public CRUD (parity with the 9 registry services) -----------------
    @property
    def revision(self) -> int:
        return self._revision

    @staticmethod
    def object_hash(obj) -> str:
        """Content hash of one schema object (barrier ack verification —
        revisions are per-node counters, so equality of numbers proves
        nothing; equality of content does)."""
        import hashlib
        import json as _json

        payload = _json.dumps(_to_jsonable(obj), sort_keys=True)
        return hashlib.blake2b(payload.encode(), digest_size=16).hexdigest()

    def digests(self) -> dict[str, dict[str, str]]:
        """{kind: {key: content-hash}} over the whole store — the gossip
        reconciliation unit (pkg/schema cache sync analog).  Hashes are
        cached at put/load time, so this is a dict copy under the lock."""
        with self._lock:
            return {
                kind: {
                    k: self._obj_hashes.get((kind, k)) or self.object_hash(o)
                    for k, o in objs.items()
                }
                for kind, objs in self._store.items()
            }

    def tombstones(self) -> dict[str, dict[str, str]]:
        with self._lock:
            return {k: dict(v) for k, v in self._tombstones.items()}

    def apply_tombstone(self, kind: str, key: str, buried_hash: str) -> bool:
        """Gossip-propagated deletion: remove the local object ONLY if
        its content matches what the peer buried (differing content means
        a newer create, which must survive); records the grave either
        way so this node stops offering the dead content.  Returns True
        when something was deleted."""
        with self._lock:
            local_hash = self._obj_hashes.get((kind, key))
            existed = key in self._store[kind]
            if existed and local_hash != buried_hash:
                return False  # newer content under the same key: keep it
            if existed:
                self._revision += 1
                del self._store[kind][key]
                self._obj_hashes.pop((kind, key), None)
                self._persist(kind)
                # gossip deletions notify delete watchers like local ones:
                # a property-backed store must bury its doc too, or the
                # deleted schema resurrects from replay on restart
                self._pending_events.append(
                    ("delete", kind, key, self._revision)
                )
            self._tombstones[kind][key] = buried_hash
            self._persist_tombstones()
        self._drain_events()
        return existed

    def export_object(self, kind: str, key: str) -> Optional[dict]:
        """JSON-able form of one stored object (gossip pull)."""
        with self._lock:
            obj = self._store[kind].get(key)
        return None if obj is None else _to_jsonable(obj)

    def stored_object_hash(self, kind: str, key: str) -> dict:
        """-> {hash, rev}: rev is this node's LOCAL per-object revision
        (persisted with the object; 0 only for pre-persistence files —
        cluster barriers still verify by content hash, never by trusting
        another node's counters)."""
        with self._lock:
            present = key in self._store[kind]
            h = self._obj_hashes.get((kind, key)) if present else None
            if present and h is None:
                h = self.object_hash(self._store[kind][key])
            rev = self._obj_revs.get((kind, key), 0)
        return {"hash": h, "rev": rev}

    def watch(self, callback) -> None:
        """callback(kind, obj, revision) on every create/update."""
        self._watchers.append(callback)

    def watch_deletes(self, callback) -> None:
        """callback(kind, key, revision) on every delete."""
        self._delete_watchers.append(callback)

    def create_group(self, g: Group) -> int:
        return self._put("group", g)

    def get_group(self, name: str) -> Group:
        return self._get("group", name)

    def list_groups(self) -> list[Group]:
        return list(self._store["group"].values())

    def delete_group(self, name: str) -> None:
        if name.startswith("_"):
            # internal groups (e.g. _schema, the registry's own property
            # backing store) must not be deletable: dropping _schema would
            # break every subsequent schema mutation's persistence
            raise ValueError(f"group {name} is internal and cannot be deleted")
        # cascade: child resources die with the group (the reference
        # orchestrates this in liaison/grpc/deletion.go) — otherwise they
        # orphan and resurrect when the group name is reused
        for kind in _KINDS:
            if kind == "group":
                continue
            doomed = [
                key
                for key, obj in self._store[kind].items()
                if getattr(obj, "group", None) == name
            ]
            for key in doomed:
                self._delete(kind, key)
        self._delete("group", name)

    def create_measure(self, m: Measure) -> int:
        self.get_group(m.group)  # must exist
        return self._put("measure", m)

    def get_measure(self, group: str, name: str) -> Measure:
        return self._get("measure", f"{group}/{name}")

    def list_measures(self, group: str) -> list[Measure]:
        return [
            m for m in self._store["measure"].values() if m.group == group
        ]

    def delete_measure(self, group: str, name: str) -> None:
        self._delete("measure", f"{group}/{name}")

    def create_stream(self, s: Stream) -> int:
        self.get_group(s.group)
        return self._put("stream", s)

    def get_stream(self, group: str, name: str) -> Stream:
        return self._get("stream", f"{group}/{name}")

    def list_streams(self, group: str) -> list[Stream]:
        return [s for s in self._store["stream"].values() if s.group == group]

    def delete_stream(self, group: str, name: str) -> None:
        self._delete("stream", f"{group}/{name}")

    def delete_trace(self, group: str, name: str) -> None:
        self._delete("trace", f"{group}/{name}")

    def create_trace(self, t: Trace) -> int:
        self.get_group(t.group)
        return self._put("trace", t)

    def get_trace(self, group: str, name: str) -> Trace:
        return self._get("trace", f"{group}/{name}")

    def create_property_schema(self, p: PropertySchema) -> int:
        self.get_group(p.group)
        return self._put("property_schema", p)

    def get_property_schema(self, group: str, name: str) -> PropertySchema:
        return self._get("property_schema", f"{group}/{name}")

    def list_property_schemas(self, group: str) -> list[PropertySchema]:
        return [
            p
            for p in self._store["property_schema"].values()
            if p.group == group
        ]

    def delete_property_schema(self, group: str, name: str) -> None:
        self._delete("property_schema", f"{group}/{name}")

    def list_traces(self, group: str) -> list[Trace]:
        return [t for t in self._store["trace"].values() if t.group == group]

    def create_index_rule(self, r: IndexRule) -> int:
        return self._put("index_rule", r)

    def get_index_rule(self, group: str, name: str) -> IndexRule:
        return self._get("index_rule", f"{group}/{name}")

    def delete_index_rule(self, group: str, name: str) -> None:
        self._delete("index_rule", f"{group}/{name}")

    def list_index_rules(self, group: str) -> list[IndexRule]:
        return [
            r for r in self._store["index_rule"].values() if r.group == group
        ]

    def create_index_rule_binding(self, b: IndexRuleBinding) -> int:
        return self._put("index_rule_binding", b)

    def get_index_rule_binding(self, group: str, name: str) -> IndexRuleBinding:
        return self._get("index_rule_binding", f"{group}/{name}")

    def delete_index_rule_binding(self, group: str, name: str) -> None:
        self._delete("index_rule_binding", f"{group}/{name}")

    def list_index_rule_bindings(self, group: str) -> list[IndexRuleBinding]:
        return [
            b
            for b in self._store["index_rule_binding"].values()
            if b.group == group
        ]

    def create_trace_pipeline(
        self, c: TracePipelineConfig, *, exclusive: bool = False
    ) -> int:
        return self._put("trace_pipeline", c, exclusive=exclusive)

    def get_trace_pipeline(self, group: str) -> TracePipelineConfig:
        return self._get("trace_pipeline", f"{group}/_pipeline")

    def delete_trace_pipeline(self, group: str) -> None:
        self._delete("trace_pipeline", f"{group}/_pipeline")

    def list_trace_pipelines(self, group: str) -> list[TracePipelineConfig]:
        return [
            c
            for c in self._store["trace_pipeline"].values()
            if c.group == group
        ]

    def create_topn(self, t: TopNAggregation) -> int:
        return self._put("topn", t)

    def get_topn(self, group: str, name: str) -> TopNAggregation:
        return self._get("topn", f"{group}/{name}")

    def delete_topn(self, group: str, name: str) -> None:
        self._delete("topn", f"{group}/{name}")

    def list_topn(self, group: str) -> list[TopNAggregation]:
        return [t for t in self._store["topn"].values() if t.group == group]
