"""Wire-compatible protobuf modules (generated — see regen.py).

The generated tree keeps the upstream package layout (banyandb.*.v1) so
message descriptors carry the exact wire names reference clients
expect; this package dir joins sys.path so those absolute imports
resolve without shadowing our own package.

    from banyandb_tpu.api import pb
    pb.measure_query_pb2.QueryRequest()
"""

from __future__ import annotations

import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
if _HERE not in sys.path:
    sys.path.insert(0, _HERE)


def _load(mod_path: str):
    import importlib

    return importlib.import_module(mod_path)


def __getattr__(name: str):
    """Lazy aliases: pb.measure_query_pb2 -> banyandb.measure.v1.query_pb2."""
    try:
        family, rest = name.split("_", 1)
        if rest.endswith("_pb2"):
            stem = rest[: -len("_pb2")]
            return _load(f"banyandb.{family}.v1.{stem}_pb2")
    except (ValueError, ImportError):
        pass
    raise AttributeError(name)
