"""Regenerate the wire-compatible protobuf modules.

The BanyanDB wire contract is defined by the reference proto tree
(/root/reference/api/proto/banyandb/** — upstream
github.com/apache/skywalking-banyandb api/proto).  Wire compatibility
means identical packages, message names, and field numbers, so this
script compiles those protos directly rather than re-typing them.

The upstream tree imports three annotation-only dependencies that buf
normally fetches (google/api/annotations.proto, protoc-gen-openapiv2
options, validate/validate.proto).  None of them affect the wire format
— they carry HTTP-gateway routes, OpenAPI metadata, and server-side
validation hints — so the sanitizer strips those imports and the option
blocks that reference them before invoking protoc.  The HTTP routes
they described are re-implemented natively in api/http_gateway.py.

Usage:  python -m banyandb_tpu.api.pb.regen [src_proto_root]
Output: banyandb/**/**_pb2.py next to this file (imported via this
package's __init__, which extends sys.path).
"""

from __future__ import annotations

import pathlib
import re
import shutil
import subprocess
import sys
import tempfile

HERE = pathlib.Path(__file__).resolve().parent
DEFAULT_SRC = pathlib.Path("/root/reference/api/proto")

# proto subtrees to compile (the services this framework serves)
SUBTREES = [
    "banyandb/common/v1",
    "banyandb/model/v1",
    "banyandb/database/v1",
    "banyandb/measure/v1",
    "banyandb/stream/v1",
    "banyandb/property/v1",
    "banyandb/trace/v1",
    "banyandb/bydbql/v1",
    "banyandb/cluster/v1",
    "banyandb/schema/v1",
    "banyandb/fodc/v1",
    "banyandb/pipeline/v1",
]

_DROP_IMPORTS = (
    "google/api/annotations.proto",
    "google/api/httpbody.proto",
    "protoc-gen-openapiv2/options/annotations.proto",
    "validate/validate.proto",
)


def _strip_balanced(text: str, start: int, open_ch: str, close_ch: str) -> int:
    """Index just past the balanced group opening at text[start]."""
    depth = 0
    i = start
    while i < len(text):
        c = text[i]
        if c == open_ch:
            depth += 1
        elif c == close_ch:
            depth -= 1
            if depth == 0:
                return i + 1
        i += 1
    raise ValueError("unbalanced group in proto source")


def sanitize(text: str) -> str:
    # 1. drop unsupported imports
    lines = []
    for ln in text.splitlines():
        if any(f'"{imp}"' in ln for imp in _DROP_IMPORTS) and ln.strip().startswith(
            "import"
        ):
            continue
        lines.append(ln)
    text = "\n".join(lines)

    # 2. remove extension option statements:  option (ext.path) = <value>;
    #    value may be a balanced {...} aggregate or a scalar.
    out = []
    i = 0
    pat = re.compile(r"option\s*\(")
    while True:
        m = pat.search(text, i)
        if not m:
            out.append(text[i:])
            break
        out.append(text[i : m.start()])
        j = _strip_balanced(text, text.index("(", m.start()), "(", ")")
        # skip to '=' then the value
        k = text.index("=", j) + 1
        while text[k].isspace():
            k += 1
        if text[k] == "{":
            k = _strip_balanced(text, k, "{", "}")
        # consume through the terminating ';'
        k = text.index(";", k) + 1
        i = k

    text = "".join(out)

    # 3. remove extension field options:  [(validate.rules)...] etc.
    out = []
    i = 0
    while True:
        j = text.find("[(", i)
        if j < 0:
            out.append(text[i:])
            break
        out.append(text[i:j])
        i = _strip_balanced(text, j, "[", "]")
    return "".join(out)


def main(src_root: pathlib.Path = DEFAULT_SRC) -> None:
    with tempfile.TemporaryDirectory() as td:
        tmp = pathlib.Path(td)
        for sub in SUBTREES:
            for proto in sorted((src_root / sub).glob("*.proto")):
                dst = tmp / sub / proto.name
                dst.parent.mkdir(parents=True, exist_ok=True)
                dst.write_text(sanitize(proto.read_text()))
        protos = [str(p.relative_to(tmp)) for p in tmp.rglob("*.proto")]
        # wipe previous output so removed protos don't linger
        if (HERE / "banyandb").exists():
            shutil.rmtree(HERE / "banyandb")
        subprocess.run(
            ["protoc", f"-I{tmp}", f"--python_out={HERE}", *protos],
            check=True,
        )
        # packages need __init__.py on some import configurations
        for d in (HERE / "banyandb").rglob("**/"):
            (d / "__init__.py").touch()
        (HERE / "banyandb" / "__init__.py").touch()
    print(f"generated {len(protos)} proto modules under {HERE / 'banyandb'}")


if __name__ == "__main__":
    main(pathlib.Path(sys.argv[1]) if len(sys.argv) > 1 else DEFAULT_SRC)
