"""pb <-> internal model translation for the wire-compatible API.

The generated modules under api/pb carry the exact upstream wire schema
(banyandb.*.v1); this module converts between those messages and the
framework's internal dataclasses (api/model.py, api/schema.py).  The
mapping notes cite the defining protos:

- model/v1/common.proto TagValue oneof  <-> python scalars/lists/bytes
- model/v1/query.proto Criteria tree    <-> Condition/LogicalExpression
- measure/v1/query.proto QueryRequest   <-> api.model.QueryRequest
- database/v1/schema.proto Measure etc. <-> api.schema dataclasses
- common/v1/common.proto Group          <-> api.schema.Group

Tag families: the wire schema groups tags into named families; the
internal schema is flat.  Family structure is preserved on the schema
objects (``tag_families`` = ordered (name, count) runs over the flat
tag tuple) so writes and Get responses regroup losslessly.
"""

from __future__ import annotations

from typing import Optional

from google.protobuf import json_format

from banyandb_tpu.api import model as im
from banyandb_tpu.api import pb
from banyandb_tpu.api import schema as isch

# enum maps (numbers fixed by the protos)
_AGG_FN = {1: "mean", 2: "max", 3: "min", 4: "count", 5: "sum"}
_AGG_FN_INV = {v: k for k, v in _AGG_FN.items()}
# SORT_UNSPECIFIED (0) means ascending in query order_by paths
# (banyand/measure/query.go:292 treats SORT_ASC || SORT_UNSPECIFIED alike);
# only TopN field_value_sort defaults to desc (measure_plan_top.go:69).
_SORT = {0: "asc", 1: "desc", 2: "asc"}
_SORT_TOPN = {0: "desc", 1: "desc", 2: "asc"}
_CATALOG = {1: isch.Catalog.STREAM, 2: isch.Catalog.MEASURE,
            3: isch.Catalog.PROPERTY, 4: isch.Catalog.TRACE}
_CATALOG_INV = {v: k for k, v in _CATALOG.items()}
_TAG_TYPE = {1: isch.TagType.STRING, 2: isch.TagType.INT,
             3: isch.TagType.STRING_ARRAY, 4: isch.TagType.INT_ARRAY,
             5: isch.TagType.DATA_BINARY, 6: isch.TagType.TIMESTAMP}
_TAG_TYPE_INV = {v: k for k, v in _TAG_TYPE.items()}
_FIELD_TYPE = {1: isch.FieldType.STRING, 2: isch.FieldType.INT,
               3: isch.FieldType.DATA_BINARY, 4: isch.FieldType.FLOAT}
_FIELD_TYPE_INV = {v: k for k, v in _FIELD_TYPE.items()}
_COND_OP = {1: "eq", 2: "ne", 3: "lt", 4: "gt", 5: "le", 6: "ge",
            7: "having", 8: "not_having", 9: "in", 10: "not_in", 11: "match"}
_IV_UNIT = {1: "hour", 2: "day"}
_IV_UNIT_INV = {v: k for k, v in _IV_UNIT.items()}


# -- time ------------------------------------------------------------------


def ts_to_millis(ts) -> int:
    return ts.seconds * 1000 + ts.nanos // 1_000_000


def millis_to_ts(ms: int):
    from google.protobuf import timestamp_pb2

    return timestamp_pb2.Timestamp(
        seconds=ms // 1000, nanos=(ms % 1000) * 1_000_000
    )


# -- tag/field values ------------------------------------------------------


def tag_value_to_py(tv) -> object:
    which = tv.WhichOneof("value")
    if which is None or which == "null":
        return None
    if which == "str":
        return tv.str.value
    if which == "int":
        return tv.int.value
    if which == "str_array":
        return list(tv.str_array.value)
    if which == "int_array":
        return list(tv.int_array.value)
    if which == "binary_data":
        return tv.binary_data
    if which == "timestamp":
        return ts_to_millis(tv.timestamp)
    raise ValueError(f"unsupported TagValue kind {which}")


def py_to_tag_value(v, tag_type: Optional[isch.TagType] = None):
    m = pb.model_common_pb2.TagValue()
    if v is None:
        m.null = 0
    elif isinstance(v, bool):
        m.int.value = int(v)
    elif isinstance(v, bytes):
        if tag_type == isch.TagType.STRING:
            m.str.value = v.decode("utf-8", "replace")
        elif tag_type == isch.TagType.INT and len(v) == 8:
            m.int.value = int.from_bytes(v, "little", signed=True)
        elif tag_type == isch.TagType.TIMESTAMP and len(v) == 8:
            m.timestamp.CopyFrom(
                millis_to_ts(int.from_bytes(v, "little", signed=True))
            )
        else:
            m.binary_data = v
    elif isinstance(v, str):
        m.str.value = v
    elif isinstance(v, int):
        if tag_type == isch.TagType.TIMESTAMP:
            m.timestamp.CopyFrom(millis_to_ts(v))
        else:
            m.int.value = v
    elif isinstance(v, float):
        m.int.value = int(v)
    elif isinstance(v, (list, tuple)):
        if all(isinstance(x, int) for x in v):
            m.int_array.value.extend(v)
        else:
            m.str_array.value.extend(str(x) for x in v)
    else:
        raise TypeError(f"unsupported tag value {type(v)}")
    return m


def field_value_to_py(fv) -> object:
    which = fv.WhichOneof("value")
    if which is None or which == "null":
        return None
    if which == "str":
        return fv.str.value
    if which == "int":
        return fv.int.value
    if which == "float":
        return fv.float.value
    if which == "binary_data":
        return fv.binary_data
    raise ValueError(f"unsupported FieldValue kind {which}")


def py_to_field_value(v):
    m = pb.model_common_pb2.FieldValue()
    if v is None:
        m.null = 0
    elif isinstance(v, bytes):
        m.binary_data = v
    elif isinstance(v, str):
        m.str.value = v
    elif isinstance(v, float):
        m.float.value = v
    elif isinstance(v, int):
        m.int.value = v
    else:
        raise TypeError(f"unsupported field value {type(v)}")
    return m


# -- criteria --------------------------------------------------------------


def criteria_to_internal(c) -> Optional[im.Criteria]:
    if c is None:
        return None
    which = c.WhichOneof("exp")
    if which is None:
        return None
    if which == "condition":
        cond = c.condition
        if cond.op not in _COND_OP:
            # an unknown/unset wire op is INVALID_ARGUMENT, never a
            # silent eq filter (same contract as measure_topn)
            raise ValueError(
                f"unknown condition op {cond.op} on tag {cond.name!r}"
            )
        op = _COND_OP[cond.op]
        val = tag_value_to_py(cond.value)
        if op in ("in", "not_in") and not isinstance(val, (list, tuple)):
            # ref rejects IN/NOT_IN with a scalar literal (the array
            # oneof is mandatory; WantErr gen_err_in_scalar)
            raise ValueError(f"{op.upper()} requires an array value")
        match_op = "or"
        match_analyzer = ""
        if cond.HasField("match_option"):
            if cond.match_option.operator == 1:  # OPERATOR_AND
                match_op = "and"
            match_analyzer = cond.match_option.analyzer
        return im.Condition(
            cond.name, op, val,
            match_op=match_op, match_analyzer=match_analyzer,
        )
    le = c.le
    op = "and" if le.op == 1 else "or"
    return im.LogicalExpression(
        op, criteria_to_internal(le.left), criteria_to_internal(le.right)
    )


def _flatten_projection(proj) -> tuple[str, ...]:
    out: list[str] = []
    for fam in proj.tag_families:
        out.extend(fam.tags)
    return tuple(out)


# -- measure query ---------------------------------------------------------


def measure_query_to_internal(req) -> im.QueryRequest:
    group_by = None
    if req.HasField("group_by"):
        group_by = im.GroupBy(
            tag_names=_flatten_projection(req.group_by.tag_projection),
            field_name=req.group_by.field_name,
        )
    agg = None
    if req.HasField("agg"):
        agg = im.Aggregation(
            function=_AGG_FN.get(req.agg.function, "count"),
            field_name=req.agg.field_name,
        )
    top = None
    if req.HasField("top"):
        top = im.Top(
            number=req.top.number or 100,
            field_name=req.top.field_name,
            field_value_sort=_SORT_TOPN.get(req.top.field_value_sort, "desc"),
        )
    order_by_ts = ""
    order_by_tag = ""
    order_by_dir = "asc"
    if req.HasField("order_by"):
        if req.order_by.index_rule_name in ("", "timestamp"):
            order_by_ts = _SORT.get(req.order_by.sort, "")
        else:  # order-by-index: the rule names the tag to sort by
            order_by_tag = req.order_by.index_rule_name
            order_by_dir = _SORT.get(req.order_by.sort, "asc")
    return im.QueryRequest(
        groups=tuple(req.groups),
        name=req.name,
        time_range=im.TimeRange(
            ts_to_millis(req.time_range.begin),
            ts_to_millis(req.time_range.end),
        )
        if req.HasField("time_range")
        else im.TimeRange(0, 1 << 62),
        criteria=criteria_to_internal(req.criteria) if req.HasField("criteria") else None,
        tag_projection=_flatten_projection(req.tag_projection),
        tag_families_projection=tuple(
            (fam.name, tuple(fam.tags))
            for fam in req.tag_projection.tag_families
        ),
        field_projection=tuple(req.field_projection.names),
        group_by=group_by,
        agg=agg,
        top=top,
        limit=int(req.limit) or 100,
        offset=int(req.offset),
        order_by_ts=order_by_ts,
        order_by_tag=order_by_tag,
        order_by_dir=order_by_dir,
        trace=req.trace,
        stages=tuple(req.stages),
    )


def _families_of(spec) -> list[tuple[str, tuple[str, ...]]]:
    """Regroup a flat internal schema's tags into wire families."""
    fams = getattr(spec, "tag_families", ()) or ()
    names = [t.name for t in spec.tags]
    if not fams:
        return [("default", tuple(names))]
    out = []
    i = 0
    for fam_name, count in fams:
        out.append((fam_name, tuple(names[i : i + count])))
        i += count
    if i < len(names):  # tags added after proto creation
        out.append(("default", tuple(names[i:])))
    return out


def measure_result_to_pb(measure: isch.Measure, req: im.QueryRequest, res):
    """QueryResult -> measure/v1 QueryResponse.

    Aggregate results become one DataPoint per group (the reference's
    shape for grouped aggregations): group tags in their families,
    aggregate outputs as fields named by the result keys.
    """
    out = pb.measure_query_pb2.QueryResponse()
    if res.groups or res.values:
        group_tags = tuple(req.group_by.tag_names) if req.group_by else ()
        agg_key = agg_field = None
        agg_int = False
        if req.agg is not None:
            # Reference response shape for grouped aggregation (want/
            # group_*.yaml in test/cases/measure): exactly ONE field,
            # named after the aggregated field, typed like it — MEAN
            # over int fields truncates (Go int64 division,
            # pkg/query/aggregation meanInt64).
            fn = req.agg.function
            agg_field = req.agg.field_name or "value"
            if fn == "count":
                agg_key = "count"
            elif fn == "percentile":
                agg_key = f"percentile({agg_field})"
            else:
                agg_key = f"{fn}({agg_field})"
            try:
                # the output field is typed like the AGGREGATED FIELD —
                # including count (count over a float field emits float,
                # want/float_top_count.yaml)
                agg_int = (
                    fn != "percentile"
                    and measure.field(agg_field).type.name == "INT"
                )
            except (KeyError, AttributeError):
                agg_int = fn == "count"
        # Tags emit in PROJECTION order under the REQUESTED family names:
        # group-key values from the group tuple, other projected tags
        # from the representative (first scanned) row (reference
        # aggregation keeps the first fed row's TagFamilies).  Without an
        # explicit projection, group tags under "default".
        fam_specs = req.tag_families_projection or (
            ("default", tuple(req.tag_projection or group_tags)),
        )
        for i, g in enumerate(res.groups):
            by_name = dict(zip(group_tags, g))
            dp = out.data_points.add()
            for fam_name, fam_tags in fam_specs:
                fam = dp.tag_families.add(name=fam_name)
                for t in fam_tags:
                    if t not in by_name and t not in res.rep_tags:
                        continue
                    v = (
                        by_name[t]
                        if t in by_name
                        else res.rep_tags[t][i]
                        if i < len(res.rep_tags.get(t, ()))
                        else None
                    )
                    tag = fam.tags.add(key=t)
                    tag.value.CopyFrom(
                        py_to_tag_value(v, measure.tag(t).type if _has_tag(measure, t) else None)
                    )
            if req.agg is None:
                # groupBy without aggregation: distinct groups, no
                # fields (want/group_no_field.yaml)
                continue
            if agg_key is not None:
                vals = res.values.get(agg_key, ())
                v = vals[i] if i < len(vals) else None
                if isinstance(v, list):  # percentile -> one field per q
                    for qi, qv in enumerate(v):
                        name = agg_field if qi == 0 else f"{agg_field}[{qi}]"
                        f = dp.fields.add(name=name)
                        f.value.CopyFrom(py_to_field_value(float(qv)))
                else:
                    f = dp.fields.add(name=agg_field)
                    f.value.CopyFrom(
                        py_to_field_value(int(v) if agg_int else v)
                    )
                continue
            for key, vals in res.values.items():
                f = dp.fields.add(name=key)
                v = vals[i] if i < len(vals) else None
                if isinstance(v, list):  # percentile rows -> one field per q
                    for qi, qv in enumerate(v):
                        if qi == 0:
                            f.value.CopyFrom(py_to_field_value(float(qv)))
                        else:
                            extra = dp.fields.add(name=f"{key}[{qi}]")
                            extra.value.CopyFrom(py_to_field_value(float(qv)))
                else:
                    f.value.CopyFrom(py_to_field_value(v))
    int_fields = {
        f.name for f in measure.fields if getattr(f.type, "name", "") == "INT"
    }
    # Strict projection semantics (want/*.yaml): the response carries
    # ONLY the projected tags/fields, in projection order; an empty
    # tagProjection yields no tag families at all.
    tag_proj = tuple(req.tag_projection)
    field_proj = tuple(req.field_projection)
    for row in res.data_points:
        dp = out.data_points.add()
        dp.timestamp.CopyFrom(millis_to_ts(row["timestamp"]))
        tags = row.get("tags", {})
        fam_specs = req.tag_families_projection or (
            (("default", tag_proj),) if tag_proj else ()
        )
        for fam_name, fam_tags in fam_specs:
            fam = dp.tag_families.add(name=fam_name)
            for t in fam_tags:
                if t not in tags:
                    continue
                tag = fam.tags.add(key=t)
                tag.value.CopyFrom(
                    py_to_tag_value(
                        tags[t],
                        measure.tag(t).type if _has_tag(measure, t) else None,
                    )
                )
        fields = row.get("fields", {})
        for fname in field_proj:
            if fname not in fields:
                continue
            f = dp.fields.add(name=fname)
            # schema-typed emission: the engine's device column is f64,
            # but INT fields must return int on the wire (want/*.yaml)
            f.value.CopyFrom(
                py_to_field_value(
                    int(fields[fname]) if fname in int_fields else fields[fname]
                )
            )
    fill_trace(out, res)
    fill_degraded(out, res)
    return out


def fill_degraded(out, res) -> None:
    """Degraded-result markers on the proto wire (docs/robustness.md).

    The reference QueryResponse has no dedicated field, so the marker
    rides the in-band trace as one explicit error span named
    ``degraded`` with an ``unavailable_nodes`` tag — emitted whether or
    not the client asked for tracing, so a partial answer is never
    silently complete-looking.  The JSON surface mirrors this with
    top-level ``degraded``/``unavailable_nodes`` keys
    (server.result_to_json)."""
    if not getattr(res, "degraded", False) or not hasattr(out, "trace"):
        return
    sp = out.trace.spans.add()
    sp.message = "degraded"
    sp.error = True
    sp.tags.add(
        key="unavailable_nodes",
        value=",".join(sorted(res.unavailable_nodes)),
    )


def fill_trace(out, res) -> None:
    """Attach in-band query-trace spans to a QueryResponse proto
    (common/v1 Trace; the reference threads pkg/query/tracer spans back
    the same way — dquery/measure.go:104).  The hierarchical span_tree
    (obs/tracer) maps natively onto common/v1 Span.children — a merged
    cluster tree keeps per-node subtrees nested on the wire; remaining
    keys of the internal trace dict become flat spans (the plan
    rendering rides the span message so `trace=true` clients see the
    plan tree)."""
    tr = getattr(res, "trace", None)
    if not tr or not hasattr(out, "trace"):
        return

    def fill_tree(sp, node: dict) -> None:
        sp.message = str(node.get("name", ""))
        # duration is nanoseconds on the wire (common/v1 Span.duration)
        sp.duration = int(float(node.get("duration_ms", 0.0)) * 1e6)
        if node.get("error"):
            sp.error = True
            sp.tags.add(key="error", value=str(node["error"]))
        for k, v in (node.get("tags") or {}).items():
            sp.tags.add(key=str(k), value=str(v))
        for child in node.get("children", ()):
            if isinstance(child, dict):
                fill_tree(sp.children.add(), child)

    def add_span(message: str, fields: dict) -> None:
        span = out.trace.spans.add()
        span.message = message
        for k, v in fields.items():
            span.tags.add(key=str(k), value=str(v))

    for key, val in tr.items():
        if key == "span_tree" and isinstance(val, dict):
            fill_tree(out.trace.spans.add(), val)
        elif isinstance(val, list) and all(isinstance(x, dict) for x in val):
            # per-phase span lists (measure _trace_spans): one proto span
            # each, named by the entry's own name where present
            for i, entry in enumerate(val):
                add_span(str(entry.get("name", f"{key}[{i}]")), entry)
        elif isinstance(val, dict):
            add_span(key, val)
        else:
            add_span(f"{key}: {val}", {})


def _has_tag(spec, name: str) -> bool:
    return any(t.name == name for t in spec.tags)


def write_request_to_point(measure: isch.Measure, wreq) -> im.DataPointValue:
    """measure/v1 WriteRequest -> internal DataPointValue.

    Tag values ride positionally per family (TagFamilyForWrite); the
    names come from data_point_spec when present, else from the schema's
    family layout (banyand/liaison/grpc/measure.go navigator analog).
    """
    dp = wreq.data_point
    fams = _families_of(measure)
    if wreq.HasField("data_point_spec") and wreq.data_point_spec.tag_family_spec:
        fams = [
            (fs.name, tuple(fs.tag_names))
            for fs in wreq.data_point_spec.tag_family_spec
        ]
        field_names = list(wreq.data_point_spec.field_names)
    else:
        field_names = [f.name for f in measure.fields]
    tags: dict[str, object] = _positional_tags(fams, dp.tag_families)
    fields: dict[str, object] = {}
    for name, fv in zip(field_names, dp.fields):
        v = field_value_to_py(fv)
        if v is not None:
            fields[name] = v
    return im.DataPointValue(
        ts_millis=ts_to_millis(dp.timestamp),
        tags=tags,
        fields=fields,
        version=dp.version,
    )


# -- stream ----------------------------------------------------------------


def _positional_tags(fams, tag_families) -> dict[str, object]:
    """Zip positional family values against the schema layout, rejecting
    count mismatches (the reference liaison's navigator errors rather
    than dropping/misassigning tags — silent truncation corrupts data)."""
    if len(tag_families) > len(fams):
        raise ValueError(
            f"write carries {len(tag_families)} tag families, schema has {len(fams)}"
        )
    tags: dict[str, object] = {}
    for (fam_name, tag_names), tfw in zip(fams, tag_families):
        if len(tfw.tags) > len(tag_names):
            raise ValueError(
                f"family {fam_name!r} carries {len(tfw.tags)} tags, "
                f"schema has {len(tag_names)}"
            )
        for name, tv in zip(tag_names, tfw.tags):
            tags[name] = tag_value_to_py(tv)
    return tags


def stream_query_to_internal(req) -> im.QueryRequest:
    order_by_ts = ""
    order_by_tag = ""
    order_by_dir = "asc"
    if req.HasField("order_by"):
        if req.order_by.index_rule_name in ("", "timestamp"):
            order_by_ts = _SORT.get(req.order_by.sort, "")
        else:  # order-by-index: the rule names the tag to sort by
            order_by_tag = req.order_by.index_rule_name
            order_by_dir = _SORT.get(req.order_by.sort, "asc")
    return im.QueryRequest(
        groups=tuple(req.groups),
        name=req.name,
        time_range=im.TimeRange(
            ts_to_millis(req.time_range.begin),
            ts_to_millis(req.time_range.end),
        )
        if req.HasField("time_range")
        else im.TimeRange(0, 1 << 62),
        criteria=criteria_to_internal(req.criteria) if req.HasField("criteria") else None,
        tag_projection=_flatten_projection(req.projection),
        limit=int(req.limit) or 100,
        offset=int(req.offset),
        order_by_ts=order_by_ts,
        order_by_tag=order_by_tag,
        order_by_dir=order_by_dir,
        trace=req.trace,
        stages=tuple(req.stages),
    )


def stream_result_to_pb(res):
    out = pb.stream_query_pb2.QueryResponse()
    for row in res.data_points:
        el = out.elements.add()
        el.element_id = str(row.get("element_id", ""))
        el.timestamp.CopyFrom(millis_to_ts(row["timestamp"]))
        fam = el.tag_families.add(name="default")
        for t, v in row.get("tags", {}).items():
            tag = fam.tags.add(key=t)
            tag.value.CopyFrom(py_to_tag_value(v))
    fill_trace(out, res)
    fill_degraded(out, res)
    return out


def element_value_from_pb(stream: "isch.Stream", wreq):
    from banyandb_tpu.models.stream import ElementValue

    el = wreq.element
    fams = _families_of(stream)
    if wreq.tag_family_spec:
        fams = [(fs.name, tuple(fs.tag_names)) for fs in wreq.tag_family_spec]
    tags = _positional_tags(fams, el.tag_families)
    body = tags.pop("body", b"") or b""
    if isinstance(body, str):
        body = body.encode()
    return ElementValue(
        element_id=el.element_id,
        ts_millis=ts_to_millis(el.timestamp),
        tags=tags,
        body=body,
    )


def trace_query_to_internal(req) -> im.QueryRequest:
    """trace/v1 QueryRequest -> internal: the full surface (criteria,
    flat tag projection, sidx order-by with limit+offset) — the plan
    split happens in models.trace.classify_plan, not here."""
    order_by_tag = ""
    order_by_dir = "asc"
    if req.HasField("order_by"):
        if req.order_by.index_rule_name not in ("", "timestamp"):
            order_by_tag = req.order_by.index_rule_name
            order_by_dir = _SORT.get(req.order_by.sort, "asc")
    return im.QueryRequest(
        groups=tuple(req.groups),
        name=req.name,
        time_range=im.TimeRange(
            ts_to_millis(req.time_range.begin),
            ts_to_millis(req.time_range.end),
        )
        if req.HasField("time_range")
        else im.TimeRange(0, 1 << 62),
        criteria=criteria_to_internal(req.criteria)
        if req.HasField("criteria")
        else None,
        tag_projection=tuple(req.tag_projection),
        limit=int(req.limit),  # 0 -> per-plan engine default
        offset=int(req.offset),
        order_by_tag=order_by_tag,
        order_by_dir=order_by_dir,
        trace=req.trace,
        stages=tuple(req.stages),
    )


def fill_trace_span_pb(sp, span: dict, t_schema=None, proj=()):
    """Fill one trace/v1 Span message from an engine span dict; tags
    outside `proj` (when non-empty) are dropped, tag types resolve from
    the schema when known.  Shared by TraceService.Query and the BydbQL
    trace catalog so the two wire surfaces cannot drift."""
    sp.span = span.get("span", b"")
    for k, v in span.get("tags", {}).items():
        if proj and k not in proj:
            continue
        ttype = None
        if t_schema is not None:
            try:
                ttype = t_schema.tag(k).type
            except KeyError:
                ttype = None
        t = sp.tags.add(key=k)
        t.value.CopyFrom(py_to_tag_value(v, ttype))


def fill_property_pb(m, group, name, pid, tags: dict, mod_revision=0, proj=()):
    """Fill one property/v1 Property message; shared by
    PropertyService.Query and the BydbQL property catalog."""
    m.metadata.group = group
    m.metadata.name = name
    m.metadata.mod_revision = int(mod_revision)
    m.id = str(pid)
    for k, v in tags.items():
        if proj and k not in proj:
            continue
        t = m.tags.add(key=k)
        t.value.CopyFrom(py_to_tag_value(v))


# -- schema objects --------------------------------------------------------


def group_to_internal(g) -> isch.Group:
    ro = g.resource_opts
    opts = isch.ResourceOpts(
        shard_num=ro.shard_num or 1,
        replicas=ro.replicas,
        segment_interval=_interval_to_internal(ro.segment_interval, isch.IntervalRule(1, "day")),
        ttl=_interval_to_internal(ro.ttl, isch.IntervalRule(7, "day")),
        stages=tuple(s.name for s in ro.stages),
    )
    return isch.Group(
        name=g.metadata.name,
        catalog=_CATALOG.get(g.catalog, isch.Catalog.MEASURE),
        resource_opts=opts,
    )


def _interval_to_internal(iv, default: isch.IntervalRule) -> isch.IntervalRule:
    if iv.num == 0:
        return default
    return isch.IntervalRule(iv.num, _IV_UNIT.get(iv.unit, "day"))


def group_to_pb(g: isch.Group):
    m = pb.common_common_pb2.Group()
    m.metadata.name = g.name
    m.catalog = _CATALOG_INV.get(g.catalog, 2)
    ro = m.resource_opts
    ro.shard_num = g.resource_opts.shard_num
    ro.replicas = g.resource_opts.replicas
    ro.segment_interval.num = g.resource_opts.segment_interval.num
    ro.segment_interval.unit = _IV_UNIT_INV[g.resource_opts.segment_interval.unit]
    ro.ttl.num = g.resource_opts.ttl.num
    ro.ttl.unit = _IV_UNIT_INV[g.resource_opts.ttl.unit]
    for s in g.resource_opts.stages:
        ro.stages.add(name=s, shard_num=g.resource_opts.shard_num)
    return m


def measure_to_internal(m) -> isch.Measure:
    tags: list[isch.TagSpec] = []
    fams: list[tuple[str, int]] = []
    for fam in m.tag_families:
        fams.append((fam.name, len(fam.tags)))
        for t in fam.tags:
            tags.append(isch.TagSpec(t.name, _TAG_TYPE.get(t.type, isch.TagType.STRING)))
    fields = tuple(
        isch.FieldSpec(f.name, _FIELD_TYPE.get(f.field_type, isch.FieldType.FLOAT))
        for f in m.fields
    )
    return isch.Measure(
        group=m.metadata.group,
        name=m.metadata.name,
        tags=tuple(tags),
        fields=fields,
        entity=isch.Entity(tuple(m.entity.tag_names)),
        interval=m.interval,
        index_mode=m.index_mode,
        tag_families=tuple(fams),
    )


def measure_to_pb(m: isch.Measure):
    out = pb.database_schema_pb2.Measure()
    out.metadata.group = m.group
    out.metadata.name = m.name
    for fam_name, tag_names in _families_of(m):
        fam = out.tag_families.add(name=fam_name)
        for tn in tag_names:
            t = m.tag(tn)
            fam.tags.add(name=t.name, type=_TAG_TYPE_INV[t.type])
    for f in m.fields:
        out.fields.add(name=f.name, field_type=_FIELD_TYPE_INV[f.type])
    out.entity.tag_names.extend(m.entity.tag_names)
    out.interval = m.interval
    out.index_mode = m.index_mode
    return out


def stream_to_internal(s) -> isch.Stream:
    tags: list[isch.TagSpec] = []
    fams: list[tuple[str, int]] = []
    for fam in s.tag_families:
        fams.append((fam.name, len(fam.tags)))
        for t in fam.tags:
            tags.append(isch.TagSpec(t.name, _TAG_TYPE.get(t.type, isch.TagType.STRING)))
    return isch.Stream(
        group=s.metadata.group,
        name=s.metadata.name,
        tags=tuple(tags),
        entity=tuple(s.entity.tag_names),
        tag_families=tuple(fams),
    )


def stream_to_pb(s: isch.Stream):
    out = pb.database_schema_pb2.Stream()
    out.metadata.group = s.group
    out.metadata.name = s.name
    for fam_name, tag_names in _families_of(s):
        fam = out.tag_families.add(name=fam_name)
        for tn in tag_names:
            t = s.tag(tn)
            fam.tags.add(name=t.name, type=_TAG_TYPE_INV[t.type])
    out.entity.tag_names.extend(s.entity)
    return out


def trace_to_internal(t) -> isch.Trace:
    """database/v1 Trace schema (schema.proto:247): flat TraceTagSpec
    list + trace/span/timestamp tag names."""
    return isch.Trace(
        group=t.metadata.group,
        name=t.metadata.name,
        tags=tuple(
            isch.TagSpec(s.name, _TAG_TYPE.get(s.type, isch.TagType.STRING))
            for s in t.tags
        ),
        trace_id_tag=t.trace_id_tag_name,
        timestamp_tag=t.timestamp_tag_name,
        span_id_tag=t.span_id_tag_name,
    )


def trace_to_pb(t: isch.Trace):
    out = pb.database_schema_pb2.Trace()
    out.metadata.group = t.group
    out.metadata.name = t.name
    for s in t.tags:
        out.tags.add(name=s.name, type=_TAG_TYPE_INV[s.type])
    out.trace_id_tag_name = t.trace_id_tag
    out.timestamp_tag_name = t.timestamp_tag
    out.span_id_tag_name = t.span_id_tag
    return out


def property_schema_to_internal(p) -> isch.PropertySchema:
    """database/v1 Property schema (schema.proto:224)."""
    return isch.PropertySchema(
        group=p.metadata.group,
        name=p.metadata.name,
        tags=tuple(
            isch.TagSpec(s.name, _TAG_TYPE.get(s.type, isch.TagType.STRING))
            for s in p.tags
        ),
    )


def property_schema_to_pb(p: isch.PropertySchema):
    out = pb.database_schema_pb2.Property()
    out.metadata.group = p.group
    out.metadata.name = p.name
    for s in p.tags:
        out.tags.add(name=s.name, type=_TAG_TYPE_INV[s.type])
    return out


# -- index rules / bindings / topn (database/v1) ----------------------------

_IDX_TYPE = {1: "inverted", 2: "skipping", 3: "tree"}
_IDX_TYPE_INV = {v: k for k, v in _IDX_TYPE.items()}


def index_rule_to_internal(r) -> isch.IndexRule:
    return isch.IndexRule(
        group=r.metadata.group,
        name=r.metadata.name,
        tags=tuple(r.tags),
        type=_IDX_TYPE.get(r.type, "inverted"),
        analyzer=r.analyzer,
    )


def index_rule_to_pb(r: isch.IndexRule):
    out = pb.database_schema_pb2.IndexRule()
    out.metadata.group = r.group
    out.metadata.name = r.name
    out.tags.extend(r.tags)
    out.type = _IDX_TYPE_INV.get(r.type, 1)
    out.analyzer = r.analyzer
    return out


def index_rule_binding_to_internal(b) -> isch.IndexRuleBinding:
    return isch.IndexRuleBinding(
        group=b.metadata.group,
        name=b.metadata.name,
        rules=tuple(b.rules),
        subject_catalog=_CATALOG.get(
            b.subject.catalog, isch.Catalog.MEASURE
        ).value,
        subject_name=b.subject.name,
        begin_at_millis=ts_to_millis(b.begin_at),
        expire_at_millis=ts_to_millis(b.expire_at),
    )


def index_rule_binding_to_pb(b: isch.IndexRuleBinding):
    out = pb.database_schema_pb2.IndexRuleBinding()
    out.metadata.group = b.group
    out.metadata.name = b.name
    out.rules.extend(b.rules)
    out.subject.catalog = _CATALOG_INV.get(
        isch.Catalog(b.subject_catalog), 2
    )
    out.subject.name = b.subject_name
    if b.begin_at_millis:
        out.begin_at.CopyFrom(millis_to_ts(b.begin_at_millis))
    if b.expire_at_millis:
        out.expire_at.CopyFrom(millis_to_ts(b.expire_at_millis))
    return out


_SORT_TOPN_RULE = {0: "all", 1: "desc", 2: "asc"}


def topn_to_internal(t) -> isch.TopNAggregation:
    src_group = t.source_measure.group
    return isch.TopNAggregation(
        group=t.metadata.group,
        name=t.metadata.name,
        source_measure=t.source_measure.name,
        field_name=t.field_name,
        # SORT_UNSPECIFIED on a RULE keeps BOTH directions (the rule can
        # then serve top AND bottom queries; ref topn.go sort handling)
        field_value_sort=_SORT_TOPN_RULE.get(t.field_value_sort, "desc"),
        group_by_tag_names=tuple(t.group_by_tag_names),
        counters_number=t.counters_number or 1000,
        lru_size=t.lru_size or 10,
        source_group="" if src_group in ("", t.metadata.group) else src_group,
        criteria=(
            json_format.MessageToDict(t.criteria)
            if t.HasField("criteria")
            else None
        ),
    )


def topn_to_pb(t: isch.TopNAggregation):
    out = pb.database_schema_pb2.TopNAggregation()
    out.metadata.group = t.group
    out.metadata.name = t.name
    out.source_measure.group = t.source_group or t.group
    out.source_measure.name = t.source_measure
    out.field_name = t.field_name
    out.field_value_sort = {"asc": 2, "desc": 1}.get(t.field_value_sort, 0)
    out.group_by_tag_names.extend(t.group_by_tag_names)
    out.counters_number = t.counters_number
    out.lru_size = t.lru_size
    if t.criteria:
        json_format.ParseDict(t.criteria, out.criteria)
    return out
