"""Multi-tenant QoS plane (docs/robustness.md "Multi-tenant QoS").

Tenant identity derives from the group namespace (``tenancy``); the
admission plane (``plane``) enforces per-tenant ingest token buckets and
weighted query concurrency caps, shedding with the existing retryable
``ServerBusy`` wire kind.  Sits at the platform layer (like ``obs``) so
storage, query and the fabric can all consult it without upward edges.
"""

from banyandb_tpu.qos.plane import QosPlane, TenantLimits, global_qos, reset_qos
from banyandb_tpu.qos.tenancy import (
    DEFAULT_TENANT,
    current_tenant,
    tenant_of_group,
    tenant_scope,
)

__all__ = [
    "DEFAULT_TENANT",
    "QosPlane",
    "TenantLimits",
    "current_tenant",
    "global_qos",
    "reset_qos",
    "tenant_of_group",
    "tenant_scope",
]
