"""The tenant admission plane: quotas, weighted query admission.

One ``QosPlane`` per process (like the global meter).  Three gates:

- **Ingest rate** — a per-tenant token bucket over accepted points
  (``write_rate`` points/s, ``write_burst`` tokens of headroom).  Over
  quota sheds IMMEDIATELY with ``ServerBusy`` — the existing retryable
  ``kind="shed"`` on the bus wire (cluster/rpc.py), RESOURCE_EXHAUSTED
  on the proto wire — never a silent drop.  The bucket admits into debt
  (one oversized batch is charged, the NEXT writes shed until the
  refill catches up) so no batch size can wedge a tenant permanently.
- **In-flight write bytes** — enforced by the memory protector's
  per-tenant charge accounting (admin/protector.py); this module only
  serves the limit.
- **Query concurrency** — per-tenant ``max_concurrent`` caps plus an
  optional global pool (``query_global_max``) shared by WEIGHT: a
  queued query waits only while its deadline budget has headroom
  (clamped to ``max_queue_s``), then sheds retryably.  Under global
  contention the waiter whose tenant has the fewest active slots per
  unit weight admits first.

Defaults are generous (every limit 0 = unlimited), so a single-tenant
deployment with ``BYDB_QOS`` on — the default — takes the fast paths
and stays byte-identical to pre-QoS behavior (tests/test_qos.py pins
this).  Per-tenant limits come from the ``BYDB_QOS_TENANTS`` JSON env
(``{"acme": {"write_rate": 1000, "weight": 4}, "*": {...}}``; ``*`` is
the default for unlisted tenants).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Optional

from banyandb_tpu.obs.metrics import global_meter
from banyandb_tpu.qos.tenancy import tenant_of_group
from banyandb_tpu.utils.envflag import env_flag, env_float, env_int, env_str


def _server_busy(msg: str):
    # lazy boundary (docs/linting.md layering): the canonical shed
    # exception lives in admin/protector; its class NAME is what the
    # rpc fabric serializes as kind="shed"
    from banyandb_tpu.admin.protector import ServerBusy

    return ServerBusy(msg)


@dataclasses.dataclass(frozen=True)
class TenantLimits:
    """Per-tenant quota set; 0 anywhere = unlimited (the generous
    default — no behavior change until an operator configures less)."""

    write_rate: float = 0.0  # accepted points/s at ingest
    write_burst: float = 0.0  # bucket headroom (0 -> 2s of write_rate)
    inflight_bytes: int = 0  # concurrent in-flight write bytes
    max_concurrent: int = 0  # concurrent queries
    weight: float = 1.0  # share of the global query pool
    cache_bytes: int = 0  # serving-cache partition budget (0 -> default)
    max_signatures: int = 0  # streamagg registrations (manual + auto)

    def burst(self) -> float:
        return self.write_burst or max(2.0 * self.write_rate, 1.0)


_LIMIT_FIELDS = {f.name for f in dataclasses.fields(TenantLimits)}


def _parse_limits(doc) -> TenantLimits:
    """One tenant's limit doc -> TenantLimits; malformed values fall
    back to the generous defaults with a warning (same policy as
    malformed BYDB_QOS_TENANTS JSON — a typo'd tuning knob must never
    keep a server from booting)."""
    kw = {}
    try:
        items = dict(doc or {}).items()
    except (TypeError, ValueError):
        items = ()
    for k, v in items:
        if k not in _LIMIT_FIELDS:
            continue
        try:
            kw[k] = type(getattr(TenantLimits, k))(v)
        except (TypeError, ValueError):
            import logging

            logging.getLogger("banyandb.qos").warning(
                "malformed QoS limit %s=%r ignored (default kept)", k, v
            )
    return TenantLimits(**kw)


class _TokenBucket:
    __slots__ = ("rate", "burst", "tokens", "t_last")

    def __init__(self, rate: float, burst: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.t_last = time.monotonic()

    def take(self, n: float) -> bool:
        now = time.monotonic()
        self.tokens = min(
            self.burst, self.tokens + (now - self.t_last) * self.rate
        )
        self.t_last = now
        if self.tokens <= 0.0:
            return False
        self.tokens -= n  # admit into debt; future takes shed until refill
        return True


class QosPlane:
    def __init__(
        self,
        *,
        enabled: Optional[bool] = None,
        tenants: Optional[dict] = None,
        query_global_max: Optional[int] = None,
        max_queue_s: Optional[float] = None,
    ):
        self.enabled = (
            env_flag("BYDB_QOS", default=True) if enabled is None else enabled
        )
        if tenants is None:
            tenants = {}
            raw = env_str("BYDB_QOS_TENANTS").strip()
            if raw:
                try:
                    tenants = json.loads(raw)
                except ValueError:
                    import logging

                    logging.getLogger("banyandb.qos").warning(
                        "malformed BYDB_QOS_TENANTS ignored (%r)", raw
                    )
                    tenants = {}
        self._default_limits = _parse_limits(tenants.get("*", {}))
        self._limits = {
            t: _parse_limits(doc)
            for t, doc in tenants.items()
            if t != "*"
        }
        self.query_global_max = (
            env_int("BYDB_QOS_QUERY_GLOBAL_MAX", 0)
            if query_global_max is None
            else query_global_max
        )
        self.max_queue_s = (
            env_float("BYDB_QOS_MAX_QUEUE_S", 5.0)
            if max_queue_s is None
            else max_queue_s
        )
        # RLock: the shed path counts (takes the lock) while still
        # inside the admission condition's critical section
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._buckets: dict[str, _TokenBucket] = {}
        self._active: dict[str, int] = {}
        self._waiting: dict[str, int] = {}
        # per-tenant counters mirrored into the meter with a tenant label
        self._counts: dict[str, dict[str, int]] = {}

    # -- config --------------------------------------------------------------
    def limits(self, tenant: str) -> TenantLimits:
        return self._limits.get(tenant, self._default_limits)

    def inflight_limit(self, tenant: str) -> int:
        """The protector's per-tenant in-flight byte budget source."""
        if not self.enabled:
            return 0
        return self.limits(tenant).inflight_bytes

    def _count(self, tenant: str, key: str, n: int = 1) -> None:
        with self._lock:
            rec = self._counts.setdefault(tenant, {})
            rec[key] = rec.get(key, 0) + n
        global_meter().counter_add(f"qos_{key}", float(n), {"tenant": tenant})

    # -- ingest --------------------------------------------------------------
    def admit_write(self, group: str, points: int) -> str:
        """Charge ``points`` against the tenant's ingest bucket; -> the
        tenant name.  Over quota raises ServerBusy (retryable shed)."""
        tenant = tenant_of_group(group)
        if not self.enabled:
            return tenant
        lim = self.limits(tenant)
        if lim.write_rate <= 0:
            self._count(tenant, "write_admitted")
            return tenant
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None or bucket.rate != lim.write_rate:
                bucket = self._buckets[tenant] = _TokenBucket(
                    lim.write_rate, lim.burst()
                )
            ok = bucket.take(float(points))
        if not ok:
            self._count(tenant, "write_shed")
            raise _server_busy(
                f"tenant {tenant!r} over ingest quota "
                f"({lim.write_rate:g} points/s); retry after backoff"
            )
        self._count(tenant, "write_admitted")
        return tenant

    # -- queries -------------------------------------------------------------
    def admit_query(self, group: str, deadline_s: Optional[float] = None):
        """Context manager holding one query slot for ``group``'s tenant;
        entering may queue (deadline-aware) and raises ServerBusy when
        the wait budget runs out.  ``.tenant`` / ``.queued_ms`` are
        readable after entry (the ``qos`` span tags)."""
        return _QueryTicket(self, tenant_of_group(group), deadline_s)

    def _eligible_locked(self, tenant: str, cap: int) -> bool:
        if cap and self._active.get(tenant, 0) >= cap:
            return False
        gmax = self.query_global_max
        if gmax:
            if sum(self._active.values()) >= gmax:
                return False
            contenders = set(self._waiting) | {tenant}
            if len(contenders) > 1:
                # weighted deficit: fewest active slots per unit weight
                # admits first (ties broken by name for determinism)
                def prio(t: str):
                    w = max(self.limits(t).weight, 1e-9)
                    return (self._active.get(t, 0) / w, t)

                if min(contenders, key=prio) != tenant:
                    return False
        return True

    def _acquire_query(
        self, tenant: str, deadline_s: Optional[float]
    ) -> float:
        """-> queued milliseconds.  Raises ServerBusy on wait-budget
        exhaustion (the explicit retryable rejection)."""
        if not self.enabled:
            return 0.0
        cap = self.limits(tenant).max_concurrent
        if cap <= 0 and self.query_global_max <= 0:
            self._count(tenant, "query_admitted")
            return 0.0
        budget = self.max_queue_s
        if deadline_s is not None:
            budget = max(min(budget, deadline_s), 0.0)
        t0 = time.monotonic()
        t_end = t0 + budget
        with self._cond:
            if self._eligible_locked(tenant, cap):
                self._active[tenant] = self._active.get(tenant, 0) + 1
                queued = False
            else:
                queued = True
                self._waiting[tenant] = self._waiting.get(tenant, 0) + 1
                try:
                    while True:
                        remaining = t_end - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(min(remaining, 0.25))
                        if self._eligible_locked(tenant, cap):
                            self._active[tenant] = (
                                self._active.get(tenant, 0) + 1
                            )
                            remaining = 1.0  # admitted marker
                            break
                    admitted = remaining > 0
                finally:
                    n = self._waiting.get(tenant, 1) - 1
                    if n:
                        self._waiting[tenant] = n
                    else:
                        self._waiting.pop(tenant, None)
                if not admitted:
                    self._count(tenant, "query_shed")
                    raise _server_busy(
                        f"tenant {tenant!r} query admission queue timed "
                        f"out after {budget:.2f}s; retry after backoff"
                    )
        queued_ms = (time.monotonic() - t0) * 1000.0
        if queued:
            self._count(tenant, "query_queued")
            global_meter().observe(
                "qos_queue_ms", queued_ms, {"tenant": tenant}
            )
        self._count(tenant, "query_admitted")
        return queued_ms

    def _release_query(self, tenant: str) -> None:
        if not self.enabled:
            return
        cap = self.limits(tenant).max_concurrent
        if cap <= 0 and self.query_global_max <= 0:
            return
        with self._cond:
            n = self._active.get(tenant, 1) - 1
            if n:
                self._active[tenant] = n
            else:
                self._active.pop(tenant, None)
            self._cond.notify_all()

    # -- streamagg registrations --------------------------------------------
    def admit_streamagg(self, group: str, existing: int) -> str:
        """Gate one NEW streamagg registration for ``group``'s tenant
        against its signature cap (``existing`` = live signatures the
        tenant already holds)."""
        tenant = tenant_of_group(group)
        if not self.enabled:
            return tenant
        cap = self.limits(tenant).max_signatures
        if cap and existing >= cap:
            self._count(tenant, "streamagg_rejected")
            raise _server_busy(
                f"tenant {tenant!r} at its streamagg signature cap "
                f"({cap}); unregister one or raise the quota"
            )
        return tenant

    # -- exposition ----------------------------------------------------------
    def export_gauges(self, meter=None) -> None:
        m = meter or global_meter()
        m.gauge_set("qos_enabled", float(self.enabled))
        with self._lock:
            active = dict(self._active)
            waiting = dict(self._waiting)
            # every tenant the plane has ever counted: gauges must
            # OVERWRITE to zero when a tenant drains, or an idle
            # tenant's last nonzero value sticks forever (gauge_set
            # persists last value)
            known = set(self._counts) | set(active) | set(waiting)
        for t in known:
            m.gauge_set(
                "qos_query_active", float(active.get(t, 0)), {"tenant": t}
            )
            m.gauge_set(
                "qos_query_waiting", float(waiting.get(t, 0)), {"tenant": t}
            )

    def stats(self) -> dict:
        with self._lock:
            tenants = sorted(
                set(self._counts) | set(self._limits) | set(self._active)
            )
            out = {}
            for t in tenants:
                lim = self.limits(t)
                out[t] = {
                    **{
                        k: self._counts.get(t, {}).get(k, 0)
                        for k in (
                            "write_admitted",
                            "write_shed",
                            "query_admitted",
                            "query_queued",
                            "query_shed",
                            "streamagg_rejected",
                        )
                    },
                    "active": self._active.get(t, 0),
                    "limits": dataclasses.asdict(lim),
                }
        return {
            "enabled": self.enabled,
            "query_global_max": self.query_global_max,
            "max_queue_s": self.max_queue_s,
            "tenants": out,
        }


class _QueryTicket:
    """The admit_query context manager (one query slot)."""

    __slots__ = ("_plane", "tenant", "_deadline_s", "queued_ms", "_held")

    def __init__(self, plane: QosPlane, tenant: str, deadline_s):
        self._plane = plane
        self.tenant = tenant
        self._deadline_s = deadline_s
        self.queued_ms = 0.0
        self._held = False

    def __enter__(self) -> "_QueryTicket":
        self.queued_ms = self._plane._acquire_query(
            self.tenant, self._deadline_s
        )
        self._held = True
        return self

    def __exit__(self, *exc) -> None:
        if self._held:
            self._held = False
            self._plane._release_query(self.tenant)


# -- process-global plane -----------------------------------------------------
_PLANE: Optional[QosPlane] = None
_PLANE_LOCK = threading.Lock()


def global_qos() -> QosPlane:
    global _PLANE
    p = _PLANE
    if p is None:
        with _PLANE_LOCK:
            if _PLANE is None:
                _PLANE = QosPlane()
            p = _PLANE
    return p


def reset_qos() -> QosPlane:
    """Re-read the env (tests / harnesses that reconfigure quotas)."""
    global _PLANE
    with _PLANE_LOCK:
        _PLANE = QosPlane()
        return _PLANE
