"""Tenant identity: the group namespace is the tenant dimension.

The reference's model already scopes every resource by group; multi-
tenant deployments name groups ``<tenant><sep><rest>`` (default
separator ``.``: ``acme.metrics`` belongs to tenant ``acme``).  A group
without the separator — every group this repo ever created before the
QoS plane — maps to the ``default`` tenant, so untenanted traffic is
byte-identical to pre-QoS behavior (the parity pin in tests/test_qos.py).

``tenant_scope``/``current_tenant`` carry the active tenant down the
query/ingest call stack on a contextvar, so layers that must partition
per tenant (the serving cache) need no signature changes.
"""

from __future__ import annotations

import contextlib
import contextvars
import os

DEFAULT_TENANT = "default"

_current: contextvars.ContextVar[str] = contextvars.ContextVar(
    "bydb_tenant", default=DEFAULT_TENANT
)


def tenant_separator() -> str:
    from banyandb_tpu.utils.envflag import env_str

    return env_str("BYDB_QOS_TENANT_SEP", ".") or "."


def tenant_of_group(group: str) -> str:
    """Group name -> tenant: the namespace prefix before the separator;
    groups without one (all pre-QoS groups) are the default tenant."""
    if not group:
        return DEFAULT_TENANT
    sep = tenant_separator()
    head, found, _rest = group.partition(sep)
    if not found or not head:
        return DEFAULT_TENANT
    return head


def current_tenant() -> str:
    return _current.get()


@contextlib.contextmanager
def tenant_scope(tenant: str):
    """Bind the active tenant for the enclosed work (thread-local via
    contextvars; restored on exit even across exceptions)."""
    token = _current.set(tenant or DEFAULT_TENANT)
    try:
        yield tenant
    finally:
        _current.reset(token)
