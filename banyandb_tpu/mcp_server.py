"""MCP server: BanyanDB for LLM agents over the Model Context Protocol.

Analog of the reference's mcp/ tier (a TypeScript MCP server exposing
list_groups_schemas / list_resources_bydbql / validate_bydbql /
query tools, /root/reference/mcp/src/server/mcp.ts) re-implemented as a
self-contained Python JSON-RPC 2.0 stdio server — no SDK dependency,
just the MCP wire shapes (initialize, tools/list, tools/call).

Run: python -m banyandb_tpu.mcp_server --root /var/lib/banyandb

Tools:
    list_groups_schemas  groups + their measure/stream/trace schemas
    list_resources       resources of one group with tag/field detail
    validate_bydbql      parse a BydbQL statement, report errors
    execute_bydbql       parse + run a BydbQL statement, JSON results
    topn_query           ranked TopN over a pre-aggregation rule
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

PROTOCOL_VERSION = "2024-11-05"


def _schema_obj(obj) -> dict:
    from banyandb_tpu.api.schema import _to_jsonable

    return _to_jsonable(obj)


class McpServer:
    def __init__(self, root: str | Path):
        from banyandb_tpu.api.schema import SchemaRegistry
        from banyandb_tpu.models.measure import MeasureEngine
        from banyandb_tpu.models.property import PropertyEngine
        from banyandb_tpu.models.stream import StreamEngine
        from banyandb_tpu.models.trace import TraceEngine

        root = Path(root)
        self.registry = SchemaRegistry(root)
        self.measure = MeasureEngine(self.registry, root / "data")
        self.stream = StreamEngine(self.registry, root / "data")
        self.trace = TraceEngine(self.registry, root / "data")
        self.property = PropertyEngine(self.registry, root / "data")

    # -- tool implementations ----------------------------------------------
    def list_groups_schemas(self) -> dict:
        out = {}
        for g in self.registry.list_groups():
            out[g.name] = {
                "catalog": g.catalog.value,
                "shard_num": g.resource_opts.shard_num,
                "measures": [m.name for m in self.registry.list_measures(g.name)],
                "streams": [s.name for s in self.registry.list_streams(g.name)],
                "traces": [t.name for t in self.registry.list_traces(g.name)],
                "topn_rules": [r.name for r in self.registry.list_topn(g.name)],
            }
        return out

    def list_resources(self, group: str) -> dict:
        return {
            "measures": [_schema_obj(m) for m in self.registry.list_measures(group)],
            "streams": [_schema_obj(s) for s in self.registry.list_streams(group)],
            "traces": [_schema_obj(t) for t in self.registry.list_traces(group)],
            "index_rules": [
                _schema_obj(r) for r in self.registry.list_index_rules(group)
            ],
        }

    def validate_bydbql(self, query: str) -> dict:
        from banyandb_tpu import bydbql

        try:
            catalog, req = bydbql.parse_with_catalog(query)
        except bydbql.QLError as e:
            return {"valid": False, "error": str(e)}
        return {
            "valid": True,
            "catalog": catalog,
            "group": req.groups[0],
            "resource": req.name,
        }

    def execute_bydbql(self, query: str) -> dict:
        from banyandb_tpu import bydbql
        from banyandb_tpu.server import result_to_json

        catalog, req = bydbql.parse_with_catalog(query)
        if catalog == "stream":
            res = self.stream.query(req)
        elif catalog == "measure":
            res = self.measure.query(req)
        else:
            raise ValueError(
                f"MCP execute supports measure/stream QL; got {catalog}"
            )
        return {"catalog": catalog, "result": result_to_json(res)}

    def topn_query(
        self, group: str, rule: str, begin_millis: int, end_millis: int, n: int = 10
    ) -> dict:
        from banyandb_tpu.api.model import TimeRange
        from banyandb_tpu.models import topn as topn_mod

        ranked = topn_mod.query_topn(
            self.measure, group, rule, TimeRange(begin_millis, end_millis), n=n
        )
        return {
            "items": [
                {"entity": list(e), "value": v} for e, v in ranked
            ]
        }

    # -- MCP wire -----------------------------------------------------------
    TOOLS = [
        {
            "name": "list_groups_schemas",
            "description": "List all groups with their resource inventories.",
            "inputSchema": {"type": "object", "properties": {}},
        },
        {
            "name": "list_resources",
            "description": "Full schemas (tags, fields, entities, index "
            "rules) of one group's resources.",
            "inputSchema": {
                "type": "object",
                "properties": {"group": {"type": "string"}},
                "required": ["group"],
            },
        },
        {
            "name": "validate_bydbql",
            "description": "Parse a BydbQL statement without executing it.",
            "inputSchema": {
                "type": "object",
                "properties": {"query": {"type": "string"}},
                "required": ["query"],
            },
        },
        {
            "name": "execute_bydbql",
            "description": "Execute a BydbQL statement (measure/stream "
            "catalogs) and return JSON results.",
            "inputSchema": {
                "type": "object",
                "properties": {"query": {"type": "string"}},
                "required": ["query"],
            },
        },
        {
            "name": "topn_query",
            "description": "Ranked entities from a TopN pre-aggregation rule.",
            "inputSchema": {
                "type": "object",
                "properties": {
                    "group": {"type": "string"},
                    "rule": {"type": "string"},
                    "begin_millis": {"type": "integer"},
                    "end_millis": {"type": "integer"},
                    "n": {"type": "integer"},
                },
                "required": ["group", "rule", "begin_millis", "end_millis"],
            },
        },
    ]

    def handle(self, msg: dict) -> dict | None:
        """One JSON-RPC request -> response dict (None for notifications)."""
        method = msg.get("method", "")
        mid = msg.get("id")
        if method.startswith("notifications/"):
            return None
        try:
            if method == "initialize":
                result = {
                    "protocolVersion": PROTOCOL_VERSION,
                    "capabilities": {"tools": {}},
                    "serverInfo": {
                        "name": "banyandb-tpu-mcp",
                        "version": "0.2.0",
                    },
                }
            elif method == "tools/list":
                result = {"tools": self.TOOLS}
            elif method == "tools/call":
                params = msg.get("params", {})
                name = params.get("name")
                args = params.get("arguments", {}) or {}
                fn = {
                    "list_groups_schemas": self.list_groups_schemas,
                    "list_resources": self.list_resources,
                    "validate_bydbql": self.validate_bydbql,
                    "execute_bydbql": self.execute_bydbql,
                    "topn_query": self.topn_query,
                }.get(name)
                if fn is None:
                    raise ValueError(f"unknown tool {name!r}")
                payload = fn(**args)
                result = {
                    "content": [
                        {"type": "text", "text": json.dumps(payload, default=str)}
                    ]
                }
            elif method == "ping":
                result = {}
            else:
                return {
                    "jsonrpc": "2.0",
                    "id": mid,
                    "error": {"code": -32601, "message": f"unknown method {method}"},
                }
            return {"jsonrpc": "2.0", "id": mid, "result": result}
        except Exception as e:  # noqa: BLE001 - reported in-band
            return {
                "jsonrpc": "2.0",
                "id": mid,
                "error": {"code": -32000, "message": f"{type(e).__name__}: {e}"},
            }

    def serve_stdio(self, stdin=None, stdout=None) -> None:
        stdin = stdin or sys.stdin
        stdout = stdout or sys.stdout
        for line in stdin:
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except json.JSONDecodeError:
                continue
            resp = self.handle(msg)
            if resp is not None:
                stdout.write(json.dumps(resp) + "\n")
                stdout.flush()


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser("banyandb-tpu MCP server")
    ap.add_argument("--root", required=True)
    args = ap.parse_args(argv)
    McpServer(args.root).serve_stdio()


if __name__ == "__main__":
    main()
