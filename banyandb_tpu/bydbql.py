"""BydbQL: the SQL-ish query language (pkg/bydbql analog).

Grammar (subset matching what the engines support; reference grammar at
pkg/bydbql/grammar.go, parser.go:67):

    SELECT <projection> FROM MEASURE <name> IN <group>
        [ TIME > <millis> AND TIME < <millis> | TIME BETWEEN a AND b ]
        [ WHERE <cond> (AND <cond>)* ]
        [ GROUP BY tag (, tag)* ]
        [ TOP <n> BY <field> [ASC|DESC] ]
        [ ORDER BY TIME [ASC|DESC] ]
        [ LIMIT <n> ] [ OFFSET <n> ]

    projection := * | item (, item)*
    item       := tag | field | fn '(' field ')' | PERCENTILE(field, q, ...)
    fn         := SUM | COUNT | MIN | MAX | MEAN | AVG
    cond       := name op literal | name IN (lit, ...) | name NOT IN (...)
    op         := = | != | < | <= | > | >=
    literal    := number | 'string' | $N   ($N binds params[N-1] —
                  prepared statements)

Hand-written tokenizer + recursive descent -> api.model.QueryRequest.
"""

from __future__ import annotations

import re
from typing import Optional

from banyandb_tpu.api.model import (
    Aggregation,
    Condition,
    GroupBy,
    LogicalExpression,
    QueryRequest,
    TimeRange,
    Top,
)

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<num>-?\d+(?:\.\d+)?)
      | (?P<str>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
      | (?P<param>\$\d+)
      | (?P<op><=|>=|!=|=|<|>|\(|\)|,|\*)
      | (?P<word>[A-Za-z_][A-Za-z0-9_.\-]*)
    )""",
    re.VERBOSE,
)

_AGG_FNS = {"sum", "count", "min", "max", "mean", "avg", "percentile"}


_DUR_UNITS = {
    "ms": 1,
    "s": 1000,
    "m": 60_000,
    "h": 3_600_000,
    "d": 86_400_000,
    "w": 604_800_000,
}
_DUR_PIECE = re.compile(r"(\d+(?:\.\d+)?)(ms|s|m|h|d|w)")


def _time_millis(v) -> int:
    """TIME bound literal -> epoch millis.

    Mirrors the reference transformer (pkg/bydbql/transformer.go:1362):
    int millis pass through; then RFC3339 absolute timestamps; then
    'now' and signed compound durations relative to now ('-2h',
    '-1h30m', '15m') per str2duration.
    """
    import datetime
    import time as _time

    try:
        return int(v)
    except (TypeError, ValueError):
        pass
    s = str(v).strip()
    low = s.lower()
    if low == "now":
        return int(_time.time() * 1000)
    sign, body = 1, s
    if s[:1] in "+-":
        sign, body = (-1 if s[0] == "-" else 1), s[1:]
    pieces = _DUR_PIECE.findall(body)
    if pieces and "".join(n + u for n, u in pieces) == body:
        delta = sum(float(n) * _DUR_UNITS[u] for n, u in pieces)
        return int(_time.time() * 1000) + sign * int(delta)
    try:
        dt = datetime.datetime.fromisoformat(s.replace("Z", "+00:00"))
    except ValueError:
        raise QLError(
            f"bad time literal {s!r} (millis, RFC3339, 'now', or "
            "signed duration like '-1h30m')"
        ) from None
    if dt.tzinfo is None:
        # RFC3339 requires an offset; a naive literal would silently
        # bind to the server's local zone and differ per node
        raise QLError(f"time literal {s!r} needs a UTC offset (RFC3339)")
    return int(dt.timestamp() * 1000)


class QLError(ValueError):
    pass


def _tokenize(text: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if not m:
            if text[pos:].strip():
                raise QLError(f"bad token at: {text[pos:pos+20]!r}")
            break
        pos = m.end()
        for kind in ("num", "str", "param", "op", "word"):
            v = m.group(kind)
            if v is not None:
                out.append((kind, v))
                break
    out.append(("eof", ""))
    return out


class _Parser:
    def __init__(self, tokens, params=()):
        self.toks = tokens
        self.i = 0
        self.params = list(params)

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        # bdlint: disable=wp-shared-state -- a _Parser is constructed per
        # parse() call and never escapes the call stack; every thread
        # cursors its own instance (declaration-based identity merges them)
        self.i += 1
        return t

    def expect_word(self, *words) -> str:
        kind, v = self.next()
        if kind != "word" or v.lower() not in words:
            raise QLError(f"expected {'/'.join(words).upper()}, got {v!r}")
        return v.lower()

    def accept_word(self, *words) -> Optional[str]:
        kind, v = self.peek()
        if kind == "word" and v.lower() in words:
            self.next()
            return v.lower()
        return None

    def expect_op(self, op: str):
        kind, v = self.next()
        if kind != "op" or v != op:
            raise QLError(f"expected {op!r}, got {v!r}")

    def literal(self):
        kind, v = self.next()
        if kind == "num":
            return float(v) if "." in v else int(v)
        if kind == "str":
            return v[1:-1].replace("\\'", "'").replace('\\"', '"')
        if kind == "param":
            # prepared-statement placeholder: $1-based index into params
            # (bydbql/v1 QueryRequest.params analog)
            idx = int(v[1:]) - 1
            if not (0 <= idx < len(self.params)):
                raise QLError(f"parameter {v} not bound ({len(self.params)} given)")
            return self.params[idx]
        if kind == "word":
            return v  # bare identifier treated as string literal
        raise QLError(f"expected literal, got {v!r}")


def parse(text: str, params=()) -> QueryRequest:
    return parse_with_catalog(text, params)[1]


def parse_with_catalog(text: str, params=()) -> tuple[str, QueryRequest]:
    """-> (catalog, request); catalog is measure|stream|trace|property.
    `params` bind $1..$n prepared-statement placeholders in literal
    positions (pkg/bydbql prepared statements analog)."""
    p = _Parser(_tokenize(text), params)
    p.expect_word("select")

    # ---- projection ----
    projections: list = []
    agg: Optional[Aggregation] = None
    if p.peek() == ("op", "*"):
        p.next()
    else:
        while True:
            kind, v = p.next()
            if kind != "word":
                raise QLError(f"bad projection item {v!r}")
            name = v
            if p.peek() == ("op", "(") and name.lower() in _AGG_FNS:
                p.next()
                field = p.next()[1]
                fn = "mean" if name.lower() == "avg" else name.lower()
                qs: list[float] = []
                while p.peek() == ("op", ","):
                    p.next()
                    qs.append(float(p.next()[1]))
                p.expect_op(")")
                if agg is not None:
                    raise QLError("only one aggregate per query")
                agg = Aggregation(fn, field, tuple(qs))
            else:
                projections.append(name)
            if p.peek() == ("op", ","):
                p.next()
                continue
            break

    p.expect_word("from")
    catalog = p.expect_word("measure", "stream", "trace", "property")
    name = p.next()[1]
    p.expect_word("in")
    group = p.next()[1]

    begin, end = 0, 2**62
    criteria = None
    group_by = None
    top = None
    limit, offset = 100, 0
    order_by_ts = ""
    order_by_tag, order_by_dir = "", "asc"

    def add_cond(c: Condition):
        nonlocal criteria
        criteria = c if criteria is None else LogicalExpression("and", criteria, c)

    while True:
        kw = p.accept_word(
            "time", "where", "group", "top", "order", "limit", "offset"
        )
        if kw is None:
            kind, v = p.peek()
            if kind == "eof":
                break
            raise QLError(f"unexpected {v!r}")
        if kw == "time":
            kind, op = p.next()
            if kind == "word" and op.lower() == "between":
                begin = _time_millis(p.literal())
                p.expect_word("and")
                end = _time_millis(p.literal()) + 1
            elif op in (">", ">="):
                begin = _time_millis(p.literal()) + (1 if op == ">" else 0)
                if p.accept_word("and"):
                    p.expect_word("time")
                    _, op2 = p.next()
                    if op2 not in ("<", "<="):
                        raise QLError("expected TIME < upper bound")
                    end = _time_millis(p.literal()) + (1 if op2 == "<=" else 0)
            elif op in ("<", "<="):
                end = _time_millis(p.literal()) + (1 if op == "<=" else 0)
            else:
                raise QLError(f"bad TIME operator {op!r}")
        elif kw == "where":
            # full boolean grammar: OR < AND < ( ... ) < condition
            def parse_cond():
                if p.peek() == ("op", "("):
                    p.next()
                    e = parse_or()
                    p.expect_op(")")
                    return e
                tag = p.next()[1]
                neg = p.accept_word("not")
                if neg and not (p.peek()[0] == "word" and p.peek()[1].lower() == "in"):
                    raise QLError("NOT must be followed by IN")
                if p.accept_word("in"):
                    p.expect_op("(")
                    vals = [p.literal()]
                    while p.peek() == ("op", ","):
                        p.next()
                        vals.append(p.literal())
                    p.expect_op(")")
                    return Condition(tag, "not_in" if neg else "in", vals)
                kind, op = p.next()
                opmap = {"=": "eq", "!=": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}
                if op not in opmap:
                    raise QLError(f"bad operator {op!r}")
                return Condition(tag, opmap[op], p.literal())

            def parse_and():
                left = parse_cond()
                while p.accept_word("and"):
                    left = LogicalExpression("and", left, parse_cond())
                return left

            def parse_or():
                left = parse_and()
                while p.accept_word("or"):
                    left = LogicalExpression("or", left, parse_and())
                return left

            add_cond(parse_or())
        elif kw == "group":
            p.expect_word("by")
            tags = [p.next()[1]]
            while p.peek() == ("op", ","):
                p.next()
                tags.append(p.next()[1])
            group_by = GroupBy(tuple(tags))
        elif kw == "top":
            n = int(p.next()[1])
            p.expect_word("by")
            field = p.next()[1]
            sort = p.accept_word("asc", "desc") or "desc"
            top = Top(n, field, sort)
        elif kw == "order":
            p.expect_word("by")
            target = p.next()[1]
            direction = p.accept_word("asc", "desc") or "asc"
            if target.lower() == "time":
                order_by_ts = direction
            else:  # order-by-index: sort rows by this tag's value
                order_by_tag = target
                order_by_dir = direction
        elif kw == "limit":
            limit = int(p.next()[1])
        elif kw == "offset":
            offset = int(p.next()[1])

    return catalog, QueryRequest(
        groups=(group,),
        name=name,
        time_range=TimeRange(begin, end),
        criteria=criteria,
        tag_projection=tuple(projections),
        field_projection=tuple(projections),
        group_by=group_by,
        agg=agg,
        top=top,
        limit=limit,
        offset=offset,
        order_by_ts=order_by_ts,
        order_by_tag=order_by_tag,
        order_by_dir=order_by_dir,
    )
