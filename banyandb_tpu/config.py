"""Config/flag system with environment binding.

Analog of the reference's viper+pflag setup (pkg/config: every flag is
also settable via environment and config file).  Resolution order, most
specific wins:

    CLI flag  >  BYDB_<NAME> env var  >  --config JSON file  >  default

Units (server roles, engines) register their flags up front; `load`
resolves everything at once and returns an attribute-style namespace.
"""

from __future__ import annotations

import argparse
import json
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Optional

from banyandb_tpu.utils.envflag import env_str


@dataclass(frozen=True)
class Flag:
    name: str  # kebab-case CLI name, e.g. "wire-port"
    default: Any
    help: str = ""
    type: Callable = str
    required: bool = False

    @property
    def env_name(self) -> str:
        return "BYDB_" + self.name.upper().replace("-", "_")

    @property
    def attr(self) -> str:
        return self.name.replace("-", "_")


class Settings(dict):
    __getattr__ = dict.__getitem__


class Config:
    def __init__(self, prog: str = "banyandb-tpu"):
        self.prog = prog
        self._flags: dict[str, Flag] = {}
        self.register("config", None, "JSON config file path")

    def register(
        self,
        name: str,
        default: Any,
        help: str = "",
        type: Optional[Callable] = None,
        required: bool = False,
    ) -> None:
        if name in self._flags:
            raise ValueError(f"flag {name!r} registered twice")
        if type is None:
            type = (
                bool
                if isinstance(default, bool)
                else (builtin_type(default) if default is not None else str)
            )
        self._flags[name] = Flag(name, default, help, type, required)

    def load(self, argv: Optional[list[str]] = None) -> Settings:
        ap = argparse.ArgumentParser(self.prog)
        for f in self._flags.values():
            kwargs: dict = {"help": f"{f.help} [env {f.env_name}]"}
            if f.type is bool:
                # --flag / --no-flag so CLI False can override env/file
                # True (tri-state default None = unresolved)
                kwargs["action"] = argparse.BooleanOptionalAction
                kwargs["default"] = None
            else:
                kwargs["type"] = f.type
                kwargs["default"] = None
            ap.add_argument(f"--{f.name}", dest=f.attr, **kwargs)
        ns = ap.parse_args(argv)

        file_vals: dict = {}
        cfg_path = getattr(ns, "config", None) or env_str("BYDB_CONFIG")
        if cfg_path:
            file_vals = json.loads(Path(cfg_path).read_text())

        out = Settings()
        missing = []
        for f in self._flags.values():
            v = getattr(ns, f.attr)
            if v is None and f.env_name in os.environ:
                raw = os.environ[f.env_name]
                v = (
                    raw.lower() in ("1", "true", "yes", "on")
                    if f.type is bool
                    else f.type(raw)
                )
            if v is None and (f.attr in file_vals or f.name in file_vals):
                # config keys may use either the CLI (kebab) or attribute
                # (snake) spelling, matching the viper/pflag convention
                v = file_vals.get(f.attr, file_vals.get(f.name))
                if v is not None:
                    if f.type is bool:
                        # normalize string bools ("false") like env vars do
                        if isinstance(v, str):
                            v = v.lower() in ("1", "true", "yes", "on")
                        else:
                            v = bool(v)
                    else:
                        v = f.type(v)
            if v is None:
                v = f.default
            if v is None and f.required:
                missing.append(f.name)
            out[f.attr] = v
        if missing:
            ap.error(f"missing required flags: {', '.join(missing)}")
        return out


def builtin_type(v: Any) -> Callable:
    return type(v)
