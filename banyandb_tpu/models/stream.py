"""Stream engine: append-only elements (logs).

Analog of banyand/stream (stream.go:40-43): elements have tags but no
fields; each element carries an opaque element-id (+ optional binary
body) stored in the part payload column (the reference keeps element ids
in timestamps.bin).  No version dedup — appends are immutable; dedup by
(series, ts, element_id) is not a stream contract.

Queries are retrieval-shaped (filter + time range + order + limit) and
IO-bound, so they run host-side; tag predicates are still evaluated on
dictionary codes.  Aggregations over streams go through the measure
model (the reference does the same).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

import numpy as np

from banyandb_tpu.api.model import QueryRequest, QueryResult
from banyandb_tpu.api.schema import SchemaRegistry, TagType
from banyandb_tpu.obs import metrics as obs_metrics
from banyandb_tpu.obs.tracer import NOOP_TRACER, Tracer
from banyandb_tpu.query import filter as qfilter
from banyandb_tpu.query import measure_exec
from banyandb_tpu.storage import encoded as _encoded
from banyandb_tpu.storage.memtable import PayloadMemtable
from banyandb_tpu.storage.part import ColumnData
from banyandb_tpu.storage.tsdb import TSDB
from banyandb_tpu.utils import hashing


# Stream schema objects live in the registry (persisted + SCHEMA_SYNC'd
# like measures); re-exported here for engine-local convenience.
from banyandb_tpu.api.schema import Stream  # noqa: E402

_H_QUERY_STREAM = obs_metrics.global_meter().histogram(
    "query_ms", {"engine": "stream"}
)


@dataclass(frozen=True)
class ElementValue:
    """measure/v1 ElementValue analog: one log element."""

    element_id: str
    ts_millis: int
    tags: dict
    body: bytes = b""


def encode_element_payload(element_id: str, body: bytes) -> bytes:
    """THE payload wire format for stream parts (id NUL body) — every
    writer (engine, liaison wqueue) and reader goes through this pair so
    the format can never fork."""
    return element_id.encode() + b"\x00" + body


def decode_element_payload(payload: bytes) -> tuple[str, bytes]:
    elem_id, _, body = payload.partition(b"\x00")
    return elem_id.decode(), body


class StreamEngine:
    def __init__(self, registry: SchemaRegistry, root: str | Path):
        import threading

        self.registry = registry
        self.root = Path(root) / "stream"
        self._tsdbs: dict[str, TSDB] = {}
        self._tsdb_lock = threading.Lock()

    def close(self) -> None:
        """Release every TSDB's index memory/file handles (bdsan fd
        hygiene; reopen stays lazy)."""
        with self._tsdb_lock:
            dbs = list(self._tsdbs.values())
        for db in dbs:
            db.close()

    def create_stream(self, s: Stream) -> None:
        self.registry.create_stream(s)

    def get_stream(self, group: str, name: str) -> Stream:
        return self.registry.get_stream(group, name)

    def _tsdb(self, group: str) -> TSDB:
        with self._tsdb_lock:
            db = self._tsdbs.get(group)
            if db is None:
                g = self.registry.get_group(group)
                db = TSDB(
                    self.root,
                    group,
                    g.resource_opts,
                    mem_factory=lambda: PayloadMemtable("stream"),
                )
                # element-index/bloom sidecars on every flushed/merged part
                # (banyand/stream/index.go + .tff filter analog)
                db.on_part_built = (
                    lambda part_dir, meta, g=group: self._build_part_index(
                        g, part_dir, meta
                    )
                )
                self._tsdbs[group] = db
            return db

    def _index_tags(
        self, group: str, stream_name: str = ""
    ) -> tuple[set[str], set[str]]:
        """(inverted tags, skipping tags) for a stream from the group's
        IndexRules, honoring IndexRuleBinding subject resolution when
        bindings exist (banyand/metadata binding semantics): with any
        binding present in the group, only rules bound to this stream
        apply; with none, every group rule applies (the common
        one-rule-set-per-group case)."""
        rules = self.registry.list_index_rules(group)
        bindings = self.registry.list_index_rule_bindings(group)
        if bindings and stream_name:
            bound: set[str] = set()
            for b in bindings:
                if b.subject_catalog == "stream" and b.subject_name == stream_name:
                    bound.update(b.rules)
            rules = [r for r in rules if r.name in bound]
        inverted: set[str] = set()
        skipping: set[str] = set()
        for r in rules:
            if r.type == "inverted":
                inverted.update(r.tags)
            elif r.type == "skipping":
                skipping.update(r.tags)
        return inverted, skipping

    def _build_part_index(self, group: str, part_dir, meta: dict) -> None:
        if "stream" not in meta:
            return
        from banyandb_tpu.index import element

        inverted, skipping = self._index_tags(group, meta.get("stream", ""))
        if inverted or skipping:
            element.build_part_index(part_dir, inverted, skipping)

    def write(self, group: str, name: str, elements: list[ElementValue]) -> int:
        s = self.get_stream(group, name)
        db = self._tsdb(group)
        shard_num = self.registry.get_group(group).resource_opts.shard_num
        tag_names = [t.name for t in s.tags]
        n = 0
        for e in elements:
            entity = [name.encode()] + [
                hashing.entity_bytes(e.tags[t]) for t in s.entity
            ]
            sid = hashing.series_id(entity)
            shard = hashing.shard_id(sid, shard_num)
            seg = db.segment_for(e.ts_millis)
            tag_bytes = {
                t.name: hashing.entity_bytes(e.tags[t.name])
                if e.tags.get(t.name) is not None
                else b""
                for t in s.tags
            }
            payload = encode_element_payload(e.element_id, e.body)
            seg.shards[shard].ingest(
                lambda mem: mem.append(
                    name, tag_names, e.ts_millis, sid, tag_bytes, payload
                )
            )
            n += 1
        return n

    def flush(self, group: Optional[str] = None) -> list[str]:
        out = []
        for gname, db in self._tsdbs.items():
            if group is None or gname == group:
                out.extend(db.flush_all())
        return out

    def query(
        self, req: QueryRequest, shard_ids=None, tracer=None
    ) -> QueryResult:
        import time as _time

        own_tracer = tracer is None and req.trace
        if own_tracer:
            tracer = Tracer("stream:query")
        t = tracer if tracer is not None else NOOP_TRACER
        t0 = _time.perf_counter()
        try:
            res = self._query_inner(req, shard_ids, t, own_tracer, tracer)
        finally:
            _H_QUERY_STREAM.observe((_time.perf_counter() - t0) * 1000)
        return res

    def _query_inner(
        self, req: QueryRequest, shard_ids, t, own_tracer, tracer
    ) -> QueryResult:
        group = req.groups[0]
        s = self.get_stream(group, req.name)
        db = self._tsdb(group)
        # leaves validate against the schema; flat AND trees additionally
        # drive block pruning + the device mask (OR trees evaluate via
        # the host criteria-tree mask — pruning by AND-intersection would
        # be wrong under OR)
        leaves, expr = measure_exec._lower_criteria(req.criteria)
        for c in leaves:
            s.tag(c.name)
        conds = leaves if not expr else None
        res = QueryResult()
        rows: list[tuple] = []
        with t.span("scan") as ss:
            for attempt in range(3):
                try:
                    rows = self._scan(db, s, req, conds, shard_ids)
                    break
                except FileNotFoundError:
                    if attempt == 2:
                        raise
            ss.tag("rows", len(rows))
        if req.order_by_tag:
            have = [r for r in rows if r[3].get(req.order_by_tag) is not None]
            miss = [r for r in rows if r[3].get(req.order_by_tag) is None]
            have.sort(
                key=lambda r: r[3][req.order_by_tag],
                reverse=(req.order_by_dir == "desc"),
            )
            rows = have + miss  # missing-tag rows last under either order
        else:
            rows.sort(key=lambda r: r[0], reverse=(req.order_by_ts != "asc"))
        off = req.offset or 0
        for ts, elem_id, body, tags in rows[off : off + (req.limit or 100)]:
            res.data_points.append(
                {
                    "element_id": elem_id,
                    "timestamp": ts,
                    "tags": tags,
                    "body": body,
                }
            )
        if req.trace:
            from banyandb_tpu.query import logical

            res.trace = {
                "plan": logical.analyze_stream(s, req).explain(),
                "rows_scanned": len(rows),
            }
            if own_tracer:
                res.trace["span_tree"] = tracer.finish()
        return res

    def _scan(
        self, db: TSDB, s: Stream, req: QueryRequest, conds, shard_ids=None
    ) -> list[tuple]:
        from banyandb_tpu.index import element

        rows: list[tuple] = []
        tag_names = [t.name for t in s.tags]
        inverted, skipping = self._index_tags(req.groups[0], s.name)
        stats = {"blocks_selected": 0, "blocks_read": 0, "blocks_skipped": 0}
        from banyandb_tpu.storage.chunk_stream import prefetched

        # the stream analog of the measure gather/compute pipeline: the
        # loop below only does metadata work (block selection, sidecar
        # pruning) and collects decode thunks; evaluation through the
        # prefetch stream overlaps part k+1's disk decode with part k's
        # mask+gather — order (and therefore result order) is identical
        # to the strict-serial path (BYDB_PIPELINE=0)
        read_ops: list = []
        for seg in db.select_segments(
            req.time_range.begin_millis, req.time_range.end_millis
        ):
            for shard_idx, shard in enumerate(seg.shards):
                if shard_ids is not None and shard_idx not in shard_ids:
                    continue
                # live memtable + in-flight flush snapshot (rows stay
                # visible while their part encodes outside the lock)
                for mem_cols in shard.hot_columns(s.name):
                    read_ops.append(lambda mc=mem_cols: mc)
                for part in shard.parts:
                    if part.meta.get("stream") != s.name:
                        continue
                    blocks = part.select_blocks(
                        req.time_range.begin_millis, req.time_range.end_millis
                    )
                    stats["blocks_selected"] += len(blocks)
                    if blocks and conds and (inverted or skipping):
                        allowed = element.prune_blocks(
                            part, conds, inverted, skipping
                        )
                        if allowed is not None:
                            blocks = [b for b in blocks if b in allowed]
                    stats["blocks_read"] += len(blocks)
                    if blocks:
                        # narrow_codes: tag columns keep their stored
                        # i8/i16 width so the device mask kernel
                        # (stream_exec.device_tag_mask) ships them
                        # compressed and widens on device
                        read_ops.append(
                            lambda p=part, b=blocks: p.read(
                                b,
                                tags=[
                                    t
                                    for t in tag_names
                                    if t in p.meta["tags"]
                                ],
                                want_payload=True,
                                narrow_codes=_encoded.device_decode_enabled(),
                            )
                        )
        for src in prefetched(read_ops, name="bydb-stream-prefetch"):
            rows.extend(self._filter_source(s, src, req, conds))
        stats["blocks_skipped"] = stats["blocks_selected"] - stats["blocks_read"]
        # bdlint: disable=wp-shared-state -- diagnostic last-query
        # snapshot: an atomic rebind of a fresh dict, last-writer-wins by
        # design (readers only ever dereference one complete snapshot)
        self.last_scan_stats = stats
        return rows

    def _filter_source(self, s: Stream, src: ColumnData, req: QueryRequest, conds):
        from banyandb_tpu.query import stream_exec

        if conds is None:  # OR criteria tree: host tree-mask evaluation
            mask = qfilter.criteria_mask(
                src, req.criteria,
                req.time_range.begin_millis, req.time_range.end_millis,
            )
        else:
            mask = stream_exec.row_mask(
                src, conds, req.time_range.begin_millis, req.time_range.end_millis
            )
        out = []
        for i in np.nonzero(mask)[0]:
            payload = src.payloads[i] if src.payloads else b"\x00"
            elem_id, body = decode_element_payload(payload)
            tags = {
                t: qfilter.decode_tag_value(
                    src.dicts[t][src.tags[t][i]], s.tag(t).type
                )
                for t in src.tags
            }
            out.append((int(src.ts[i]), elem_id, body, tags))
        return out


