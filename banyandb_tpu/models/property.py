"""Property engine: mutable documents with ModRevision semantics.

Analog of banyand/property (db/shard.go doc fields _source/_id/_timestamp,
etcd-style ModRevision, update = overwrite + tombstone semantics at merge).
Backed by one InvertedIndex per (group, shard) — the same backing choice
as the reference's per-(group,shard) Bluge store — and, like the
reference, this is also the store the schema registry rides on.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

from banyandb_tpu.api.schema import SchemaRegistry
from banyandb_tpu.index.inverted import And, Doc, InvertedIndex, Query, TermQuery
from banyandb_tpu.obs import metrics as obs_metrics
from banyandb_tpu.utils import hashing

_H_QUERY_PROPERTY = obs_metrics.global_meter().histogram(
    "query_ms", {"engine": "property"}
)


@dataclass(frozen=True)
class Property:
    """property/v1 Property analog."""

    group: str
    name: str
    id: str
    tags: dict  # tag name -> str value
    mod_revision: int = 0
    create_revision: int = 0


class PropertyEngine:
    def __init__(self, registry: SchemaRegistry, root: str | Path):
        self.registry = registry
        self.root = Path(root) / "property"
        self._lock = threading.Lock()
        self._shards: dict[tuple[str, int], InvertedIndex] = {}
        self._revision = int(time.time() * 1000)

    def close(self) -> None:
        """Persist + release every shard index's memory and mmaps (bdsan
        fd hygiene; indexes lazily reopen on next use)."""
        with self._lock:
            shards = list(self._shards.values())
        for idx in shards:
            idx.reclaim()

    def _shard_idx(self, group: str, shard: int) -> InvertedIndex:
        with self._lock:
            key = (group, shard)
            idx = self._shards.get(key)
            if idx is None:
                idx = InvertedIndex(self.root / group / f"shard-{shard}.idx")
                self._shards[key] = idx
            return idx

    def _shard_for(self, group: str, name: str, pid: str) -> InvertedIndex:
        g = self.registry.get_group(group)
        sid = hashing.series_id([name.encode(), pid.encode()])
        return self._shard_idx(group, hashing.shard_id(sid, g.resource_opts.shard_num))

    def _all_shards(self, group: str) -> list[InvertedIndex]:
        g = self.registry.get_group(group)
        return [
            self._shard_idx(group, s)
            for s in range(g.resource_opts.shard_num)
        ]

    @staticmethod
    def _doc_id(name: str, pid: str) -> int:
        return hashing.series_id([name.encode(), pid.encode()])

    # -- apply/get/delete (liaison/grpc/property.go surface) ---------------
    def apply(
        self,
        p: Property,
        strategy: str = "merge",
        ttl_seconds: Optional[float] = None,
    ) -> Property:
        """Create or update; returns the stored property with revisions.

        strategy="merge" merges tags into an existing doc (the reference's
        default apply strategy); "replace" overwrites the tag set.
        ttl_seconds sets a lease: the property stops resolving at expiry
        and is physically removed by sweep_expired (the reference's
        property-expire-delete-timeout GC).
        """
        idx = self._shard_for(p.group, p.name, p.id)
        with self._lock:
            self._revision += 1
            rev = self._revision
        doc_id = self._doc_id(p.name, p.id)
        old = idx.get(doc_id)
        tags = dict(p.tags)
        create_rev = rev
        if old is not None:
            old_src = json.loads(old.payload)
            create_rev = old.numerics.get("@create", rev)
            if strategy == "merge":
                merged = dict(old_src["tags"])
                merged.update(tags)
                tags = merged
        stored = Property(
            group=p.group, name=p.name, id=p.id, tags=tags,
            mod_revision=rev, create_revision=create_rev,
        )
        keywords = {"@name": p.name.encode(), "@id": p.id.encode()}
        for k, v in tags.items():
            keywords[k] = str(v).encode()
        numerics = {"@mod": rev, "@create": create_rev}
        if ttl_seconds is not None:
            numerics["@expire"] = int((time.time() + ttl_seconds) * 1000)
        idx.insert(
            [
                Doc(
                    doc_id=doc_id,
                    keywords=keywords,
                    numerics=numerics,
                    payload=json.dumps(
                        {"id": p.id, "name": p.name, "tags": tags}
                    ).encode(),
                )
            ]
        )
        return stored

    @staticmethod
    def _expired(doc, now_millis: Optional[int] = None) -> bool:
        exp = doc.numerics.get("@expire")
        if exp is None:
            return False
        now = now_millis if now_millis is not None else int(time.time() * 1000)
        return exp <= now

    def sweep_expired(self, group: str, now_millis: Optional[int] = None) -> int:
        """Physically remove expired docs (merge-time GC analog)."""
        removed = 0
        for idx in self._all_shards(group):
            dead = [
                doc_id
                for doc_id in idx.search(None).tolist()
                if self._expired(idx.get(doc_id), now_millis)
            ]
            if dead:
                idx.delete(dead)
                removed += len(dead)
        if removed:
            with self._lock:
                self._revision += 1  # state-tree freshness (see delete())
        return removed

    def get(self, group: str, name: str, pid: str) -> Optional[Property]:
        idx = self._shard_for(group, name, pid)
        doc = idx.get(self._doc_id(name, pid))
        if doc is None or self._expired(doc):
            return None
        src = json.loads(doc.payload)
        return Property(
            group=group, name=name, id=pid, tags=src["tags"],
            mod_revision=doc.numerics.get("@mod", 0),
            create_revision=doc.numerics.get("@create", 0),
        )

    def delete(self, group: str, name: str, pid: str) -> bool:
        idx = self._shard_for(group, name, pid)
        doc_id = self._doc_id(name, pid)
        if idx.get(doc_id) is None:
            return False
        idx.delete([doc_id])
        with self._lock:
            # any mutation advances the revision: the repair state tree's
            # freshness guard must see deletions too
            self._revision += 1
        return True

    def query(
        self,
        group: str,
        name: str,
        *,
        tag_filters: Optional[dict] = None,
        ids: Optional[list[str]] = None,
        limit: int = 100,
    ) -> list[Property]:
        """Scatter across shards, filter by name + tags (+ id set)."""
        t0 = time.time()
        try:
            return self._query_inner(
                group, name, tag_filters=tag_filters, ids=ids, limit=limit
            )
        finally:
            _H_QUERY_PROPERTY.observe((time.time() - t0) * 1000)

    def _query_inner(
        self,
        group: str,
        name: str,
        *,
        tag_filters: Optional[dict] = None,
        ids: Optional[list[str]] = None,
        limit: int = 100,
    ) -> list[Property]:
        clauses: list = [TermQuery("@name", name.encode())]
        for k, v in (tag_filters or {}).items():
            clauses.append(TermQuery(k, str(v).encode()))
        q: Query = And(tuple(clauses))
        out: list[Property] = []
        idset = set(ids) if ids else None
        for idx in self._all_shards(group):
            for doc_id in idx.search(q).tolist():
                doc = idx.get(doc_id)
                if self._expired(doc):
                    continue
                src = json.loads(doc.payload)
                if idset is not None and src["id"] not in idset:
                    continue
                out.append(
                    Property(
                        group=group, name=name, id=src["id"], tags=src["tags"],
                        mod_revision=doc.numerics.get("@mod", 0),
                        create_revision=doc.numerics.get("@create", 0),
                    )
                )
                if len(out) >= limit:
                    return out
        return out

    def docs_in_shard(self, group: str, shard: int) -> list[Property]:
        """All live docs of one (group, shard) — repair-tree enumeration
        (banyand/property/db/repair.go walks the shard store the same
        way)."""
        idx = self._shard_idx(group, shard)
        out = []
        for doc_id in idx.search(None).tolist():
            doc = idx.get(doc_id)
            if doc is None or self._expired(doc):
                continue
            src = json.loads(doc.payload)
            out.append(
                Property(
                    group=group,
                    name=src["name"],
                    id=src["id"],
                    tags=src["tags"],
                    mod_revision=doc.numerics.get("@mod", 0),
                    create_revision=doc.numerics.get("@create", 0),
                )
            )
        return out

    @property
    def revision(self) -> int:
        return self._revision

    def persist(self) -> None:
        # snapshot under the lock: a concurrent first-touch (lifecycle
        # property sweep, schema-plane write) growing _shards mid-walk
        # is a RuntimeError otherwise
        with self._lock:
            shards = list(self._shards.values())
        for idx in shards:
            idx.persist()

    def persist_group(self, group: str) -> None:
        """Persist only one group's shards (schema-plane writes touch
        just the _schema group; fsyncing every shard would stall)."""
        with self._lock:
            shards = [
                idx for (g, _s), idx in self._shards.items() if g == group
            ]
        for idx in shards:
            idx.persist()
