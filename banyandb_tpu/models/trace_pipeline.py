"""Post-trace pipeline: tail-sampling chains gating storage events.

Analog of the reference's native-plugin trace pipeline
(docs/design/post-trace-pipeline.md, banyand/trace/pipeline_registry.go,
pipeline_chain.go, pkg/pipeline/sdk): sampler stages receive a columnar
batch of spans and return keep-masks; chains gate rows at LSM merge
(PIPELINE_EVENT_MERGE).  Instead of Go `.so` plugins (a loader the
reference itself flags as unsafe), samplers here are plain callables
registered in-process — the same vectorized contract, a safer plugin
surface (out-of-process plugins can ride the bus later).

A sampler: fn(batch: TraceBatch) -> bool mask (True = keep) or None
(= keep all).  Stages AND together, so any stage can only narrow.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from banyandb_tpu.storage.part import ColumnData

EVENT_MERGE = "merge"


@dataclass
class TraceBatch:
    """Columnar span view handed to samplers (vectorized TraceBatch +
    column projection of the reference SDK)."""

    trace_name: str
    cols: ColumnData

    def __len__(self) -> int:
        return int(self.cols.ts.size)

    @property
    def ts(self) -> np.ndarray:
        return self.cols.ts

    def tag_values(self, tag: str) -> list[bytes]:
        """Decoded per-row byte values of one tag column."""
        codes = self.cols.tags.get(tag)
        if codes is None:
            return [b""] * len(self)
        d = self.cols.dicts[tag]
        return [d[c] for c in codes.tolist()]

    def tag_ints(self, tag: str) -> np.ndarray:
        """Per-row int64 view of an INT tag column."""
        codes = self.cols.tags.get(tag)
        if codes is None:
            return np.zeros(len(self), dtype=np.int64)
        d = self.cols.dicts[tag]
        vals = np.asarray(
            [int.from_bytes(v, "little", signed=True) if v else 0 for v in d],
            dtype=np.int64,
        )
        return vals[codes]


Sampler = Callable[[TraceBatch], Optional[np.ndarray]]


class TracePipelineRegistry:
    """Per-(group, trace) sampler chains (pipeline_registry.go analog)."""

    def __init__(self):
        self._chains: dict[tuple[str, str], list[Sampler]] = {}

    def register(self, group: str, trace_name: str, sampler: Sampler) -> None:
        self._chains.setdefault((group, trace_name), []).append(sampler)

    def chain(self, group: str, trace_name: str) -> list[Sampler]:
        return list(self._chains.get((group, trace_name), []))

    def merge_filter_for(self, group: str):
        """-> TSDB merge_filter callable applying this group's chains."""

        def merge_filter(kind: str, name: str, cols: ColumnData):
            if kind != "trace":
                return None
            chain = self._chains.get((group, name))
            if not chain:
                return None
            batch = TraceBatch(trace_name=name, cols=cols)
            keep = np.ones(len(batch), dtype=bool)
            for sampler in chain:
                mask = sampler(batch)
                if mask is not None:
                    keep &= np.asarray(mask, dtype=bool)
            return keep

        return merge_filter


# -- stock samplers (plugins/skywalking analog building blocks) -------------


def keep_slow_traces(duration_tag: str, threshold: int) -> Sampler:
    """Keep every span of any trace containing a span >= threshold.

    Whole-trace decisions need visibility of the whole trace: the keep
    set is remembered across batches (a slow span seen in ANY earlier
    batch protects later merges), and for a strict guarantee run the
    chain at finalize (TraceEngine.finalize_segments merges each shard
    in one pass, so the batch holds the complete segment — the
    PIPELINE_EVENT_FINALIZE analog).  Incremental merges before the
    qualifying span has been observed are best-effort.
    """
    seen_slow: set[int] = set()

    def sampler(batch: TraceBatch) -> np.ndarray:
        dur = batch.tag_ints(duration_tag)
        slow = dur >= threshold
        seen_slow.update(np.unique(batch.cols.series[slow]).tolist())
        keep_series = np.asarray(sorted(seen_slow), dtype=np.int64)
        return np.isin(batch.cols.series, keep_series)

    return sampler


def keep_tag_values(tag: str, values: set[bytes]) -> Sampler:
    """Keep spans whose tag is in the value set (error-status keeps)."""

    def sampler(batch: TraceBatch) -> np.ndarray:
        vals = batch.tag_values(tag)
        return np.asarray([v in values for v in vals], dtype=bool)

    return sampler
