"""Trace engine: spans grouped by trace ID.

Analog of banyand/trace (trace.go:43-46): spans are opaque payloads
(spans.bin) with flat tag columns; routing is by trace-id hash
(partition.TraceShardID, pkg/partition/route.go:40); each part carries a
trace-id bloom filter (traceID.filter) consulted before block reads; and
ordered retrieval (e.g. traces by duration) goes through a per-segment
ordered secondary index (the reference's sidx, banyand/internal/sidx).
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

import numpy as np

from banyandb_tpu.api.model import QueryRequest, QueryResult, TimeRange
from banyandb_tpu.api.schema import SchemaRegistry, TagType
from banyandb_tpu.index.sidx import SidxStore
from banyandb_tpu.index.sidx import decode_ref as sidx_decode_ref
from banyandb_tpu.index.sidx import encode_ref as sidx_encode_ref
from banyandb_tpu.obs import metrics as obs_metrics
from banyandb_tpu.query import measure_exec
from banyandb_tpu.storage.memtable import PayloadMemtable
from banyandb_tpu.storage.part import ColumnData
from banyandb_tpu.storage.tsdb import TSDB
from banyandb_tpu.utils import hashing
from banyandb_tpu.utils.bloom import Bloom

BLOOM_FILE = "traceid.filter"

_H_QUERY_TRACE = obs_metrics.global_meter().histogram(
    "query_ms", {"engine": "trace"}
)


# Trace schema objects live in the registry (persisted + SCHEMA_SYNC'd
# like measures); re-exported here for engine-local convenience.
from banyandb_tpu.api.schema import Trace  # noqa: E402


@dataclass(frozen=True)
class SpanValue:
    ts_millis: int
    tags: dict
    span: bytes  # opaque span payload


def write_trace_bloom(part, trace_id_tag: str) -> bool:
    """THE trace-id bloom sidecar builder — local flushes and installed
    (liaison-shipped) parts both go through this, so sizing/encoding/
    filename can never fork.  Returns True when a bloom was written."""
    from banyandb_tpu.utils import fs

    if trace_id_tag not in part.meta.get("tags", ()):
        return False
    ids = part.dict_for(trace_id_tag)
    bloom = Bloom(max(len(ids), 1))
    for v in ids:
        bloom.add(v)
    fs.atomic_write(part.dir / BLOOM_FILE, bloom.to_bytes())
    return True


def trace_shard_id(trace_id: str, shard_num: int) -> int:
    """partition.TraceShardID analog: hash the trace id directly."""
    h = hashlib.blake2b(trace_id.encode(), digest_size=8).digest()
    return int.from_bytes(h, "little") % shard_num


class TraceEngine:
    def __init__(self, registry: SchemaRegistry, root: str | Path):
        import threading

        self.registry = registry
        self.root = Path(root) / "trace"
        self._tsdbs: dict[str, TSDB] = {}
        self._tsdb_lock = threading.Lock()
        # ordered-index stores per (group, segment-start, rule-tag): the
        # part-based sidx (index/sidx.py, interfaces.go:58 analog)
        self._sidx: dict[tuple, SidxStore] = {}
        # tail-sampling pipeline (post-trace-pipeline analog)
        from banyandb_tpu.models.trace_pipeline import TracePipelineRegistry

        self.pipeline = TracePipelineRegistry()

    def close(self) -> None:
        """Release every TSDB's index memory/file handles (bdsan fd
        hygiene; reopen stays lazy)."""
        with self._tsdb_lock:
            dbs = list(self._tsdbs.values())
        for db in dbs:
            db.close()

    def create_trace(self, t: Trace) -> None:
        self.registry.create_trace(t)

    def get_trace(self, group: str, name: str) -> Trace:
        return self.registry.get_trace(group, name)

    def _tsdb(self, group: str) -> TSDB:
        with self._tsdb_lock:
            db = self._tsdbs.get(group)
            if db is None:
                g = self.registry.get_group(group)
                db = TSDB(
                    self.root, group, g.resource_opts,
                    mem_factory=lambda: PayloadMemtable("trace"),
                )
                # sampler-chain gating at merge (trace/merger.go:318-342)
                db.merge_filter = self.pipeline.merge_filter_for(group)
                self._tsdbs[group] = db
            return db

    def _ordered_index(self, group: str, seg, rule_tag: str) -> SidxStore:
        with self._tsdb_lock:
            key = (group, seg.start, rule_tag)
            idx = self._sidx.get(key)
            if idx is None:
                idx = SidxStore(seg.root / f"sidx-{rule_tag}")
                self._sidx[key] = idx
            return idx

    # -- write (svc write path analog) -------------------------------------
    def write(
        self,
        group: str,
        name: str,
        spans: list[SpanValue],
        *,
        ordered_tags: tuple[str, ...] = (),
    ) -> int:
        """Ingest spans; `ordered_tags` are INT tags maintained in the
        ordered secondary index (the TYPE_TREE rule analog, e.g. duration).
        """
        t = self.get_trace(group, name)
        db = self._tsdb(group)
        shard_num = self.registry.get_group(group).resource_opts.shard_num
        tag_names = [x.name for x in t.tags]
        n = 0
        for sp in spans:
            trace_id = str(sp.tags[t.trace_id_tag])
            sid = hashing.series_id([name.encode(), trace_id.encode()])
            shard = trace_shard_id(trace_id, shard_num)
            seg = db.segment_for(sp.ts_millis)
            tag_bytes = {
                x.name: hashing.entity_bytes(sp.tags[x.name])
                if sp.tags.get(x.name) is not None
                else b""
                for x in t.tags
            }
            # ordering keys FIRST: if a concurrent flush tick lands
            # between these two inserts, the failure direction is a
            # prunable dangling key — never a durable span whose key was
            # still mem-only (query_ordered would omit it forever)
            for rt in ordered_tags:
                v = sp.tags.get(rt)
                if v is None:
                    continue
                self._ordered_index(group, seg, rt).insert(
                    int(v), sidx_encode_ref(trace_id, sp.ts_millis)
                )
            seg.shards[shard].ingest(
                lambda mem: mem.append(
                    name, tag_names, sp.ts_millis, sid, tag_bytes, sp.span
                )
            )
            n += 1
        return n

    def _flush_sidx_first(self) -> None:
        """Commit sidx flushes BEFORE span parts publish (the adapted
        sidx/interfaces.go:37 snapshot-transaction contract): stage every
        store's part, then publish them all, then let the caller flush
        spans.  Any crash between the two publish points leaves at worst
        DANGLING ordered keys, which query_ordered prunes via
        verify_live — never durable spans missing their ordering keys
        (the old order's divergence)."""
        txns = []
        try:
            for idx in list(self._sidx.values()):
                t = idx.prepare_flush()
                if t is not None:
                    txns.append(t)
        except BaseException:
            for t in txns:
                t.abort()
            raise
        for i, t in enumerate(txns):
            try:
                t.commit()
            except BaseException:
                # a failed commit must not leak the remaining stores'
                # flush locks (that would deadlock every future flush)
                for u in txns[i + 1 :]:
                    try:
                        u.abort()
                    except Exception:  # noqa: BLE001
                        pass
                raise

    def flush(self, group: Optional[str] = None) -> list[str]:
        out = []
        self._flush_sidx_first()
        for gname, db in list(self._tsdbs.items()):
            if group is None or gname == group:
                out.extend(db.flush_all())
                self._write_blooms(db, gname)
        for idx in list(self._sidx.values()):
            idx.merge()
        return out

    def _write_blooms(self, db: TSDB, group: str) -> None:
        """Attach a trace-id bloom file to parts that lack one."""
        for seg in db.segments:
            for shard in seg.shards:
                for part in shard.parts:
                    name = part.meta.get("trace")
                    if not name or (part.dir / BLOOM_FILE).exists():
                        continue
                    try:
                        t = self.registry.get_trace(group, name)
                    except KeyError:
                        continue
                    write_trace_bloom(part, t.trace_id_tag)

    def maintain(
        self, group: Optional[str] = None, *, flush_sidx: bool = True
    ) -> None:
        """Periodic companion work the generic lifecycle flusher can't do
        for trace TSDBs: trace-id bloom sidecars on new parts + sidx
        ordered-index flush/merge.  Ordering keys always publish BEFORE
        span parts (_flush_sidx_first here and as the lifecycle
        pre_flush hook), so no crash window leaves durable spans without
        their keys.  Wired as the lifecycle extra tick."""
        for gname, db in list(self._tsdbs.items()):
            if group is None or gname == group:
                self._write_blooms(db, gname)
        if flush_sidx:
            # skipped when the caller already runs _flush_sidx_first as
            # the lifecycle pre_flush hook (one sidx part per tick, not
            # two)
            self._flush_sidx_first()
        for idx in list(self._sidx.values()):
            idx.merge()

    def finalize_segments(self, group: str) -> int:
        """Run the sampler chain over COMPLETE segments: every shard's
        parts merge in one pass, so whole-trace keep decisions see every
        span (PIPELINE_EVENT_FINALIZE, trace finalize_scanner analog).
        Returns the number of shards compacted."""
        db = self._tsdb(group)
        n = 0
        for seg in db.segments:
            for shard in seg.shards:
                parts = shard.parts
                if len(parts) < 2:
                    continue
                if shard.merge(min_merge=len(parts), max_parts=len(parts)):
                    n += 1
        return n

    # -- queries -----------------------------------------------------------
    def query_by_trace_id(self, group: str, name: str, trace_id: str) -> list[dict]:
        """All spans of one trace (the trace span-store lookup)."""
        t0 = time.perf_counter()
        try:
            return self._query_by_trace_id(group, name, trace_id)
        finally:
            _H_QUERY_TRACE.observe((time.perf_counter() - t0) * 1000)

    def _query_by_trace_id(
        self, group: str, name: str, trace_id: str
    ) -> list[dict]:
        t = self.get_trace(group, name)
        db = self._tsdb(group)
        shard_num = self.registry.get_group(group).resource_opts.shard_num
        shard_idx = trace_shard_id(trace_id, shard_num)
        tid = trace_id.encode()
        out: list[dict] = []
        for seg in db.segments:
            shard = seg.shards[shard_idx]
            # live memtable + in-flight flush snapshot (flush encodes
            # parts outside the shard lock)
            sources = list(shard.hot_columns(name))
            for part in shard.parts:
                if part.meta.get("trace") != name:
                    continue
                bloom_path = part.dir / BLOOM_FILE
                if bloom_path.exists():
                    bloom = Bloom.from_bytes(bloom_path.read_bytes())
                    if tid not in bloom:
                        continue
                sources.append(
                    part.read(
                        range(len(part.blocks)),
                        tags=part.meta["tags"],
                        want_payload=True,
                    )
                )
            for src in sources:
                d = src.dicts.get(t.trace_id_tag, [])
                lut = {v: i for i, v in enumerate(d)}
                code = lut.get(tid, -1)
                if code < 0:
                    continue
                sel = np.nonzero(src.tags[t.trace_id_tag] == code)[0]
                for i in sel:
                    out.append(self._row_to_span(t, src, int(i)))
        out.sort(key=lambda s: s["timestamp"])
        return out

    def query_ordered(
        self,
        group: str,
        name: str,
        order_tag: str,
        time_range: TimeRange,
        *,
        lo: Optional[int] = None,
        hi: Optional[int] = None,
        asc: bool = False,
        limit: int = 20,
        verify_live: bool = True,
        with_keys: bool = False,
    ) -> list:
        """Trace ids ordered by an indexed numeric tag (sidx TYPE_TREE
        retrieval: e.g. slowest traces in a window).

        with_keys=True returns [(key, trace_id)] instead of bare ids —
        the distributed path needs the ordering keys to k-way merge
        per-node results at the liaison.

        verify_live drops ids whose spans were since removed by the
        sampler pipeline (the ordered index is ingest-time and is not
        rewritten by merge gating); cost is one span lookup per
        candidate, bounded by `limit`.
        """
        t_q0 = time.perf_counter()
        try:
            return self._query_ordered(
                group, name, order_tag, time_range, lo=lo, hi=hi, asc=asc,
                limit=limit, verify_live=verify_live, with_keys=with_keys,
            )
        finally:
            _H_QUERY_TRACE.observe((time.perf_counter() - t_q0) * 1000)

    def _query_ordered(
        self,
        group: str,
        name: str,
        order_tag: str,
        time_range: TimeRange,
        *,
        lo: Optional[int] = None,
        hi: Optional[int] = None,
        asc: bool = False,
        limit: int = 20,
        verify_live: bool = True,
        with_keys: bool = False,
    ) -> list:
        import heapq

        db = self._tsdb(group)
        # One key-ordered stream per overlapping segment, heap-merged so
        # the global order holds across segment boundaries.  Per-segment
        # fetch starts at 4x limit (headroom for duplicates / dead
        # candidates) and grows adaptively: if fewer than `limit` live
        # ids survive while some segment's stream was truncated at its
        # cap, the fetch quadruples and the scan repeats — heavy
        # tail-sampling kill rates never starve the result below what
        # actually exists.  sidx block pruning keeps reads key-relevant.
        segs = db.select_segments(time_range.begin_millis, time_range.end_millis)
        fetch = max(limit, 1) * 4
        while True:
            self.last_sidx_blocks_read = 0
            streams = []
            truncated = False
            for seg in segs:
                st = self._ordered_index(group, seg, order_tag)
                chunk = st.range_query(lo, hi, asc=asc, limit=fetch)
                truncated = truncated or len(chunk) >= fetch
                streams.append(iter(chunk))
                self.last_sidx_blocks_read += st.last_blocks_read
            merged = heapq.merge(
                *streams, key=lambda kp: kp[0] if asc else -kp[0]
            )
            seen: list[str] = []
            keyed: list[tuple[int, str]] = []
            for _k, payload in merged:
                tid, ts = sidx_decode_ref(payload)
                if not (time_range.begin_millis <= ts < time_range.end_millis):
                    continue
                if tid in seen:
                    continue
                if verify_live and not self.query_by_trace_id(group, name, tid):
                    continue
                seen.append(tid)
                keyed.append((int(_k), tid))
                if len(seen) >= limit:
                    return keyed if with_keys else seen
            if not truncated:
                return keyed if with_keys else seen
            fetch *= 4

    def _row_to_span(self, t: Trace, src: ColumnData, i: int) -> dict:
        from banyandb_tpu.query import filter as qfilter

        tags = {
            tn: qfilter.decode_tag_value(src.dicts[tn][col[i]], t.tag(tn).type)
            for tn, col in src.tags.items()
        }
        return {
            "timestamp": int(src.ts[i]),
            "tags": tags,
            "span": src.payloads[i] if src.payloads else b"",
        }
