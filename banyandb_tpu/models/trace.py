"""Trace engine: spans grouped by trace ID.

Analog of banyand/trace (trace.go:43-46): spans are opaque payloads
(spans.bin) with flat tag columns; routing is by trace-id hash
(partition.TraceShardID, pkg/partition/route.go:40); each part carries a
trace-id bloom filter (traceID.filter) consulted before block reads; and
ordered retrieval (e.g. traces by duration) goes through a per-segment
ordered secondary index (the reference's sidx, banyand/internal/sidx).
"""

from __future__ import annotations

import hashlib
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

import numpy as np

from banyandb_tpu.api.model import QueryRequest, QueryResult, TimeRange
from banyandb_tpu.api.schema import SchemaRegistry, TagType
from banyandb_tpu.index.sidx import SidxStore
from banyandb_tpu.index.sidx import decode_ref as sidx_decode_ref
from banyandb_tpu.index.sidx import encode_ref as sidx_encode_ref
from banyandb_tpu.obs import metrics as obs_metrics
from banyandb_tpu.query import measure_exec
from banyandb_tpu.storage.memtable import PayloadMemtable
from banyandb_tpu.storage.part import ColumnData
from banyandb_tpu.storage.tsdb import TSDB
from banyandb_tpu.utils import hashing
from banyandb_tpu.utils.bloom import Bloom

BLOOM_FILE = "traceid.filter"

_H_QUERY_TRACE = obs_metrics.global_meter().histogram(
    "query_ms", {"engine": "trace"}
)

# per-plan default row/trace limits when the request carries none
# (by_id/scan count span rows; ordered counts traces, the sidx key unit)
_DEFAULT_LIMITS = {"by_id": 100, "ordered": 20, "scan": 100}


def classify_plan(req: QueryRequest, tid_tag: str) -> tuple:
    """Lower a trace QueryRequest onto one of the three read plans.

    -> (kind, tids, lo, hi, residual) where kind is ``by_id`` (trace-id
    eq/IN criteria: bloom-gated span-store lookups), ``ordered``
    (order_by_tag set: sidx TYPE_TREE walk with key bounds lo/hi), or
    ``scan`` (criteria-only: zone-map-planned part scan).  AND criteria
    only — OR trees raise rather than silently flatten.  Multiple
    trace-id conditions INTERSECT (AND semantics); an empty intersection
    is an empty by_id plan, not an error.  The liaison shares this
    lowering so node and gather halves can never disagree on the plan.
    """
    from banyandb_tpu.query.measure_exec import _lower_criteria

    leaves, expr = _lower_criteria(req.criteria)
    if expr:
        raise ValueError("OR criteria not supported for trace queries")
    id_sets: list[set[str]] = []
    residual = []
    for c in leaves:
        if c.name == tid_tag and c.op == "eq":
            id_sets.append({str(c.value)})
        elif c.name == tid_tag and c.op == "in":
            id_sets.append({str(v) for v in c.value})
        else:
            residual.append(c)
    if id_sets:
        return "by_id", sorted(set.intersection(*id_sets)), None, None, residual
    if req.order_by_tag:
        lo = hi = None
        rest = []
        for c in residual:
            if c.name == req.order_by_tag and c.op in ("gt", "ge", "lt", "le"):
                # duplicate bounds INTERSECT (AND semantics)
                if c.op in ("gt", "ge"):
                    b = int(c.value) + (1 if c.op == "gt" else 0)
                    lo = b if lo is None else max(lo, b)
                else:
                    b = int(c.value) - (1 if c.op == "lt" else 0)
                    hi = b if hi is None else min(hi, b)
            else:
                rest.append(c)
        return "ordered", None, lo, hi, rest
    return "scan", None, None, None, residual


# Trace schema objects live in the registry (persisted + SCHEMA_SYNC'd
# like measures); re-exported here for engine-local convenience.
from banyandb_tpu.api.schema import Trace  # noqa: E402


@dataclass(frozen=True)
class SpanValue:
    ts_millis: int
    tags: dict
    span: bytes  # opaque span payload


def write_trace_bloom(part, trace_id_tag: str) -> bool:
    """THE trace-id bloom sidecar builder — local flushes and installed
    (liaison-shipped) parts both go through this, so sizing/encoding/
    filename can never fork.  Returns True when a bloom was written."""
    from banyandb_tpu.utils import fs

    if trace_id_tag not in part.meta.get("tags", ()):
        return False
    ids = part.dict_for(trace_id_tag)
    bloom = Bloom(max(len(ids), 1))
    for v in ids:
        bloom.add(v)
    fs.atomic_write(part.dir / BLOOM_FILE, bloom.to_bytes())
    return True


def trace_shard_id(trace_id: str, shard_num: int) -> int:
    """partition.TraceShardID analog: hash the trace id directly."""
    h = hashlib.blake2b(trace_id.encode(), digest_size=8).digest()
    return int.from_bytes(h, "little") % shard_num


class TraceEngine:
    def __init__(self, registry: SchemaRegistry, root: str | Path):
        import threading

        self.registry = registry
        self.root = Path(root) / "trace"
        self._tsdbs: dict[str, TSDB] = {}
        self._tsdb_lock = threading.Lock()
        # ordered-index stores per (group, segment-start, rule-tag): the
        # part-based sidx (index/sidx.py, interfaces.go:58 analog)
        self._sidx: dict[tuple, SidxStore] = {}
        # tail-sampling pipeline (post-trace-pipeline analog)
        from banyandb_tpu.models.trace_pipeline import TracePipelineRegistry

        self.pipeline = TracePipelineRegistry()

    def close(self) -> None:
        """Release every TSDB's index memory/file handles (bdsan fd
        hygiene; reopen stays lazy)."""
        with self._tsdb_lock:
            dbs = list(self._tsdbs.values())
        for db in dbs:
            db.close()

    def create_trace(self, t: Trace) -> None:
        self.registry.create_trace(t)

    def get_trace(self, group: str, name: str) -> Trace:
        return self.registry.get_trace(group, name)

    def _tsdb(self, group: str) -> TSDB:
        with self._tsdb_lock:
            db = self._tsdbs.get(group)
            if db is None:
                g = self.registry.get_group(group)
                db = TSDB(
                    self.root, group, g.resource_opts,
                    mem_factory=lambda: PayloadMemtable("trace"),
                )
                # sampler-chain gating at merge (trace/merger.go:318-342)
                db.merge_filter = self.pipeline.merge_filter_for(group)
                self._tsdbs[group] = db
            return db

    def _ordered_index(self, group: str, seg, rule_tag: str) -> SidxStore:
        with self._tsdb_lock:
            key = (group, seg.start, rule_tag)
            idx = self._sidx.get(key)
            if idx is None:
                idx = SidxStore(seg.root / f"sidx-{rule_tag}")
                self._sidx[key] = idx
            return idx

    # -- write (svc write path analog) -------------------------------------
    def write(
        self,
        group: str,
        name: str,
        spans: list[SpanValue],
        *,
        ordered_tags: tuple[str, ...] = (),
    ) -> int:
        """Ingest spans; `ordered_tags` are INT tags maintained in the
        ordered secondary index (the TYPE_TREE rule analog, e.g. duration).
        """
        t = self.get_trace(group, name)
        db = self._tsdb(group)
        shard_num = self.registry.get_group(group).resource_opts.shard_num
        tag_names = [x.name for x in t.tags]
        n = 0
        for sp in spans:
            trace_id = str(sp.tags[t.trace_id_tag])
            sid = hashing.series_id([name.encode(), trace_id.encode()])
            shard = trace_shard_id(trace_id, shard_num)
            seg = db.segment_for(sp.ts_millis)
            tag_bytes = {
                x.name: hashing.entity_bytes(sp.tags[x.name])
                if sp.tags.get(x.name) is not None
                else b""
                for x in t.tags
            }
            # ordering keys FIRST: if a concurrent flush tick lands
            # between these two inserts, the failure direction is a
            # prunable dangling key — never a durable span whose key was
            # still mem-only (query_ordered would omit it forever)
            for rt in ordered_tags:
                v = sp.tags.get(rt)
                if v is None:
                    continue
                self._ordered_index(group, seg, rt).insert(
                    int(v), sidx_encode_ref(trace_id, sp.ts_millis)
                )
            seg.shards[shard].ingest(
                lambda mem: mem.append(
                    name, tag_names, sp.ts_millis, sid, tag_bytes, sp.span
                )
            )
            n += 1
        return n

    def _flush_sidx_first(self) -> None:
        """Commit sidx flushes BEFORE span parts publish (the adapted
        sidx/interfaces.go:37 snapshot-transaction contract): stage every
        store's part, then publish them all, then let the caller flush
        spans.  Any crash between the two publish points leaves at worst
        DANGLING ordered keys, which query_ordered prunes via
        verify_live — never durable spans missing their ordering keys
        (the old order's divergence)."""
        txns = []
        try:
            for idx in list(self._sidx.values()):
                t = idx.prepare_flush()
                if t is not None:
                    txns.append(t)
        except BaseException:
            for t in txns:
                t.abort()
            raise
        for i, t in enumerate(txns):
            try:
                t.commit()
            except BaseException:
                # a failed commit must not leak the remaining stores'
                # flush locks (that would deadlock every future flush)
                for u in txns[i + 1 :]:
                    try:
                        u.abort()
                    except Exception:  # noqa: BLE001
                        pass
                raise

    def flush(self, group: Optional[str] = None) -> list[str]:
        out = []
        self._flush_sidx_first()
        for gname, db in list(self._tsdbs.items()):
            if group is None or gname == group:
                out.extend(db.flush_all())
                self._write_blooms(db, gname)
        for idx in list(self._sidx.values()):
            idx.merge()
        return out

    def _write_blooms(self, db: TSDB, group: str) -> None:
        """Attach a trace-id bloom file to parts that lack one."""
        for seg in db.segments:
            for shard in seg.shards:
                for part in shard.parts:
                    name = part.meta.get("trace")
                    if not name or (part.dir / BLOOM_FILE).exists():
                        continue
                    try:
                        t = self.registry.get_trace(group, name)
                    except KeyError:
                        continue
                    write_trace_bloom(part, t.trace_id_tag)

    def maintain(
        self, group: Optional[str] = None, *, flush_sidx: bool = True
    ) -> None:
        """Periodic companion work the generic lifecycle flusher can't do
        for trace TSDBs: trace-id bloom sidecars on new parts + sidx
        ordered-index flush/merge.  Ordering keys always publish BEFORE
        span parts (_flush_sidx_first here and as the lifecycle
        pre_flush hook), so no crash window leaves durable spans without
        their keys.  Wired as the lifecycle extra tick."""
        for gname, db in list(self._tsdbs.items()):
            if group is None or gname == group:
                self._write_blooms(db, gname)
        if flush_sidx:
            # skipped when the caller already runs _flush_sidx_first as
            # the lifecycle pre_flush hook (one sidx part per tick, not
            # two)
            self._flush_sidx_first()
        for idx in list(self._sidx.values()):
            idx.merge()

    def finalize_segments(self, group: str) -> int:
        """Run the sampler chain over COMPLETE segments: every shard's
        parts merge in one pass, so whole-trace keep decisions see every
        span (PIPELINE_EVENT_FINALIZE, trace finalize_scanner analog).
        Returns the number of shards compacted."""
        db = self._tsdb(group)
        n = 0
        for seg in db.segments:
            for shard in seg.shards:
                parts = shard.parts
                if len(parts) < 2:
                    continue
                if shard.merge(min_merge=len(parts), max_parts=len(parts)):
                    n += 1
        return n

    # -- queries -----------------------------------------------------------
    def query_by_trace_id(self, group: str, name: str, trace_id: str) -> list[dict]:
        """All spans of one trace (the trace span-store lookup)."""
        t0 = time.perf_counter()
        try:
            return self._query_by_trace_id(group, name, trace_id)
        finally:
            _H_QUERY_TRACE.observe((time.perf_counter() - t0) * 1000)

    def _query_by_trace_id(
        self, group: str, name: str, trace_id: str
    ) -> list[dict]:
        t = self.get_trace(group, name)
        db = self._tsdb(group)
        shard_num = self.registry.get_group(group).resource_opts.shard_num
        shard_idx = trace_shard_id(trace_id, shard_num)
        tid = trace_id.encode()
        out: list[dict] = []
        self.last_bloom_blocks_skipped = 0
        for seg in db.segments:
            shard = seg.shards[shard_idx]
            # live memtable + in-flight flush snapshot (flush encodes
            # parts outside the shard lock)
            sources = list(shard.hot_columns(name))
            for part in shard.parts:
                if part.meta.get("trace") != name:
                    continue
                bloom_path = part.dir / BLOOM_FILE
                if bloom_path.exists():
                    bloom = Bloom.from_bytes(bloom_path.read_bytes())
                    if tid not in bloom:
                        n = len(part.blocks)
                        self.last_bloom_blocks_skipped += n
                        obs_metrics.global_meter().counter_add(
                            "blocks_skipped", float(n),
                            labels={"reason": "bloom"},
                        )
                        continue
                sources.append(
                    part.read(
                        range(len(part.blocks)),
                        tags=part.meta["tags"],
                        want_payload=True,
                    )
                )
            for src in sources:
                d = src.dicts.get(t.trace_id_tag, [])
                lut = {v: i for i, v in enumerate(d)}
                code = lut.get(tid, -1)
                if code < 0:
                    continue
                sel = np.nonzero(src.tags[t.trace_id_tag] == code)[0]
                for i in sel:
                    out.append(self._row_to_span(t, src, int(i)))
        out.sort(key=lambda s: s["timestamp"])
        return out

    def query_ordered(
        self,
        group: str,
        name: str,
        order_tag: str,
        time_range: TimeRange,
        *,
        lo: Optional[int] = None,
        hi: Optional[int] = None,
        asc: bool = False,
        limit: int = 20,
        offset: int = 0,
        verify_live: bool = True,
        with_keys: bool = False,
        accept=None,
        shard_pred=None,
    ) -> list:
        """Trace ids ordered by an indexed numeric tag (sidx TYPE_TREE
        retrieval: e.g. slowest traces in a window).

        limit AND offset both count accepted traces and are consumed
        inside the walk — offset skips the first `offset` survivors
        without ever fetching their spans into the result, so page N
        costs one walk, not N fetches.

        with_keys=True returns [(key, trace_id)] instead of bare ids —
        the distributed path needs the ordering keys to k-way merge
        per-node results at the liaison.

        verify_live drops ids whose spans were since removed by the
        sampler pipeline (the ordered index is ingest-time and is not
        rewritten by merge gating); cost is one span lookup per
        candidate, bounded by `limit + offset`.  `accept` generalizes it:
        a callable(trace_id) -> bool deciding survival (residual criteria
        checks ride the same span fetch).  `shard_pred(trace_id)` drops
        candidates routed to shards this node does not own — the sidx is
        per-segment, not per-shard.
        """
        t_q0 = time.perf_counter()
        try:
            return self._query_ordered(
                group, name, order_tag, time_range, lo=lo, hi=hi, asc=asc,
                limit=limit, offset=offset, verify_live=verify_live,
                with_keys=with_keys, accept=accept, shard_pred=shard_pred,
            )
        finally:
            _H_QUERY_TRACE.observe((time.perf_counter() - t_q0) * 1000)

    def _query_ordered(
        self,
        group: str,
        name: str,
        order_tag: str,
        time_range: TimeRange,
        *,
        lo: Optional[int] = None,
        hi: Optional[int] = None,
        asc: bool = False,
        limit: int = 20,
        offset: int = 0,
        verify_live: bool = True,
        with_keys: bool = False,
        accept=None,
        shard_pred=None,
    ) -> list:
        import heapq

        db = self._tsdb(group)
        # One key-ordered stream per overlapping segment, heap-merged so
        # the global order holds across segment boundaries.  Per-segment
        # fetch starts at 4x (limit+offset) (headroom for duplicates /
        # dead candidates) and grows adaptively: if fewer than `limit`
        # live ids survive while some segment's stream was truncated at
        # its cap, the fetch quadruples and the scan repeats — heavy
        # tail-sampling kill rates never starve the result below what
        # actually exists.  sidx block pruning keeps reads key-relevant.
        segs = db.select_segments(time_range.begin_millis, time_range.end_millis)
        fetch = max(limit + max(offset, 0), 1) * 4
        while True:
            self.last_sidx_blocks_read = 0
            streams = []
            truncated = False
            for seg in segs:
                st = self._ordered_index(group, seg, order_tag)
                chunk = st.range_query(lo, hi, asc=asc, limit=fetch)
                truncated = truncated or len(chunk) >= fetch
                streams.append(iter(chunk))
                self.last_sidx_blocks_read += st.last_blocks_read
            # tid tie-break keeps equal keys deterministic across
            # repeated walks, topologies and replica merges
            merged = heapq.merge(
                *streams,
                key=lambda kp: (
                    kp[0] if asc else -kp[0],
                    sidx_decode_ref(kp[1])[0],
                ),
            )
            seen: set[str] = set()
            out: list[str] = []
            keyed: list[tuple[int, str]] = []
            skip = 0
            for _k, payload in merged:
                tid, ts = sidx_decode_ref(payload)
                if not (time_range.begin_millis <= ts < time_range.end_millis):
                    continue
                if shard_pred is not None and not shard_pred(tid):
                    continue
                if tid in seen:
                    continue
                if accept is not None:
                    if not accept(tid):
                        continue
                elif verify_live and not self.query_by_trace_id(
                    group, name, tid
                ):
                    continue
                seen.add(tid)
                if skip < offset:
                    skip += 1
                    continue
                out.append(tid)
                keyed.append((int(_k), tid))
                if len(out) >= limit:
                    return keyed if with_keys else out
            if not truncated:
                return keyed if with_keys else out
            fetch *= 4

    # -- unified span-level query surface ----------------------------------
    def query(self, req: QueryRequest, *, shard_ids=None, tracer=None) -> QueryResult:
        """Full trace read surface: general AND tag criteria (eq/ne/in/
        not_in, numeric ranges), tag projection, sidx order-by asc/desc
        with limit+offset consumed inside the walk.  Plans split three
        ways (classify_plan): trace-id criteria go through the bloom-
        gated span store, order_by_tag through the sidx tree, and
        criteria-only scans prune blocks on per-part zone maps before
        any read.  `shard_ids` restricts to owned shards (distributed
        data nodes); rows are {trace_id, timestamp, tags, span[, key]}.
        """
        t_q0 = time.perf_counter()
        try:
            return self._query(req, shard_ids=shard_ids, tracer=tracer)
        finally:
            _H_QUERY_TRACE.observe((time.perf_counter() - t_q0) * 1000)

    def _query(self, req: QueryRequest, *, shard_ids=None, tracer=None) -> QueryResult:
        from banyandb_tpu.obs.tracer import NOOP_TRACER
        from banyandb_tpu.query.ql_exec import span_matches

        tr = tracer if tracer is not None else NOOP_TRACER
        group = req.groups[0]
        t = self.get_trace(group, req.name)
        tid_tag = t.trace_id_tag
        kind, tids, lo, hi, residual = classify_plan(req, tid_tag)
        off = max(req.offset or 0, 0)
        limit = req.limit or _DEFAULT_LIMITS[kind]
        proj = tuple(req.tag_projection or ())
        rng = req.time_range
        shard_num = self.registry.get_group(group).resource_opts.shard_num
        owned = set(shard_ids) if shard_ids is not None else None

        def in_range(ts: int) -> bool:
            return rng.begin_millis <= ts < rng.end_millis

        def shape(tid: str, span: dict, key=None) -> dict:
            tags = span["tags"]
            if proj:
                tags = {k: v for k, v in tags.items() if k in proj}
            row = {
                "trace_id": tid,
                "timestamp": span["timestamp"],
                "tags": tags,
                "span": span.get("span", b""),
            }
            if key is not None:
                row["key"] = int(key)
            return row

        res = QueryResult()
        if kind == "by_id":
            rows: list[dict] = []
            skipped = 0
            with tr.span("bloom") as bs:
                for tid in tids:
                    if owned is not None and (
                        trace_shard_id(tid, shard_num) not in owned
                    ):
                        continue
                    for s in self._query_by_trace_id(group, req.name, tid):
                        if not in_range(s["timestamp"]):
                            continue
                        if residual and not span_matches(s, residual):
                            continue
                        rows.append(shape(tid, s))
                    skipped += self.last_bloom_blocks_skipped
                bs.tag("traces", len(tids))
                bs.tag("blocks_skipped", skipped)
            with tr.span("merge") as ms:
                rows.sort(key=_row_order)
                rows = rows[off : off + limit]
                ms.tag("rows", len(rows))
            res.data_points = rows
            return res

        if kind == "ordered":
            spans_cache: dict[str, list[dict]] = {}

            def accept(tid: str) -> bool:
                spans = [
                    s
                    for s in self._query_by_trace_id(group, req.name, tid)
                    if in_range(s["timestamp"])
                    and (not residual or span_matches(s, residual))
                ]
                if spans:
                    spans_cache[tid] = spans
                return bool(spans)

            shard_pred = None
            if owned is not None:
                shard_pred = (
                    lambda tid: trace_shard_id(tid, shard_num) in owned
                )
            with tr.span("sidx") as ss:
                keyed = self._query_ordered(
                    group, req.name, req.order_by_tag, rng,
                    lo=lo, hi=hi, asc=(req.order_by_dir != "desc"),
                    limit=limit, offset=off, with_keys=True,
                    accept=accept, shard_pred=shard_pred,
                )
                ss.tag("traces", len(keyed))
                ss.tag("blocks_read", self.last_sidx_blocks_read)
            rows = []
            with tr.span("part_gather") as ps:
                for k, tid in keyed:
                    for s in spans_cache[tid]:
                        rows.append(shape(tid, s, key=k))
                ps.tag("rows", len(rows))
            res.data_points = rows
            return res

        # criteria-only scan: zone-map planning before any block read
        rows = []
        blocks_read = 0
        zone_conds, range_conds = _scan_pruners(t, residual)
        from banyandb_tpu.storage.encoded import zone_skip_enabled

        use_zones = zone_skip_enabled()
        db = self._tsdb(group)
        with tr.span("part_gather") as ps:
            for seg in db.select_segments(rng.begin_millis, rng.end_millis):
                for shard_idx, shard in enumerate(seg.shards):
                    if owned is not None and shard_idx not in owned:
                        continue
                    sources = list(shard.hot_columns(req.name))
                    for part in shard.parts:
                        if part.meta.get("trace") != req.name:
                            continue
                        preds = None
                        if use_zones:
                            preds = _part_scan_preds(
                                part, zone_conds, range_conds
                            )
                        bids = part.select_blocks(
                            rng.begin_millis, rng.end_millis,
                            zone_preds=preds,
                        )
                        if not len(bids):
                            continue
                        blocks_read += len(bids)
                        sources.append(
                            part.read(
                                bids,
                                tags=part.meta["tags"],
                                want_payload=True,
                            )
                        )
                    for src in sources:
                        for i in range(len(src.ts)):
                            if not in_range(int(src.ts[i])):
                                continue
                            s = self._row_to_span(t, src, i)
                            if residual and not span_matches(s, residual):
                                continue
                            tid = str(s["tags"].get(tid_tag, ""))
                            rows.append(shape(tid, s))
            ps.tag("rows", len(rows))
            ps.tag("blocks_read", blocks_read)
        with tr.span("merge") as ms:
            rows.sort(key=_row_order)
            rows = rows[off : off + limit]
            ms.tag("rows", len(rows))
        res.data_points = rows
        return res

    def _row_to_span(self, t: Trace, src: ColumnData, i: int) -> dict:
        from banyandb_tpu.query import filter as qfilter

        tags = {
            tn: qfilter.decode_tag_value(src.dicts[tn][col[i]], t.tag(tn).type)
            for tn, col in src.tags.items()
        }
        return {
            "timestamp": int(src.ts[i]),
            "tags": tags,
            "span": src.payloads[i] if src.payloads else b"",
        }


def _row_order(row: dict) -> tuple:
    """Deterministic scan/by-id row order: (ts, trace_id, payload) — the
    liaison merge re-sorts with the same key so topologies agree byte-
    for-byte even on equal timestamps."""
    return (row["timestamp"], row["trace_id"], row["span"])


def _scan_pruners(t: Trace, residual: list) -> tuple[list, dict]:
    """Split residual AND leaves into zone-map prunable shapes:
    (eq/IN byte-value conds, {int_tag: [range conds]}).  Anything not
    prunable stays residual-only — pruning is best-effort, filtering is
    authoritative."""
    zone_conds: list[tuple[str, list[bytes]]] = []
    range_conds: dict[str, list] = {}
    for c in residual:
        try:
            tag_type = t.tag(c.name).type
        except KeyError:
            continue
        if c.op == "eq":
            try:
                zone_conds.append((c.name, [measure_exec._tag_value_bytes(c.value)]))
            except TypeError:
                pass
        elif c.op == "in":
            try:
                zone_conds.append(
                    (c.name, [measure_exec._tag_value_bytes(v) for v in c.value])
                )
            except TypeError:
                pass
        elif c.op in ("gt", "ge", "lt", "le") and tag_type is TagType.INT:
            try:
                float(c.value)
            except (TypeError, ValueError):
                continue
            range_conds.setdefault(c.name, []).append(c)
    return zone_conds, range_conds


def _range_ok(v: float, conds: list) -> bool:
    for c in conds:
        b = float(c.value)
        if c.op == "gt" and not v > b:
            return False
        if c.op == "ge" and not v >= b:
            return False
        if c.op == "lt" and not v < b:
            return False
        if c.op == "le" and not v <= b:
            return False
    return True


def _part_scan_preds(part, zone_conds, range_conds) -> Optional[list]:
    """Allowed-code zone predicates for one part: eq/IN conds via the
    shared planner lowering, plus INT range conds decoded against the
    part's tag dictionary (absent raw = unset = 0, matching
    decode_tag_value).  A tag whose dictionary has no surviving code
    collapses to the none-match sentinel — the whole part prunes without
    reading a block."""
    from banyandb_tpu.query.planner import part_zone_preds

    preds = list(part_zone_preds(part, zone_conds)) if zone_conds else []
    part_tags = part.meta.get("tags", ())
    for name, conds in range_conds.items():
        if name not in part_tags:
            # no column: every row decodes to 0; prune only if 0 fails
            if not _range_ok(0.0, conds):
                preds.append(("*", np.zeros(0, dtype=np.int64)))
            continue
        codes = [
            i
            for i, raw in enumerate(part.dict_for(name))
            if _range_ok(
                float(int.from_bytes(raw, "little", signed=True)) if raw else 0.0,
                conds,
            )
        ]
        if not codes:
            preds.append(("*", np.zeros(0, dtype=np.int64)))
        else:
            preds.append((f"tag_{name}", np.asarray(sorted(codes), dtype=np.int64)))
    return preds or None
