"""Property anti-entropy repair: Merkle reconciliation between replicas.

Analog of banyand/property/db/repair.go + repair_gossip.go
(docs/concept/property-repair.md): each replica summarizes its
(group, name) property set as a two-level hash tree — root over 256
slots, slot over the docs hashing into it (slot = doc_id % 256) — and two
replicas reconcile root -> differing slots -> per-doc (id, mod_revision)
lists; the higher mod_revision wins each conflict and missing docs copy
across.  The exchange shape mirrors the reference's bidi-gRPC rounds but
runs over any pair of PropertyEngine handles (the gossip scheduler
drives pair selection above this layer).
"""

from __future__ import annotations

import hashlib
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from banyandb_tpu.models.property import Property, PropertyEngine

SLOTS = 256


def _doc_hash(p) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(p.id.encode())
    h.update(p.mod_revision.to_bytes(8, "little"))
    for k in sorted(p.tags):
        h.update(k.encode() + b"=" + str(p.tags[k]).encode() + b";")
    return h.digest()


def wins(x, y) -> bool:
    """True when doc x beats doc y: higher mod_revision, with a
    deterministic content-hash tie-break — revisions are per-node
    counters, so two nodes can mint EQUAL revisions for different
    content; without a total order those replicas would never
    converge."""
    if x.mod_revision != y.mod_revision:
        return x.mod_revision > y.mod_revision
    return _doc_hash(x) > _doc_hash(y)


def _slot_of(p) -> int:
    return int.from_bytes(
        hashlib.blake2b(p.id.encode(), digest_size=2).digest(), "little"
    ) % SLOTS


def state_tree(engine: "PropertyEngine", group: str, name: str) -> dict:
    """{'root': hex, 'slots': {slot: hex}} — the state-tree.data analog."""
    slots: dict[int, hashlib.blake2b] = {}
    for p in engine.query(group, name, limit=1_000_000):
        s = _slot_of(p)
        h = slots.get(s)
        if h is None:
            h = slots[s] = hashlib.blake2b(digest_size=16)
        h.update(_doc_hash(p))
    slot_hex = {s: h.hexdigest() for s, h in sorted(slots.items())}
    root = hashlib.blake2b(digest_size=16)
    for s, hx in sorted(slot_hex.items()):
        root.update(s.to_bytes(2, "little") + bytes.fromhex(hx))
    return {"root": root.hexdigest(), "slots": slot_hex}


def _slot_docs(engine, group, name, slot: int) -> dict[str, "Property"]:
    return {
        p.id: p
        for p in engine.query(group, name, limit=1_000_000)
        if _slot_of(p) == slot
    }


def repair_pair(
    a: "PropertyEngine", b: "PropertyEngine", group: str, name: str
) -> int:
    """Reconcile (group, name) between two replicas; returns docs copied.

    Round 1: roots.  Round 2: differing slots.  Round 3: per-doc
    (id, mod) — higher mod_revision wins, ties are already identical by
    hash construction, missing docs copy across.
    """
    from banyandb_tpu.models.property import Property

    ta, tb = state_tree(a, group, name), state_tree(b, group, name)
    if ta["root"] == tb["root"]:
        return 0
    slots = set(ta["slots"]) | set(tb["slots"])
    copied = 0
    for s in slots:
        if ta["slots"].get(s) == tb["slots"].get(s):
            continue
        docs_a = _slot_docs(a, group, name, int(s))
        docs_b = _slot_docs(b, group, name, int(s))
        for pid in set(docs_a) | set(docs_b):
            pa, pb = docs_a.get(pid), docs_b.get(pid)
            if pa is not None and (pb is None or wins(pa, pb)):
                _install(b, pa)
                copied += 1
            elif pb is not None and (pa is None or wins(pb, pa)):
                _install(a, pb)
                copied += 1
    return copied


# -- persisted shard state tree (state-tree.data analog) --------------------


def _entity_of(p) -> str:
    return f"{p.name}/{p.id}"


def _entity_slot(entity: str) -> int:
    return int.from_bytes(
        hashlib.blake2b(entity.encode(), digest_size=2).digest(), "little"
    ) % SLOTS


def build_shard_tree(engine: "PropertyEngine", group: str, shard: int) -> dict:
    """Three-level Merkle over one (group, shard): root -> slot SHAs ->
    per-entity leaf SHAs, PERSISTED next to the shard
    (banyand/property/db/repair.go:95 state-tree.data analog).  The
    persisted tree is reused while the engine revision is unchanged, so
    repeated gossip rounds over a quiet shard cost one file read."""
    import json

    from banyandb_tpu.utils import fs

    path = engine.root / "repair" / f"state-tree-{group}-{shard}.json"
    rev = engine.revision
    try:
        cached = json.loads(path.read_text())
        if cached.get("built_rev") == rev:
            return cached
    except (OSError, ValueError):
        pass

    leaves: dict[str, list] = {}
    for p in engine.docs_in_shard(group, shard):
        e = _entity_of(p)
        s = str(_entity_slot(e))
        leaves.setdefault(s, []).append([e, _doc_hash(p).hex()])
    for lst in leaves.values():
        lst.sort()
    slot_sha = {}
    for s, lst in leaves.items():
        h = hashlib.blake2b(digest_size=16)
        for e, hx in lst:
            h.update(e.encode() + bytes.fromhex(hx))
        slot_sha[s] = h.hexdigest()
    root = hashlib.blake2b(digest_size=16)
    for s in sorted(slot_sha, key=int):
        root.update(int(s).to_bytes(2, "little") + bytes.fromhex(slot_sha[s]))
    tree = {
        "built_rev": rev,
        "root": root.hexdigest(),
        "slots": slot_sha,
        "leaves": leaves,
    }
    path.parent.mkdir(parents=True, exist_ok=True)
    fs.atomic_write_json(path, tree)
    return tree


def install_verbatim(engine: "PropertyEngine", p) -> None:
    """Public alias of _install for the wire repair path."""
    _install(engine, p)


def _install(engine: "PropertyEngine", p) -> None:
    """Install a replica's doc verbatim (preserving its mod_revision) —
    repair must not mint new revisions or the tree never converges."""
    import json

    from banyandb_tpu.index.inverted import Doc

    # the engine's revision counter is the persisted state tree's
    # freshness guard: advance it so the NEXT build_shard_tree sees the
    # install (the doc's own mod_revision stays the replica's, above)
    with engine._lock:
        engine._revision += 1
    idx = engine._shard_for(p.group, p.name, p.id)
    keywords = {"@name": p.name.encode(), "@id": p.id.encode()}
    for k, v in p.tags.items():
        keywords[k] = str(v).encode()
    idx.insert(
        [
            Doc(
                doc_id=engine._doc_id(p.name, p.id),
                keywords=keywords,
                numerics={"@mod": p.mod_revision, "@create": p.create_revision},
                payload=json.dumps(
                    {"id": p.id, "name": p.name, "tags": p.tags}
                ).encode(),
            )
        ]
    )
