"""Property anti-entropy repair: Merkle reconciliation between replicas.

Analog of banyand/property/db/repair.go + repair_gossip.go
(docs/concept/property-repair.md): each replica summarizes its
(group, name) property set as a two-level hash tree — root over 256
slots, slot over the docs hashing into it (slot = doc_id % 256) — and two
replicas reconcile root -> differing slots -> per-doc (id, mod_revision)
lists; the higher mod_revision wins each conflict and missing docs copy
across.  The exchange shape mirrors the reference's bidi-gRPC rounds but
runs over any pair of PropertyEngine handles (the gossip scheduler
drives pair selection above this layer).
"""

from __future__ import annotations

import hashlib
from typing import TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover
    from banyandb_tpu.models.property import Property, PropertyEngine

SLOTS = 256


def _doc_hash(p) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    h.update(p.id.encode())
    h.update(p.mod_revision.to_bytes(8, "little"))
    for k in sorted(p.tags):
        h.update(k.encode() + b"=" + str(p.tags[k]).encode() + b";")
    return h.digest()


def _slot_of(p) -> int:
    return int.from_bytes(
        hashlib.blake2b(p.id.encode(), digest_size=2).digest(), "little"
    ) % SLOTS


def state_tree(engine: "PropertyEngine", group: str, name: str) -> dict:
    """{'root': hex, 'slots': {slot: hex}} — the state-tree.data analog."""
    slots: dict[int, hashlib.blake2b] = {}
    for p in engine.query(group, name, limit=1_000_000):
        s = _slot_of(p)
        h = slots.get(s)
        if h is None:
            h = slots[s] = hashlib.blake2b(digest_size=16)
        h.update(_doc_hash(p))
    slot_hex = {s: h.hexdigest() for s, h in sorted(slots.items())}
    root = hashlib.blake2b(digest_size=16)
    for s, hx in sorted(slot_hex.items()):
        root.update(s.to_bytes(2, "little") + bytes.fromhex(hx))
    return {"root": root.hexdigest(), "slots": slot_hex}


def _slot_docs(engine, group, name, slot: int) -> dict[str, "Property"]:
    return {
        p.id: p
        for p in engine.query(group, name, limit=1_000_000)
        if _slot_of(p) == slot
    }


def repair_pair(
    a: "PropertyEngine", b: "PropertyEngine", group: str, name: str
) -> int:
    """Reconcile (group, name) between two replicas; returns docs copied.

    Round 1: roots.  Round 2: differing slots.  Round 3: per-doc
    (id, mod) — higher mod_revision wins, ties are already identical by
    hash construction, missing docs copy across.
    """
    from banyandb_tpu.models.property import Property

    ta, tb = state_tree(a, group, name), state_tree(b, group, name)
    if ta["root"] == tb["root"]:
        return 0
    slots = set(ta["slots"]) | set(tb["slots"])
    copied = 0
    for s in slots:
        if ta["slots"].get(s) == tb["slots"].get(s):
            continue
        docs_a = _slot_docs(a, group, name, int(s))
        docs_b = _slot_docs(b, group, name, int(s))
        for pid in set(docs_a) | set(docs_b):
            pa, pb = docs_a.get(pid), docs_b.get(pid)
            if pa is not None and (pb is None or pa.mod_revision > pb.mod_revision):
                _install(b, pa)
                copied += 1
            elif pb is not None and (pa is None or pb.mod_revision > pa.mod_revision):
                _install(a, pb)
                copied += 1
    return copied


def _install(engine: "PropertyEngine", p) -> None:
    """Install a replica's doc verbatim (preserving its mod_revision) —
    repair must not mint new revisions or the tree never converges."""
    import json

    from banyandb_tpu.index.inverted import Doc

    idx = engine._shard_for(p.group, p.name, p.id)
    keywords = {"@name": p.name.encode(), "@id": p.id.encode()}
    for k, v in p.tags.items():
        keywords[k] = str(v).encode()
    idx.insert(
        [
            Doc(
                doc_id=engine._doc_id(p.name, p.id),
                keywords=keywords,
                numerics={"@mod": p.mod_revision, "@create": p.create_revision},
                payload=json.dumps(
                    {"id": p.id, "name": p.name, "tags": p.tags}
                ).encode(),
            )
        ]
    )
