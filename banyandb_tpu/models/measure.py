"""Measure engine: metrics with tags + numeric fields per series.

Analog of banyand/measure (measure.go:81, write path tstable.go:333,
query path query.go:88) over the TPU-first substrate: writes land in
per-shard memtables routed by entity hash; queries gather memtable +
part columns and run the device executor (query/measure_exec.py).
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Optional

import numpy as np

from banyandb_tpu.api.model import (
    QueryRequest,
    QueryResult,
    WriteRequest,
)
from banyandb_tpu.api.schema import (
    FieldType,
    Measure,
    SchemaRegistry,
    TagType,
)
from banyandb_tpu.obs import metrics as obs_metrics
from banyandb_tpu.obs.tracer import NOOP_TRACER, Tracer
from banyandb_tpu.query import filter as qfilter
from banyandb_tpu.query import measure_exec
from banyandb_tpu.storage.memtable import MemTable
from banyandb_tpu.storage.part import ColumnData
from banyandb_tpu.storage.tsdb import TSDB
from banyandb_tpu.utils import hashing


_RAW_FIELD_TYPES = (FieldType.STRING, FieldType.DATA_BINARY)
_RAW_FIELD_PREFIX = "@f:"

# engine-level latency instrument (one per query engine; the other
# three live in their models/ modules) + part-gather stage attribution
_H_QUERY = obs_metrics.global_meter().histogram(
    "query_ms", {"engine": "measure"}
)
_H_PART_GATHER = obs_metrics.stage_histogram("part_gather")

# Server-assigned write versions are MONOTONIC per process (the
# reference assigns nanosecond timestamps per point): two writes of the
# same (series, ts) must resolve to the later one, even within one
# batch/millisecond.  A plain now()-per-batch ties and dedup picks
# arbitrarily.
import threading as _threading

_version_lock = _threading.Lock()
_version_base = time.time_ns()


def _next_versions(n: int) -> int:
    """Reserve n consecutive monotonic versions; returns the first."""
    global _version_base
    with _version_lock:
        start = _version_base
        _version_base += n
        return start


def _numeric_fields(m: Measure):
    return [f for f in m.fields if f.type not in _RAW_FIELD_TYPES]


def _tag_col_names(m: Measure) -> list[str]:
    """Schema tags + reserved raw-field columns, the storage tag layout."""
    return [t.name for t in m.tags] + [
        _RAW_FIELD_PREFIX + f.name for f in _raw_fields(m)
    ]


def _raw_fields(m: Measure):
    """STRING / DATA_BINARY fields: stored, projected, never aggregated.

    They ride the dictionary-encoded tag machinery under reserved
    '@f:<name>' column names (the part/memtable formats already handle
    arbitrary byte columns there), mirroring the reference's non-numeric
    field columns (FIELD_TYPE_STRING in pkg/test/measure/testdata)."""
    return [f for f in m.fields if f.type in _RAW_FIELD_TYPES]


def _raw_field_bytes(v) -> bytes:
    if v is None:
        return b""
    if isinstance(v, bytes):
        return v
    if isinstance(v, str):
        return v.encode()
    return str(v).encode()


class DictColumn:
    """A dictionary-encoded tag column: `values` (distinct tag values)
    + int `codes` per row.  The wire's columnar write envelope ships tag
    columns this way; keeping the encoding end-to-end (client -> bus ->
    engine -> memtable) means per-row Python work never happens on the
    ingest hot path — only per-DISTINCT-value work does."""

    __slots__ = ("values", "codes")

    def __init__(self, values: list, codes: np.ndarray):
        self.values = values
        self.codes = np.asarray(codes)

    def __len__(self) -> int:
        return len(self.codes)

    def __getitem__(self, i):
        # row-shaped access for the slow paths (index-mode, series docs)
        return self.values[int(self.codes[i])]

    def take(self, idx: np.ndarray) -> "DictColumn":
        return DictColumn(self.values, self.codes[idx])

    def row_values(self) -> list:
        """Materialized per-row value list (compat escape hatch)."""
        return np.asarray(self.values, dtype=object)[self.codes].tolist()


def series_ids_for_columns(
    name: str, ent_cols: list, n: int
) -> "tuple[np.ndarray, np.ndarray]":
    """Vectorized series-id assignment for columnar ingest: hash each
    DISTINCT entity tuple once.  ``ent_cols`` holds one column per
    entity tag, each a ``DictColumn`` of canonical bytes or a per-row
    bytes list.  -> (per-row series ids [n], unique-inverse index [n]).

    Shared by ``MeasureEngine.write_columns`` and the worker pool's
    shard router (cluster/workers.py) so in-process and multi-process
    ingest route every row to the same shard."""
    radix_prod = 1
    for c in ent_cols:
        if isinstance(c, DictColumn):
            radix_prod *= max(len(c.values), 1)
    if all(isinstance(c, DictColumn) for c in ent_cols) and (
        radix_prod < 2**62  # int64 mixed-radix key must not wrap
    ):
        # all-encoded fast lane: distinct entities are distinct
        # mixed-radix code keys — int unique, zero per-row Python
        key = np.zeros(n, dtype=np.int64)
        for c in ent_cols:
            key = key * len(c.values) + np.asarray(c.codes, dtype=np.int64)
        uk, inv = np.unique(key, return_inverse=True)
        radices = [len(c.values) for c in ent_cols]
        digits: list[np.ndarray] = []
        rem = uk
        for r in reversed(radices):
            digits.append(rem % r)
            rem = rem // r
        digits.reverse()  # per-entity-tag unique codes aligned with uk
        uniq_sids = np.fromiter(
            (
                hashing.series_id(
                    [name.encode()]
                    + [
                        ent_cols[j].values[int(digits[j][i])]
                        for j in range(len(ent_cols))
                    ]
                )
                for i in range(len(uk))
            ),
            dtype=np.int64,
            count=len(uk),
        )
    else:
        rowed = [
            c.row_values() if isinstance(c, DictColumn) else c
            for c in ent_cols
        ]
        ent_rows = np.empty(n, dtype=object)
        for i in range(n):
            ent_rows[i] = tuple(c[i] for c in rowed)
        uniq, inv = np.unique(ent_rows, return_inverse=True)
        uniq_sids = np.fromiter(
            (hashing.series_id([name.encode(), *e]) for e in uniq),
            dtype=np.int64,
            count=len(uniq),
        )
    return uniq_sids[inv], inv


class MeasureEngine:
    """All measure resources of all groups, one TSDB per group."""

    def __init__(self, registry: SchemaRegistry, root: str | Path):
        from banyandb_tpu.models.topn import TopNProcessorManager

        import threading

        self.registry = registry
        self.root = Path(root) / "measure"
        self._tsdbs: dict[str, TSDB] = {}
        self._tsdb_lock = threading.Lock()
        self._loops = None
        self.topn = TopNProcessorManager(self)
        # Serving-cache companions: persistent dictionaries + remaps per
        # measure (measure_exec.DictState), created lazily under the lock.
        self._dict_states: dict[tuple[str, str], measure_exec.DictState] = {}
        # Continuous streaming aggregation (query/streamagg.py): rolling
        # materialized windows for registered dashboard signatures,
        # updated at ingest and reloaded (with a deterministic part
        # backfill) across restarts.  Function-local import: the engines
        # layer reaches the executor layer lazily, like flush()'s
        # precompile hook.
        from banyandb_tpu.query.streamagg import StreamAggRegistry

        self.streamagg = StreamAggRegistry(self)

    def _dict_state(self, group: str, name: str) -> "measure_exec.DictState":
        key = (group, name)
        with self._tsdb_lock:
            st = self._dict_states.get(key)
            if st is None:
                st = self._dict_states[key] = measure_exec.DictState()
            return st

    def start_lifecycle(self, extra_tsdbs=None, **kw) -> None:
        """Start background flush/merge/retention (svc_standalone analog).

        extra_tsdbs: optional callable returning MORE TSDBs to manage —
        the stream/trace engines' trees, so parts installed there (e.g.
        via the liaison write queue) merge and retention-sweep too."""
        from banyandb_tpu.storage.loops import LifecycleLoops

        if self._loops is None:

            def all_tsdbs():
                out = list(self._tsdbs.values())
                if extra_tsdbs is not None:
                    out.extend(extra_tsdbs())
                return out

            self._loops = LifecycleLoops(all_tsdbs, **kw)
            self._loops.start()

    def stop_lifecycle(self) -> None:
        if self._loops is not None:
            self._loops.stop()
            self._loops = None

    def close(self) -> None:
        """Deterministic shutdown: stop the loops and release every
        TSDB's index memory and file handles (bdsan fd hygiene)."""
        self.stop_lifecycle()
        with self._tsdb_lock:
            dbs = list(self._tsdbs.values())
        for db in dbs:
            db.close()

    # -- plumbing ----------------------------------------------------------
    def _tsdb(self, group: str) -> TSDB:
        # Locked get-or-create: two racing creators would own duplicate
        # Shard objects over one directory (epoch collisions, lost writes).
        with self._tsdb_lock:
            db = self._tsdbs.get(group)
            if db is None:
                g = self.registry.get_group(group)
                # One memtable schema per group would be wrong — tag/field
                # sets differ per measure — so shards key their memtables
                # per measure.
                db = TSDB(
                    self.root,
                    group,
                    g.resource_opts,
                    mem_factory=lambda: _MultiMeasureMemtable(),
                )
                self._tsdbs[group] = db
            return db

    # -- write path (write_standalone.go analog) ---------------------------
    def write(self, req: WriteRequest, _internal: bool = False) -> int:
        m = self.registry.get_measure(req.group, req.name)
        db = self._tsdb(req.group)
        shard_num = self.registry.get_group(req.group).resource_opts.shard_num
        n = 0
        # streaming-aggregation hook rows (query/streamagg.py): only
        # collected when a materialized signature is registered for this
        # measure — the common case pays one frozenset lookup
        sa_rows = (
            []
            if not m.index_mode
            and self.streamagg.active(req.group, req.name)
            else None
        )
        # ingest gate (query/streamagg.py): ticket in before rows
        # become memtable-visible, out after the window observe — a
        # concurrent registration backfill drains these tickets before
        # it stops buffering, so pre-snapshot rows never double-apply
        self.streamagg.ingest_enter()
        try:
            for p in req.points:
                # Series identity is (measure, entity values) — two measures
                # sharing an entity tuple must not collide in the series index.
                entity = [req.name.encode()] + [
                    hashing.entity_bytes(p.tags[t]) for t in m.entity.tag_names
                ]
                sid = hashing.series_id(entity)
                seg = db.segment_for(p.ts_millis)
                version = p.version or _next_versions(1)
                tag_bytes = {
                    t.name: _tag_to_bytes(p.tags.get(t.name), t.type)
                    for t in m.tags
                }
                for f in _raw_fields(m):
                    tag_bytes[_RAW_FIELD_PREFIX + f.name] = _raw_field_bytes(
                        p.fields.get(f.name)
                    )
                field_vals = {
                    f.name: float(p.fields.get(f.name, 0))
                    for f in _numeric_fields(m)
                }
                if m.index_mode:
                    # Index-mode measures live entirely in the series index —
                    # one doc per data point (handleIndexMode,
                    # banyand/measure/write_standalone.go:348).
                    _index_mode_write(
                        seg, m, sid, p.ts_millis, version, tag_bytes, field_vals
                    )
                    n += 1
                    continue
                shard = hashing.shard_id(sid, shard_num)
                entity_tags = {t: tag_bytes[t] for t in m.entity.tag_names}
                entity_tags["@measure"] = req.name.encode()
                seg.series_index.insert_series(sid, entity_tags)
                seg.shards[shard].ingest(
                    lambda mem: mem.append_measure(
                        m.name,
                        _tag_col_names(m),
                        [f.name for f in _numeric_fields(m)],
                        p.ts_millis,
                        sid,
                        version,
                        tag_bytes,
                        field_vals,
                    )
                )
                n += 1
                if sa_rows is not None:
                    sa_rows.append(
                        (p.ts_millis, sid, version, shard, tag_bytes, field_vals)
                    )
                if not _internal:
                    self.topn.observe(m, p, sid=sid, version=version)
            if sa_rows:
                self._observe_streamagg_rows(m, sa_rows)
        finally:
            self.streamagg.ingest_exit()
        return n

    def _observe_streamagg_rows(self, m: Measure, rows: list) -> None:
        """Row-path bridge onto the columnar streamagg observe: rows are
        (ts, sid, version, shard, tag_bytes dict, field_vals dict)."""
        n = len(rows)
        ts = np.fromiter((r[0] for r in rows), np.int64, count=n)
        sids = np.fromiter((r[1] for r in rows), np.int64, count=n)
        vers = np.fromiter((r[2] for r in rows), np.int64, count=n)
        shards = np.fromiter((r[3] for r in rows), np.int64, count=n)
        self.streamagg.observe(
            m.group,
            m.name,
            ts=ts,
            series=sids,
            versions=vers,
            shards=shards,
            tag_col=lambda t: np.asarray(
                [r[4].get(t, b"") for r in rows], dtype=object
            ),
            field_col=lambda f: np.fromiter(
                (r[5].get(f, 0.0) for r in rows), np.float64, count=n
            ),
        )

    def write_points_bulk(self, req: WriteRequest) -> int:
        """Row-shaped request -> columnar ingest: the wire handlers'
        bridge onto write_columns.  One decode pass over the points
        builds columns; entity-tag presence is validated with the row
        path's strictness (missing entity tag raises KeyError rather
        than silently writing the empty value)."""
        m = self.registry.get_measure(req.group, req.name)
        pts = req.points
        n = len(pts)
        if n == 0:
            return 0
        ts = np.fromiter((p.ts_millis for p in pts), np.int64, count=n)
        v0 = _next_versions(n)
        versions = np.fromiter(
            ((p.version or (v0 + i)) for i, p in enumerate(pts)),
            np.int64,
            count=n,
        )
        tags = {t.name: [p.tags.get(t.name) for p in pts] for t in m.tags}
        for t in m.entity.tag_names:
            if any(v is None for v in tags[t]):
                raise KeyError(t)
        fields: dict[str, object] = {
            f.name: np.fromiter(
                (float(p.fields.get(f.name, 0)) for p in pts),
                np.float64,
                count=n,
            )
            for f in _numeric_fields(m)
        }
        for f in _raw_fields(m):
            fields[f.name] = [p.fields.get(f.name) for p in pts]
        return self.write_columns(
            req.group,
            req.name,
            ts_millis=ts,
            tags=tags,
            fields=fields,
            versions=versions,
        )

    def write_columns(
        self,
        group: str,
        name: str,
        *,
        ts_millis: np.ndarray,
        tags: dict[str, list],
        fields: dict[str, np.ndarray],
        versions: Optional[np.ndarray] = None,
    ) -> int:
        """Vectorized bulk ingest (the high-throughput write path).

        Row-oriented write() parses point protos one by one (the
        reference's gRPC streaming shape); collectors that already hold
        columns use this path: unique entities are hashed once, routing
        and interning are NumPy passes, and memtable appends are bulk
        extends.  Semantics match write() exactly — TopN rules observe
        bulk writes (topn.observe_columns) and index-mode measures take
        the per-doc index path — one write path, two decode shapes
        (ref single path banyand/measure/write_standalone.go:348).
        """
        m = self.registry.get_measure(group, name)
        db = self._tsdb(group)
        opts = self.registry.get_group(group).resource_opts
        shard_num = opts.shard_num
        iv_millis = opts.segment_interval.millis
        n = len(ts_millis)
        if n == 0:
            return 0
        versions = (
            versions
            if versions is not None
            else _next_versions(n) + np.arange(n, dtype=np.int64)
        )
        tag_bytes: dict[str, object] = {}
        for t in m.tags:
            vals = tags.get(t.name)
            # None elements map to the empty value, matching the row path.
            # DictColumn stays encoded: only its DISTINCT values pay the
            # bytes conversion.  Columns are validated here (lengths,
            # code bounds) because a ragged or out-of-range column that
            # reached the memtable would corrupt it permanently — the
            # wire envelope hands us client-controlled codes.
            if vals is None:
                tag_bytes[t.name] = None
            elif isinstance(vals, DictColumn):
                codes = np.asarray(vals.codes)
                if len(codes) != n:
                    raise ValueError(
                        f"tag {t.name}: {len(codes)} codes for {n} rows"
                    )
                if codes.size and (
                    int(codes.min()) < 0
                    or int(codes.max()) >= len(vals.values)
                ):
                    raise ValueError(
                        f"tag {t.name}: code out of range for dict of "
                        f"{len(vals.values)}"
                    )
                tag_bytes[t.name] = DictColumn(
                    [
                        hashing.entity_bytes(v) if v is not None else b""
                        for v in vals.values
                    ],
                    codes,
                )
            else:
                if len(vals) != n:
                    raise ValueError(
                        f"tag {t.name}: {len(vals)} values for {n} rows"
                    )
                tag_bytes[t.name] = [
                    hashing.entity_bytes(v) if v is not None else b""
                    for v in vals
                ]
        for f in m.fields:
            col = fields.get(f.name)
            if col is not None and len(col) != n:
                raise ValueError(
                    f"field {f.name}: {len(col)} values for {n} rows"
                )
        if len(versions) != n:
            raise ValueError(f"{len(versions)} versions for {n} rows")
        # raw (string/binary) fields ride the tag machinery ('@f:' cols)
        for f in _raw_fields(m):
            vals = fields.get(f.name)
            key = _RAW_FIELD_PREFIX + f.name
            if vals is None:
                tag_bytes[key] = None
            elif isinstance(vals, DictColumn):
                tag_bytes[key] = DictColumn(
                    [_raw_field_bytes(v) for v in vals.values], vals.codes
                )
            else:
                tag_bytes[key] = [_raw_field_bytes(v) for v in vals]
        num_fields = {
            f.name: fields.get(f.name) for f in _numeric_fields(m)
        }
        for t in m.entity.tag_names:
            if tag_bytes.get(t) is None:
                # row-path strictness: a missing entity tag is a client
                # error, not an empty value
                raise KeyError(t)

        # --- series ids: hash each DISTINCT entity tuple once -------------
        ent_cols = [tag_bytes[t] for t in m.entity.tag_names]
        sids, inv = series_ids_for_columns(name, ent_cols, n)
        shards = sids % shard_num

        seg_cache: dict[int, object] = {}

        def seg_for(start: int):
            seg = seg_cache.get(start)
            if seg is None:
                seg = seg_cache[start] = db.segment_for(start)
            return seg

        # --- route per (segment, shard) with boolean masks ----------------
        seg_starts = ts_millis - (ts_millis % iv_millis)
        if m.index_mode:
            # One index doc per point (handleIndexMode analog, same
            # semantics as the row path): the inverted index takes docs
            # one at a time, so the win here is upstream decode only.
            # Index-mode rows never feed TopN (row-path parity).
            for start in np.unique(seg_starts).tolist():
                seg = seg_for(int(start))
                for i in np.nonzero(seg_starts == start)[0].tolist():
                    _index_mode_write(
                        seg,
                        m,
                        int(sids[i]),
                        int(ts_millis[i]),
                        int(versions[i]),
                        {
                            t: (
                                tag_bytes[t][i]
                                if tag_bytes[t] is not None
                                else b""
                            )
                            for t in tag_bytes
                        },
                        {
                            f.name: (
                                float(np.asarray(num_fields[f.name])[i])
                                if num_fields.get(f.name) is not None
                                else 0.0
                            )
                            for f in _numeric_fields(m)
                        },
                    )
            return n
        self.streamagg.ingest_enter()  # see write(): backfill drain gate
        try:
            for start in np.unique(seg_starts).tolist():
                seg = seg_for(int(start))
                seg_mask = seg_starts == start
                # series registration is PER SEGMENT (each segment owns its own
                # series index, same as the row path): one doc per distinct
                # entity appearing in this segment
                seg_rows = np.nonzero(seg_mask)[0]
                first = np.unique(inv[seg_mask], return_index=True)[1]
                for row in seg_rows[first].tolist():
                    doc = {t: tag_bytes[t][row] for t in m.entity.tag_names}
                    doc["@measure"] = name.encode()
                    seg.series_index.insert_series(int(sids[row]), doc)
                for shard_idx in np.unique(shards[seg_mask]).tolist():
                    mask = seg_mask & (shards == shard_idx)
                    idx = np.nonzero(mask)[0]
                    sel_tags = {}
                    for t, col in tag_bytes.items():
                        if col is None:
                            sel_tags[t] = None
                        elif isinstance(col, DictColumn):
                            sel_tags[t] = col.take(idx)
                        else:
                            sel_tags[t] = [col[i] for i in idx]
                    sel_fields = {}
                    for f in _numeric_fields(m):
                        v = num_fields.get(f.name)
                        sel_fields[f.name] = (
                            np.asarray(v)[idx] if v is not None else None
                        )
                    shard_obj = seg.shards[int(shard_idx)]
                    shard_obj.ingest(
                        lambda mem: mem.append_measure_bulk(
                            name,
                            _tag_col_names(m),
                            [f.name for f in _numeric_fields(m)],
                            ts_millis[idx],
                            sids[idx],
                            versions[idx],
                            sel_tags,
                            sel_fields,
                        )
                    )
            self.topn.observe_columns(
                m, ts_millis, tags, num_fields,
                sids=sids, versions=versions,
            )
            if self.streamagg.active(group, name):

                def _sa_tag(t: str) -> np.ndarray:
                    col = tag_bytes.get(t)
                    if col is None:
                        return np.full(n, b"", dtype=object)
                    if isinstance(col, DictColumn):
                        return np.asarray(col.values, dtype=object)[
                            np.asarray(col.codes)
                        ]
                    return np.asarray(col, dtype=object)

                def _sa_field(f: str) -> np.ndarray:
                    col = num_fields.get(f)
                    if col is None:
                        return np.zeros(n, dtype=np.float64)
                    return np.asarray(col, dtype=np.float64)

                self.streamagg.observe(
                    group, name,
                    ts=ts_millis, series=sids, versions=versions,
                    shards=shards, tag_col=_sa_tag, field_col=_sa_field,
                )
        finally:
            self.streamagg.ingest_exit()
        return n

    def ensure_result_measure(self, group: str) -> None:
        """Auto-register the shared _top_n_result measure for a group."""
        from banyandb_tpu.models.topn import RESULT_MEASURE, result_measure_schema

        try:
            self.registry.get_measure(group, RESULT_MEASURE)
        except KeyError:
            self.registry.create_measure(result_measure_schema(group))

    def flush(self, group: Optional[str] = None) -> list[str]:
        out = []
        for name, db in self._tsdbs.items():
            if group is None or name == group:
                out.extend(db.flush_all())
        if out:
            # first-flush hook: parts now exist on disk, so the next
            # query is the cold one — warm recorded plan kernels in the
            # background (no-op unless BYDB_PRECOMPILE; lazy import keeps
            # the engines layer from depending upward on query/)
            from banyandb_tpu.query.precompile import default_registry

            default_registry().note_flush()
        return out

    # -- query path (query.go:88 analog) -----------------------------------
    def query(
        self, req: QueryRequest, shard_ids=None, tracer=None
    ) -> QueryResult:
        """Execute; when req.trace is set, attach in-band trace spans
        (pkg/query/tracer.go analog: spans ride back in the response).

        `tracer` (obs.tracer.Tracer): caller-owned span sink — servers
        pass one so the tree also feeds the slow-query flight recorder;
        when None and req.trace is set the engine owns a local one and
        attaches its tree as res.trace["span_tree"].

        Routing decisions come off the logical plan tree
        (query/logical.py, measure_analyzer.go:70 analog): the analyzer
        is the single owner of index-mode short-circuit and aggregate-vs-
        raw selection; this method lowers the tree onto the fused
        executors."""
        from banyandb_tpu.query import logical, planner

        own_tracer = tracer is None and req.trace
        if own_tracer:
            tracer = Tracer("measure:query")
        t = tracer if tracer is not None else NOOP_TRACER

        t_start = time.perf_counter()
        group = req.groups[0]
        m = self.registry.get_measure(group, req.name)
        db = self._tsdb(group)
        with t.span("analyze"):
            plan = logical.analyze_measure(m, req)
        # Materialized-window rewrite (query/streamagg.py): an aggregate
        # whose (signature, time range, group-by) is covered by rolling
        # windows folds states instead of rescanning parts; partial
        # head/tail windows rescan ONLY the uncovered sub-ranges.
        is_agg = plan.find("GroupByAggregate") is not None
        if is_agg and not m.index_mode:
            cover = self.streamagg.plan_cover(m, req)
            if cover is not None:
                res = self._query_materialized(
                    m, req, db, plan, cover, shard_ids, tracer, t,
                    t_start, own_tracer,
                )
                if res is not None:
                    if planner.enabled():
                        planner.record_decision("materialized")
                    return res
                # coverage lost (window evicted mid-plan): full rescan
        # Cost-based scan planning (query/planner, BYDB_PLANNER): the
        # pre-gather estimate decides group-by strategy, the fused chunk
        # schedule, and whether the zone-map pre-pass is worth running.
        # All decisions are result-preserving — BYDB_PLANNER=0 restores
        # the fixed thresholds with byte-identical output.
        decision = None
        pspan = None
        if (
            is_agg
            and not m.index_mode
            and plan.leaf().kind != "IndexModeScan"
            and planner.enabled()
        ):
            with t.span("planner") as pspan:
                decision = planner.plan_scan(
                    self, db, m, req,
                    span=pspan if tracer is not None else None,
                )
        # hidden (indexed non-entity) tags resolve BEFORE the gather:
        # their per-row stored values are superseded by the latest-
        # write-wins series join (_join_hidden_tags), so block pruning
        # must never use them — a block whose stored values all fail a
        # hidden-tag predicate may still hold rows whose JOINED value
        # matches (and vice versa its rows may carry the series' newest
        # value that other blocks need)
        hidden = (
            self._hidden_index_tags(group, req.name, m)
            if not is_agg and not m.index_mode
            else set()
        )
        t_pg = time.perf_counter()  # stage metric covers ONLY part gather
        with t.span("part_gather") as gs:
            if plan.leaf().kind == "IndexModeScan":
                # Short-circuit: whole measure lives in the series index
                # (SearchWithoutSeries, measure/query.go:506,559).
                sources = self._index_sources(db, m, req, shard_ids)
            else:
                # A concurrent merge can GC a part dir after we snapshot
                # the part list; that read raises FileNotFoundError and we
                # retry against the fresh snapshot (the reference's epoch
                # contract).
                for attempt in range(3):
                    try:
                        sources = self._gather_sources(
                            db, m, req, shard_ids=shard_ids,
                            zone_prepass=(
                                decision.zone_prepass
                                if decision is not None
                                else True
                            ),
                            zone_exclude=hidden,
                        )
                        break
                    except FileNotFoundError:
                        if attempt == 2:
                            raise
            gs.tag("sources", len(sources)).tag(
                "rows", sum(int(s.ts.size) for s in sources)
            )
        t_gather = time.perf_counter()
        _H_PART_GATHER.observe((t_gather - t_pg) * 1000)
        analyzers = self._tag_analyzers(group, req.name)
        try:
            if is_agg:
                with t.span("execute") as es:
                    res = measure_exec.execute_aggregate(
                        m, req, sources,
                        dict_state=self._dict_state(group, req.name),
                        analyzers=analyzers,
                        span=es if tracer is not None else None,
                        plan_hints=decision,
                    )
                if decision is not None:
                    # est-vs-actual on the (already closed) planner span:
                    # tags serialize when the tree renders, at query end
                    if decision.actual_rows is not None and pspan is not None:
                        pspan.tag("actual_rows", decision.actual_rows)
                    planner.record_decision(decision.path)
            else:
                with t.span("execute") as es:
                    es.tag("path", "raw_rows")
                    if hidden:
                        sources = _join_hidden_tags(sources, hidden)
                    res = _raw_rows(m, req, sources, analyzers=analyzers)
        finally:
            # observed on error paths too (stream/trace/property parity:
            # per-engine latency must not go dark when queries fail)
            _H_QUERY.observe((time.perf_counter() - t_start) * 1000)
        if req.trace:
            res.trace = _trace_spans(t_start, t_gather, sources, m.index_mode)
            res.trace["plan"] = plan.explain()
            if own_tracer:
                res.trace["span_tree"] = tracer.finish()
        return res

    def _query_materialized(
        self, m, req, db, plan, cover, shard_ids, tracer, t, t_start,
        own_tracer,
    ) -> QueryResult:
        """Answer a covered aggregate from materialized rolling windows
        (query/streamagg.py): fold window states into partials, rescan
        only the uncovered head/tail ranges, then run the ordinary
        combine/finalize tail — `BYDB_STREAMAGG=0` byte-parity rides on
        the finalize path being shared."""
        analyzers = self._tag_analyzers(m.group, req.name)
        with t.span("streamagg") as ss:
            span = ss if tracer is not None else None
            parts = self.streamagg.answer(
                cover,
                shard_ids=shard_ids,
                rescan=lambda b, e: self._rescan_partials(
                    db, m, req, b, e, shard_ids, analyzers, span
                ),
                span=span,
            )
            if parts is None:
                return None  # coverage lost: caller runs the rescan
            try:
                res = measure_exec.finalize_partials(
                    m, req, parts, span=span
                )
            finally:
                _H_QUERY.observe(
                    (time.perf_counter() - t_start) * 1000
                )
        if req.trace:
            from banyandb_tpu.storage.cache import device_cache, global_cache

            res.trace = {
                "spans": [
                    {
                        "name": "streamagg",
                        "duration_ms": round(
                            (time.perf_counter() - t_start) * 1000, 3
                        ),
                        "coverage": cover.kind,
                    }
                ],
                "serving_cache": global_cache().stats(),
                "device_cache": device_cache().stats(),
                "total_ms": round(
                    (time.perf_counter() - t_start) * 1000, 3
                ),
                "plan": plan.explain(),
            }
            if own_tracer:
                res.trace["span_tree"] = tracer.finish()
        return res

    def _rescan_partials(
        self, db, m, req, begin, end, shard_ids, analyzers, span
    ):
        """Bounded rescan of one uncovered sub-range through the normal
        gather+compute path (block selection prunes to the range; the
        merged-part retry lives in gather_query_sources)."""
        import dataclasses as _dc

        from banyandb_tpu.api.model import TimeRange as _TR

        sub = _dc.replace(req, time_range=_TR(begin, end))
        sources = self.gather_query_sources(
            sub, shard_ids=shard_ids, serial=True
        )
        return measure_exec.compute_partials(
            m, sub, sources,
            dict_state=self._dict_state(m.group, req.name),
            analyzers=analyzers,
            span=span,
        )

    def query_partials(
        self,
        req: QueryRequest,
        shard_ids=None,
        hist_range=None,
        tracer=None,
    ):
        """Data-node map phase: partial aggregates over (a subset of) local
        shards (banyand/query processor + agg_return_partial analog).

        `tracer`: the data node's own span sink — its finished tree rides
        the RPC reply back to the liaison for the cluster-wide merge."""
        from banyandb_tpu.query import planner

        t = tracer if tracer is not None else NOOP_TRACER
        t0 = time.perf_counter()
        group = req.groups[0]
        m = self.registry.get_measure(group, req.name)
        # Materialized-window map phase: a covered node folds its local
        # shard subset's window states into one Partials (merged across
        # shards/nodes by the liaison exactly like scan partials).  The
        # percentile second round pins hist_range and must rescan —
        # windows hold no histograms.
        if hist_range is None and not m.index_mode:
            cover = self.streamagg.plan_cover(m, req)
            if cover is not None:
                analyzers = self._tag_analyzers(group, req.name)
                with t.span("streamagg") as ss:
                    span = ss if tracer is not None else None
                    parts = self.streamagg.answer(
                        cover,
                        shard_ids=shard_ids,
                        rescan=lambda b, e: self._rescan_partials(
                            self._tsdb(group), m, req, b, e,
                            shard_ids, analyzers, span,
                        ),
                        span=span,
                    )
                    if parts is not None:
                        try:
                            out = (
                                parts[0]
                                if len(parts) == 1
                                else measure_exec.combine_partials(parts)
                            )
                        finally:
                            _H_QUERY.observe(
                                (time.perf_counter() - t0) * 1000
                            )
                        if planner.enabled():
                            planner.record_decision("materialized")
                        return out
                # coverage lost mid-plan: fall through to the rescan
        # the data-node side of cost-based planning: same estimate, same
        # result-preserving hints, per-node planner span in the graft
        decision = None
        pspan = None
        if not m.index_mode and planner.enabled():
            with t.span("planner") as pspan:
                decision = planner.plan_scan(
                    self, self._tsdb(group), m, req,
                    span=pspan if tracer is not None else None,
                )
        t_pg = time.perf_counter()  # stage metric covers ONLY part gather
        with t.span("part_gather") as gs:
            sources = self.gather_query_sources(
                req, shard_ids=shard_ids,
                zone_prepass=(
                    decision.zone_prepass if decision is not None else True
                ),
            )
            gs.tag("sources", len(sources)).tag(
                "rows", sum(int(s.ts.size) for s in sources)
            ).tag("shards", sorted(shard_ids) if shard_ids else "all")
        _H_PART_GATHER.observe((time.perf_counter() - t_pg) * 1000)
        analyzers = self._tag_analyzers(group, req.name)
        try:
            with t.span("compute_partials") as cs:
                span = cs if tracer is not None else None
                if m.index_mode:
                    out = measure_exec.compute_partials(
                        m, req, sources, hist_range=hist_range,
                        analyzers=analyzers, span=span,
                    )
                else:
                    out = measure_exec.compute_partials(
                        m,
                        req,
                        sources,
                        hist_range=hist_range,
                        dict_state=self._dict_state(group, req.name),
                        analyzers=analyzers,
                        span=span,
                        plan_hints=decision,
                    )
            if decision is not None:
                if decision.actual_rows is not None and pspan is not None:
                    pspan.tag("actual_rows", decision.actual_rows)
                planner.record_decision(decision.path)
        finally:
            _H_QUERY.observe((time.perf_counter() - t0) * 1000)
        return out

    def _hidden_index_tags(self, group: str, name: str, m: Measure) -> set:
        """Indexed NON-ENTITY tags (the reference's 'hidden' tags): the
        reference stores them as series-level metadata docs where the
        latest-ts write wins and joins them onto every row of the
        series (write_standalone.go metadataDocs).  This engine stores
        tags per row, so the raw retrieval path applies the same
        latest-write-wins join explicitly (_join_hidden_tags)."""
        out: set = set()
        try:
            rules = {r.name: r for r in self.registry.list_index_rules(group)}
            for b in self.registry.list_index_rule_bindings(group):
                if b.subject_name != name:
                    continue
                for rn in b.rules:
                    r = rules.get(rn)
                    if r is not None:
                        out.update(r.tags)
        except Exception:  # noqa: BLE001 — registries without bindings
            return set()
        return out - set(m.entity.tag_names)

    def _tag_analyzers(self, group: str, name: str) -> dict[str, str]:
        """tag -> analyzer from index rules BOUND to this measure (the
        MATCH op's mandatory context, ref inverted/query.go:371).  Rules
        without an analyzer map to 'keyword' (exact-term match)."""
        out: dict[str, str] = {}
        try:
            rules = {r.name: r for r in self.registry.list_index_rules(group)}
            for b in self.registry.list_index_rule_bindings(group):
                if b.subject_name != name:
                    continue
                for rn in b.rules:
                    r = rules.get(rn)
                    if r is None:
                        continue
                    for t in r.tags:
                        out[t] = r.analyzer or "keyword"
        except Exception:  # noqa: BLE001 — registries without bindings
            pass
        return out

    def gather_query_sources(
        self, req, shard_ids=None, serial=False, zone_prepass=True
    ):
        """Source selection for the map phase, shared by the host partial
        path, the mesh fast path (parallel/mesh_query.py) and the
        streamagg bounded rescans (`serial=True` skips the part
        prefetch thread): same segment/series pruning, same retry on
        concurrently-merged parts.  ``zone_prepass=False`` (planner
        decision: estimated selectivity ~1) skips the zone-map block
        pre-pass — identical rows, no per-part predicate lowering."""
        group = req.groups[0]
        m = self.registry.get_measure(group, req.name)
        db = self._tsdb(group)
        if m.index_mode:
            return self._index_sources(db, m, req, shard_ids)
        for attempt in range(3):
            try:
                return self._gather_sources(
                    db, m, req, shard_ids=shard_ids, serial=serial,
                    zone_prepass=zone_prepass,
                )
            except FileNotFoundError:
                if attempt == 2:
                    raise

    def _index_sources(self, db, m, req, shard_ids):
        """Index-mode sources, optionally restricted to a shard subset
        (distributed scatter: shard = seriesID % shard_num)."""
        sources = _index_mode_sources(db, m, req)
        if shard_ids is None:
            return sources
        shard_num = self.registry.get_group(m.group).resource_opts.shard_num
        out = []
        for src in sources:
            mask = np.isin(src.series % shard_num, list(shard_ids))
            if not mask.any():
                continue
            out.append(
                ColumnData(
                    ts=src.ts[mask],
                    series=src.series[mask],
                    version=src.version[mask],
                    tags={t: c[mask] for t, c in src.tags.items()},
                    fields={f: v[mask] for f, v in src.fields.items()},
                    dicts=src.dicts,
                )
            )
        return out

    def _gather_sources(
        self,
        db: TSDB,
        m: Measure,
        req: QueryRequest,
        shard_ids=None,
        serial: bool = False,
        zone_prepass: bool = True,
        zone_exclude: set = frozenset(),
    ) -> list[ColumnData]:
        """Collect per-source decode thunks (metadata-only work: segment
        selection, series-index pruning, block selection), then evaluate
        them through the prefetchable chunk stream — part *k+1* decodes
        on the prefetch thread while part *k*'s rows series-filter and
        append on this one.  Thunk order is the serial iteration order,
        so the concatenation (and every downstream dedup/accumulation)
        is byte-identical to the strict-serial path (BYDB_PIPELINE=0)."""
        from banyandb_tpu.storage.chunk_stream import prefetched

        from banyandb_tpu.storage import encoded as enc_mod

        read_ops = []
        tag_names = _tag_col_names(m)  # incl. '@f:' raw-field columns
        field_names = [f.name for f in _numeric_fields(m)]
        entity_conds = _entity_eq_conditions(m, req)
        narrow = enc_mod.device_decode_enabled()
        # Zone-map skipping (ROADMAP item 3 / arXiv 2104.12815):
        # conjunctive eq/in tag predicates prune at BLOCK granularity
        # against the per-block code zone maps written at flush/merge —
        # a skipped block is never read, let alone decoded.
        # ``zone_prepass=False`` is the planner's ~1-selectivity call:
        # nothing would skip, so the per-part dict lowering + per-block
        # interval checks are pure overhead (results identical — zone
        # skipping only ever removes reads of non-matching blocks)
        zone_conds = (
            _conjunctive_eq_conditions(req)
            if (enc_mod.zone_skip_enabled() and zone_prepass)
            else []
        )
        if zone_exclude:
            # hidden-tag predicates evaluate against the JOINED series
            # value, never the stored per-row one — block pruning on
            # them would drop rows the join makes match
            zone_conds = [
                (name, vals)
                for name, vals in zone_conds
                if name not in zone_exclude
            ]
        for seg in db.select_segments(
            req.time_range.begin_millis, req.time_range.end_millis
        ):
            # Series pruning: entity-tag equality conditions resolve to a
            # candidate seriesID set via the segment's series index
            # (searchSeriesList, measure/query.go:314); part blocks outside
            # the candidate series range are skipped.
            series_ids = None
            if entity_conds and len(seg.series_index):
                # An empty index means "no information" (legacy parts, lost
                # sidx file) — skip pruning rather than prune everything.
                from banyandb_tpu.index.inverted import And, Or, TermQuery

                clauses = [TermQuery("@measure", m.name.encode())]
                for name, values in entity_conds:
                    terms = tuple(TermQuery(name, v) for v in values)
                    clauses.append(terms[0] if len(terms) == 1 else Or(terms))
                series_ids = np.sort(
                    seg.series_index.search(And(tuple(clauses)))
                )
            # Row-level series filter companion to block pruning: blocks
            # are 8192 rows, so a one-series query over small (young)
            # parts still decodes ~everything — dropping non-candidate
            # ROWS here shrinks the whole downstream pipeline (remap,
            # dedup lexsort, device transfer, kernel) by the selectivity
            # factor.  Hash digest keys the derived source for the
            # serving cache (same parts + same series set => same rows).
            sfilter_key = None
            if series_ids is not None:
                sfilter_key = hash(series_ids.tobytes())

            # evaluation is DEFERRED to the prefetch stream below, and
            # series_ids/sfilter_key are reassigned per segment — bind
            # this segment's values as defaults, not closure cells
            def _series_rows(
                src: ColumnData, ckey, sids=series_ids, skey=sfilter_key
            ) -> Optional[ColumnData]:
                if sids is None:
                    return src
                keep = np.zeros(src.series.shape[0], dtype=bool)
                if sids.size:
                    pos = np.searchsorted(sids, src.series)
                    pos[pos >= sids.size] = 0
                    keep = sids[pos] == src.series
                if not keep.any():
                    return None
                if keep.all():
                    return src
                return ColumnData(
                    ts=src.ts[keep],
                    series=src.series[keep],
                    version=src.version[keep],
                    tags={t: c[keep] for t, c in src.tags.items()},
                    fields={f: v[keep] for f, v in src.fields.items()},
                    dicts=src.dicts,
                    cache_key=(
                        (*ckey, "sfilter", skey) if ckey else None
                    ),
                )

            def _read_part(part, blocks, filt):
                src = part.read(
                    blocks,
                    tags=[t for t in tag_names if t in part.meta["tags"]],
                    fields=[f for f in field_names if f in part.meta["fields"]],
                    narrow_codes=narrow,
                )
                return filt(src, src.cache_key)

            for shard_idx, shard in enumerate(seg.shards):
                if shard_ids is not None and shard_idx not in shard_ids:
                    continue
                # live memtable + any in-flight flush snapshot (rows
                # between flush's two commit points stay visible;
                # version dedup collapses a racing double-expose)
                hot_cols = shard.hot_columns(m.name)
                for mem_cols in hot_cols:
                    read_ops.append(
                        lambda mc=mem_cols, filt=_series_rows: filt(
                            mc, mc.cache_key
                        )
                    )
                shard_parts = [
                    p for p in shard.parts if p.meta.get("measure") == m.name
                ]
                # Zone skipping is dedup-safety-gated: a block whose
                # zones exclude every predicate value may still hold the
                # NEWEST version of a (series, ts) row whose older,
                # matching copy lives in a kept source — dropping it
                # would resurrect the stale row.  So first collect every
                # kept source's key interval across the whole shard
                # (version dedup is scoped to a shard: series hash to
                # exactly one, segments partition time), then let
                # select_blocks drop only overlap-free marked blocks.
                plans: list = []  # (part, candidate blocks, marked set)
                kept_intervals: list = []
                if zone_conds and shard_parts:
                    from banyandb_tpu.storage.part import KeyInterval

                    for mem_cols in hot_cols:
                        kept_intervals.append(
                            KeyInterval.conservative(
                                int(mem_cols.series.min()),
                                int(mem_cols.series.max()),
                                int(mem_cols.ts.min()),
                                int(mem_cols.ts.max()),
                            )
                        )
                for part in shard_parts:
                    cands = part.select_blocks(
                        req.time_range.begin_millis,
                        req.time_range.end_millis,
                        series_ids=series_ids,
                    )
                    marked: set = set()
                    if zone_conds:
                        marked = part.zone_marked(
                            cands, _part_zone_preds(part, zone_conds)
                        )
                        kept_intervals.extend(
                            part.block_interval(i)
                            for i in cands
                            if i not in marked
                        )
                    plans.append((part, cands, marked))
                for part, cands, marked in plans:
                    blocks = (
                        part.finalize_zone_skip(cands, marked, kept_intervals)
                        if marked
                        else cands
                    )
                    if blocks:
                        read_ops.append(
                            lambda p=part, b=blocks, filt=_series_rows,
                            rd=_read_part: rd(p, b, filt)
                        )
        # a mid-stream decode error (e.g. a part merged away under us)
        # re-raises here exactly as the serial loop would — query()'s
        # FileNotFoundError retry still applies.  `serial` (bounded
        # streamagg head/tail rescans) skips the prefetch thread
        # entirely: results are byte-identical by the pipeline contract,
        # and at a few blocks of work the thread handoffs cost more
        # than the overlap buys — especially under write-saturated GIL
        return [
            src
            for src in prefetched(
                read_ops,
                name="bydb-part-prefetch",
                enabled=False if serial else None,
            )
            if src is not None
        ]


def _tag_to_bytes(value, tag_type: TagType) -> bytes:
    if value is None:
        return b""
    return hashing.entity_bytes(value)


class _MultiMeasureMemtable:
    """Shard memtable keyed by measure name (one MemTable each).

    The reference keeps one tstable per (group, shard) with rows of all
    measures distinguished by series; here hot rows stay per-measure so a
    flush produces one part per measure with that measure's columns.
    """

    def __init__(self):
        self._tables: dict[str, MemTable] = {}

    def __len__(self) -> int:
        return sum(len(t) for t in self._tables.values())

    def append_measure(
        self, measure, tag_names, field_names, ts, sid, version, tags, fields
    ) -> None:
        tbl = self._tables.get(measure)
        if tbl is None:
            tbl = self._tables[measure] = MemTable(tag_names, field_names)
        tbl.append(ts, sid, version, tags, fields)

    def append_measure_bulk(
        self, measure, tag_names, field_names, ts, sids, versions, tags, fields
    ) -> None:
        tbl = self._tables.get(measure)
        if tbl is None:
            tbl = self._tables[measure] = MemTable(tag_names, field_names)
        tbl.append_bulk(ts, sids, versions, tags, fields)

    def drain(self) -> list:
        return [
            (name, tbl.snapshot_columns(), {"measure": name})
            for name, tbl in self._tables.items()
        ]

    def columns_for(self, measure: str) -> Optional[ColumnData]:
        tbl = self._tables.get(measure)
        return tbl.snapshot_columns() if tbl else None

    def per_measure(self) -> dict[str, MemTable]:
        return dict(self._tables)


def _join_hidden_tags(
    sources: list[ColumnData], hidden: set
) -> list[ColumnData]:
    """Latest-write-wins join for hidden (indexed non-entity) tags:
    compute each series' newest value per hidden tag across the
    gathered sources — (ts, version)-max, the write path's own
    ordering — and rewrite every row of that series to carry it, so
    filters AND projections see the joined value exactly like the
    reference's series-metadata docs.  Scoped to the gathered (time-
    pruned) sources: a rewrite outside the queried range is invisible
    here, which matches block pruning's visibility everywhere else."""
    import dataclasses as _dc

    latest: dict[str, dict[int, tuple]] = {t: {} for t in hidden}
    for src in sources:
        for t in hidden:
            col = src.tags.get(t)
            if col is None:
                continue
            d = src.dicts[t]
            for i in range(src.ts.shape[0]):
                sid = int(src.series[i])
                stamp = (int(src.ts[i]), int(src.version[i]))
                cur = latest[t].get(sid)
                if cur is None or stamp > cur[0]:
                    latest[t][sid] = (stamp, d[int(col[i])])
    if not any(latest[t] for t in hidden):
        return sources
    out = []
    for src in sources:
        tags = dict(src.tags)
        dicts = dict(src.dicts)
        changed = False
        for t in hidden:
            by_sid = latest[t]
            if not by_sid and t not in tags:
                continue
            vals = sorted({v for _, v in by_sid.values()} | {b""})
            vidx = {v: i for i, v in enumerate(vals)}
            codes = np.fromiter(
                (
                    vidx[by_sid[int(s)][1]] if int(s) in by_sid else 0
                    for s in src.series
                ),
                dtype=np.int32,
                count=src.series.shape[0],
            )
            tags[t] = codes
            dicts[t] = vals
            changed = True
        if not changed:
            out.append(src)
            continue
        out.append(
            _dc.replace(src, tags=tags, dicts=dicts, cache_key=None)
        )
    return out


def _raw_rows(
    m: Measure,
    req: QueryRequest,
    sources: list[ColumnData],
    analyzers: Optional[dict] = None,
) -> QueryResult:
    """Projection/limit query without aggregation: host-side assembly.

    The aggregate path is the TPU hot loop; raw row retrieval is IO-bound
    and stays on host (the reference's row iterator, query.go:594).
    """
    res = QueryResult()
    conds, _expr = measure_exec._lower_criteria(req.criteria)
    for c in conds:
        m.tag(c.name)  # schema validation: typo'd tag -> KeyError, matching
        # the aggregate path instead of silently returning unfiltered rows
    rows: list[tuple] = []
    for src in sources:
        if src.ts.size == 0:
            continue
        mask = qfilter.criteria_mask(
            src, req.criteria, req.time_range.begin_millis,
            req.time_range.end_millis, analyzers=analyzers,
            tag_types={t.name: t.type for t in m.tags},
        )
        raw_types = {
            _RAW_FIELD_PREFIX + f.name: f.type for f in _raw_fields(m)
        }
        for i in np.nonzero(mask)[0]:
            tags = {}
            fields = {}
            for t in src.tags:
                raw = src.dicts[t][src.tags[t][i]]
                ftype = raw_types.get(t)
                if ftype is not None:
                    # reserved '@f:' column: a stored raw field value
                    fields[t[len(_RAW_FIELD_PREFIX):]] = (
                        raw
                        if ftype == FieldType.DATA_BINARY
                        else raw.decode(errors="replace")
                    )
                else:
                    tags[t] = qfilter.decode_tag_value(raw, m.tag(t).type)
            for f in src.fields:
                fields[f] = float(src.fields[f][i])
            rows.append(
                (
                    int(src.ts[i]),
                    int(src.version[i]),
                    tags,
                    fields,
                    int(src.series[i]),
                )
            )

    # Version dedup then ordering: by an indexed tag's value when
    # order_by_tag is set (order-by-index analog), else by ts.
    # Index-mode measures dedup PER SERIES across segments (docs are
    # series-keyed upserts; an older segment may still hold a replaced
    # doc) — row measures dedup per (series, ts): a rewrite of the same
    # series at the same timestamp REPLACES the row even when non-entity
    # tags changed (want/duplicated_part.yaml keeps only the last write)
    best: dict[tuple, tuple] = {}
    for row in rows:
        key = (row[4],) if m.index_mode else (row[4], row[0])
        if key not in best or best[key][1] < row[1]:
            best[key] = row
    if req.top:
        # row-level top-N (measure_top.go): rank raw points by the
        # field's value, emit in ranking order
        fname = req.top.field_name
        desc = req.top.field_value_sort != "asc"
        ranked = sorted(
            (r for r in best.values() if fname in r[3]),
            key=lambda r: r[3][fname],
            reverse=desc,
        )
        ordered = ranked[: req.top.number]
    elif req.order_by_tag:
        have = [r for r in best.values() if r[2].get(req.order_by_tag) is not None]
        miss = [r for r in best.values() if r[2].get(req.order_by_tag) is None]
        have.sort(
            key=lambda r: r[2][req.order_by_tag],
            reverse=(req.order_by_dir == "desc"),
        )
        ordered = have + miss  # missing-tag rows last under either order
    else:
        # default (no order_by) is timestamp ASC — pinned by the
        # reference's limit/offset golden (want/limit.yaml: offset 3
        # lands on the 4th-written row)
        ordered = sorted(
            best.values(), key=lambda r: r[0], reverse=(req.order_by_ts == "desc")
        )
    off = req.offset or 0
    for ts, _ver, tags, fields, _sid in ordered[off : off + (req.limit or 100)]:
        res.data_points.append({"timestamp": ts, "tags": tags, "fields": fields})
    return res


def _trace_spans(t_start, t_gather, sources, index_mode: bool) -> dict:
    """In-band query trace (pkg/query/tracer.go Span analog)."""
    from banyandb_tpu.storage.cache import device_cache, global_cache

    t_end = time.perf_counter()
    rows = sum(int(s.ts.size) for s in sources)
    return {
        "spans": [
            {
                "name": "gather_sources",
                "duration_ms": round((t_gather - t_start) * 1000, 3),
                "sources": len(sources),
                "rows": rows,
                "index_mode": index_mode,
            },
            {
                "name": "execute",
                "duration_ms": round((t_end - t_gather) * 1000, 3),
            },
        ],
        "serving_cache": global_cache().stats(),
        "device_cache": device_cache().stats(),
        "total_ms": round((t_end - t_start) * 1000, 3),
    }


# -- series pruning helpers -------------------------------------------------


def _entity_eq_conditions(m: Measure, req: QueryRequest):
    """[(entity_tag, [candidate byte values])] from AND'ed eq/in conditions."""
    try:
        conds = measure_exec._collect_conditions(req.criteria)
    except NotImplementedError:
        return []
    entity = set(m.entity.tag_names)
    out = []
    for c in conds:
        if c.name not in entity:
            continue
        if c.op == "eq":
            out.append((c.name, [measure_exec._tag_value_bytes(c.value)]))
        elif c.op == "in":
            out.append(
                (c.name, [measure_exec._tag_value_bytes(v) for v in c.value])
            )
    return out


# Moved into the query layer (the cost-based planner estimates from the
# same lowering); lazily re-exported here for the gather path + existing
# tests (function-local import per the layering policy — models sits
# BELOW query in the layer map).


def _conjunctive_eq_conditions(req: QueryRequest):
    from banyandb_tpu.query.planner import conjunctive_eq_conditions

    return conjunctive_eq_conditions(req)


def _part_zone_preds(part, zone_conds) -> list:
    from banyandb_tpu.query.planner import part_zone_preds

    return part_zone_preds(part, zone_conds)


# -- index-mode measures (doc-per-point in the series index) ---------------


def _series_doc_id(measure: str, sid: int) -> int:
    """Index-mode doc identity = the SERIES (ref DocID: uint64(series.ID),
    write_standalone.go:89): a new point for the same series REPLACES the
    doc — index-mode measures hold each series' latest state, not a
    point history."""
    import hashlib

    h = hashlib.blake2b(
        measure.encode() + b"\x00" + sid.to_bytes(8, "little", signed=True),
        digest_size=8,
    )
    return int.from_bytes(h.digest(), "little", signed=True)


def _index_mode_write(seg, m: Measure, sid, ts_millis, version, tag_bytes, field_vals):
    from banyandb_tpu.index.inverted import Doc

    idx = seg.series_index._idx
    payload = np.asarray(
        [field_vals.get(f.name, 0.0) for f in m.fields], dtype=np.float64
    ).tobytes()
    keywords = dict(tag_bytes)
    keywords["@measure"] = m.name.encode()
    # check-and-insert under the index lock (dedup-by-version contract);
    # series-keyed doc id => a newer point REPLACES the series' doc
    idx.insert_if_newer(
        Doc(
            doc_id=_series_doc_id(m.name, sid),
            keywords=keywords,
            numerics={"@ts": ts_millis, "@version": version, "@series": sid},
            payload=payload,
        )
    )


def _index_mode_sources(db: TSDB, m: Measure, req: QueryRequest) -> list[ColumnData]:
    """Build scan sources straight from index docs (SearchWithoutSeries) —
    the same device executor then runs over them unchanged.

    Segments wholly past the group's TTL are excluded at QUERY time (the
    retention sweep may not have run yet; ref 'excludes data expired
    beyond TTL' golden): data past retention must never surface."""
    from banyandb_tpu.index.inverted import And, RangeQuery, TermQuery

    ttl_floor = None
    ttl = getattr(db.opts, "ttl", None)
    if ttl is not None and ttl.millis:
        ttl_floor = int(time.time() * 1000) - ttl.millis
    sources = []
    for seg in db.select_segments(
        req.time_range.begin_millis, req.time_range.end_millis
    ):
        if ttl_floor is not None and seg.end <= ttl_floor:
            continue  # fully expired segment
        idx = seg.series_index._idx
        ids = idx.search(
            And(
                (
                    TermQuery("@measure", m.name.encode()),
                    RangeQuery(
                        "@ts",
                        req.time_range.begin_millis,
                        req.time_range.end_millis - 1,
                    ),
                )
            )
        )
        docs = idx.get_many(ids.tolist())
        if not docs:
            continue
        n = len(docs)
        ts = np.asarray([d.numerics["@ts"] for d in docs], dtype=np.int64)
        series = np.asarray([d.numerics["@series"] for d in docs], dtype=np.int64)
        version = np.asarray(
            [d.numerics.get("@version", 0) for d in docs], dtype=np.int64
        )
        tags: dict[str, np.ndarray] = {}
        dicts: dict[str, list[bytes]] = {}
        for tname in _tag_col_names(m):
            vocab: dict[bytes, int] = {}
            codes = np.empty(n, dtype=np.int32)
            for i, d in enumerate(docs):
                v = d.keywords.get(tname, b"")
                codes[i] = vocab.setdefault(v, len(vocab))
            tags[tname] = codes
            dicts[tname] = [
                v for v, _ in sorted(vocab.items(), key=lambda kv: kv[1])
            ]
        fields: dict[str, np.ndarray] = {}
        num_fields = _numeric_fields(m)
        raw = np.frombuffer(b"".join(d.payload for d in docs), dtype=np.float64)
        raw = (
            raw.reshape(n, len(num_fields))
            if num_fields
            else raw.reshape(n, 0)
        )
        for j, f in enumerate(num_fields):
            fields[f.name] = raw[:, j].copy()
        sources.append(
            ColumnData(
                ts=ts, series=series, version=version,
                tags=tags, fields=fields, dicts=dicts,
            )
        )
    return sources
