"""TopN pre-aggregation: ingest-time streaming top/bottom-N.

Analog of banyand/measure/topn.go (topNProcessorManager :94, streaming
processor :340): measure writes flow through per-rule tumbling time
windows; on window close the per-group aggregates are ranked and the
winners land as data points in the shared ``_top_n_result`` measure,
which the normal (TPU) query path then serves; TopN queries re-rank
across windows (topn_post_processor.go analog).

Windows are tiny (counters_number bounded), so window accumulation is a
dict of float sums host-side; the heavy path — querying the result
measure — rides the standard device executor.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

from banyandb_tpu.api.model import (
    DataPointValue,
    QueryRequest,
    TimeRange,
    WriteRequest,
)
from banyandb_tpu.api.schema import (
    Entity,
    FieldSpec,
    FieldType,
    Measure,
    TagSpec,
    TagType,
    TopNAggregation,
)

if TYPE_CHECKING:  # pragma: no cover
    from banyandb_tpu.models.measure import MeasureEngine

RESULT_MEASURE = "_top_n_result"
_SEP = "\x01"


def rule_key_tags(rule: TopNAggregation, m: Measure) -> tuple[str, ...]:
    """Counter-key dimensions: the source measure's entity tags plus any
    rule group-by tags beyond them.  Results DISPLAY the entity prefix;
    the extra dims exist so query conditions (e.g. http.uri = null) can
    filter counters (ref null_group/eq goldens)."""
    ent = tuple(m.entity.tag_names)
    extras = tuple(
        t for t in rule.group_by_tag_names if t not in ent
    )
    return ent + extras


def _key_str(v) -> str:
    """Canonical STRING domain for counter keys, criteria literals and
    query conditions alike: None (absent/null) -> "", bytes decode,
    everything else str() — one domain so the row path, the columnar
    path and query-time filters can never disagree on a value."""
    if v is None:
        return ""
    if isinstance(v, bytes):
        return v.decode(errors="replace")
    return str(v)


def _canon_cond_value(v):
    if isinstance(v, (list, tuple)):
        return tuple(_key_str(x) for x in v)
    return _key_str(v)


def _rule_criteria(rule: TopNAggregation):
    """Parsed ingest-time Criteria for the rule (None = no filter), with
    condition literals canonicalized into the string key domain."""
    if not rule.criteria:
        return None
    from google.protobuf import json_format

    from banyandb_tpu.api import pb, wire
    from banyandb_tpu.api.model import Condition as _C
    from banyandb_tpu.api.model import LogicalExpression as _LE

    crit = pb.model_query_pb2.Criteria()
    json_format.ParseDict(rule.criteria, crit)

    def canon(node):
        if node is None:
            return None
        if isinstance(node, _C):
            return _C(node.name, node.op, _canon_cond_value(node.value))
        assert isinstance(node, _LE)
        return _LE(node.op, canon(node.left), canon(node.right))

    return canon(wire.criteria_to_internal(crit))


def _crit_tag_names(crit) -> set:
    """Tag names referenced by a (canonicalized) criteria tree."""
    from banyandb_tpu.api.model import Condition as _C

    out: set = set()

    def walk(node):
        if node is None:
            return
        if isinstance(node, _C):
            out.add(node.name)
        else:
            walk(node.left)
            walk(node.right)

    walk(crit)
    return out


def _row_matches(tags: dict, crit) -> bool:
    """Evaluate a canonicalized Criteria tree over string-domain tag
    values (tags values already through _key_str)."""
    from banyandb_tpu.api.model import Condition as _C
    from banyandb_tpu.api.model import LogicalExpression as _LE

    if crit is None:
        return True
    if isinstance(crit, _C):
        v = tags.get(crit.name, "")
        if crit.op == "eq":
            return v == crit.value
        if crit.op == "ne":
            return v != crit.value
        if crit.op == "in":
            return v in crit.value
        if crit.op == "not_in":
            return v not in crit.value
        raise ValueError(f"topn rule criteria op {crit.op!r} not supported")
    assert isinstance(crit, _LE)
    left = _row_matches(tags, crit.left)
    right = _row_matches(tags, crit.right)
    return (left and right) if crit.op == "and" else (left or right)


def result_measure_schema(group: str) -> Measure:
    """The shared result measure (storage-and-format.md §3.5 analog)."""
    return Measure(
        group=group,
        name=RESULT_MEASURE,
        tags=(
            TagSpec("topn_name", TagType.STRING),
            TagSpec("sort", TagType.STRING),
            TagSpec("entity", TagType.STRING),
        ),
        fields=(FieldSpec("value", FieldType.FLOAT),),
        entity=Entity(("topn_name", "sort", "entity")),
    )


_VERSION_ROWS_CAP_DEFAULT = 1 << 18


def _version_rows_cap() -> int:
    """Per-window bound on the (series, ts) -> last-version tracking
    table (version-merge exactness, see _Window.rows).  0 disables."""
    from banyandb_tpu.utils.envflag import env_int

    return env_int("BYDB_TOPN_VERSION_ROWS", _VERSION_ROWS_CAP_DEFAULT)


@dataclass
class _Window:
    start: int
    sums: dict  # entity tuple -> [sum, count]
    dirty: bool = True  # has un-emitted accumulation
    # (series, ts) -> (version, entity tuple, value): last-version
    # tracking so a REWRITE of the same (series, ts) REPLACES its
    # earlier contribution instead of adding (the reference
    # version-merges rows before feeding counters).  Bounded by
    # BYDB_TOPN_VERSION_ROWS per window; past the cap the table drops
    # (rows=None) and accumulation degrades to additive — exactness
    # for the dashboard-scale windows goldens exercise, bounded memory
    # under firehose ingest.
    rows: "Optional[dict]" = None


class TopNProcessorManager:
    """Per-engine manager: routes measure writes into rule windows.

    Window lifecycle follows the reference's streaming processor: a
    window whose close time the watermark passed EMITS its ranked
    counters, but its state is KEPT so late rows keep accumulating and
    re-emit the window with a higher version — the result measure's
    (series, window-start) dedup replaces the earlier emission.  Memory
    is bounded per rule by lru_size windows (TopNAggregation.lru_size):
    the oldest window is finally emitted and evicted when the bound is
    exceeded — only data older than the eviction horizon is dropped."""

    def __init__(
        self,
        engine: "MeasureEngine",
        *,
        window_millis: int = 60_000,
        lateness_millis: int = 0,
    ):
        self.engine = engine
        self.window_millis = window_millis
        self.lateness_millis = lateness_millis
        # (group, rule name) -> {window_start -> _Window}
        self._windows: dict[tuple, dict[int, _Window]] = defaultdict(dict)
        self._watermark: dict[tuple, int] = {}
        self._emit_seq = 0
        # parsed rule-criteria cache: (group, rule) -> (criteria_dict, tree)
        self._crit_cache: dict[tuple, tuple] = {}
        # One manager serves every write thread of the engine (gRPC pool
        # workers, the bus executor, bulk columnar ingest): window sums
        # are read-modify-write and _flush_closed iterates _windows, so
        # ALL accumulation state is guarded by one reentrant lock
        # (reentrant: flush_all_windows and observe share _emit).
        self._obs_lock = threading.RLock()
        # ranked emissions queue here UNDER the lock and are written to
        # the result measure AFTER it is released (_drain_emits): holding
        # _obs_lock across engine.write would nest it over the whole
        # storage/registry lock family for no benefit — result-measure
        # (series, window) version dedup makes drain order irrelevant
        self._pending_emits: list[tuple[str, tuple]] = []
        # read ONCE: _accumulate runs per ingested row under _obs_lock —
        # an env parse there would be pure hot-loop overhead
        self._version_rows_cap = _version_rows_cap()

    def _cached_criteria(self, key: tuple, rule: TopNAggregation):
        hit = self._crit_cache.get(key)
        if hit is not None and hit[0] == rule.criteria:
            return hit[1]
        parsed = _rule_criteria(rule)
        self._crit_cache[key] = (rule.criteria, parsed)
        return parsed

    def _accumulate(
        self, win: _Window, rule: TopNAggregation, ent: tuple,
        value: float, sid, ts_millis: int, version,
    ) -> bool:
        """Version-merged window accumulation: a REWRITE of the same
        (series, ts) with a higher version REPLACES its earlier
        contribution (the reference version-merges rows before feeding
        counters); an older/equal version loses, matching the storage
        plane's max-version dedup.  The superseded contribution is
        retracted even when the NEW entity cannot claim a counter slot
        (bounded counters) — the dead version must never keep ranking;
        the row record then carries ent=None so a later rewrite has
        nothing further to retract.  Tracking is per-window bounded
        (BYDB_TOPN_VERSION_ROWS) — past the cap the table drops and
        accumulation degrades to additive.  -> True when window state
        changed."""
        rkey = prev = None
        if sid is not None and win.rows is not None:
            rkey = (sid, ts_millis)
            prev = win.rows.get(rkey)
            if (
                prev is not None
                and version is not None
                and version <= prev[0]
            ):
                return False  # stale rewrite loses
        if prev is not None and prev[1] is not None:
            pacc = win.sums.get(prev[1])
            if pacc is not None:
                # retract the superseded version's contribution (the
                # acc may reach count 0: _emit skips empty counters)
                pacc[0] -= prev[2]
                pacc[1] -= 1
        acc = win.sums.get(ent)
        if acc is None:
            if len(win.sums) >= rule.counters_number:
                # bounded counters (heap-capacity analog): the new
                # version is uncounted, but the retraction above stands
                if rkey is not None:
                    win.rows[rkey] = (version or 0, None, 0.0)
                return prev is not None
            acc = win.sums[ent] = [0.0, 0]
        acc[0] += value
        acc[1] += 1
        if rkey is not None:
            win.rows[rkey] = (version or 0, ent, value)
            if len(win.rows) > self._version_rows_cap:
                win.rows = None  # cap: additive from here on
        return True

    def _new_window(self, start: int) -> _Window:
        return _Window(
            start, {}, rows={} if self._version_rows_cap > 0 else None
        )

    def observe(
        self, m: Measure, p: DataPointValue, sid=None, version=None
    ) -> None:
        """Feed one written point through all TopN rules of its measure."""
        with self._obs_lock:
            self._observe_locked(m, p, sid, version)
        self._drain_emits()

    def _observe_locked(
        self, m: Measure, p: DataPointValue, sid=None, version=None
    ) -> None:
        for rule in self.engine.registry.list_topn(m.group):
            if rule.source_measure != m.name:
                continue
            key = (m.group, rule.name)
            # criteria filter runs BEFORE any window allocation: rejected
            # rows must not create empty windows (they would prematurely
            # LRU-evict real ones)
            crit = self._cached_criteria(key, rule)
            if crit is not None and not _row_matches(
                {
                    t: _key_str(p.tags.get(t))
                    for t in _crit_tag_names(crit)
                },
                crit,
            ):
                continue
            start = p.ts_millis - (p.ts_millis % self.window_millis)
            wins = self._windows[key]
            win = wins.get(start)
            if win is None:
                win = wins[start] = self._new_window(start)
                self._evict_over_lru(key, rule)
            # counters key = entity tags + extra group-by dims (results
            # display the entity prefix; extras serve conditions)
            ent = tuple(
                _key_str(p.tags.get(t)) for t in rule_key_tags(rule, m)
            )
            if self._accumulate(
                win, rule, ent,
                float(p.fields.get(rule.field_name, 0)),
                sid, p.ts_millis, version,
            ):
                win.dirty = True
            wm = self._watermark.get(key, 0)
            if p.ts_millis > wm:
                self._watermark[key] = p.ts_millis
            self._flush_closed(key, rule)

    def _evict_over_lru(self, key: tuple, rule: TopNAggregation) -> None:
        wins = self._windows[key]
        bound = max(int(rule.lru_size or 10), 2)
        while len(wins) > bound:
            oldest = min(wins)
            win = wins.pop(oldest)
            if win.dirty:
                self._emit(key[0], rule, win)

    def observe_columns(
        self, m: Measure, ts_millis, tags, fields, sids=None, versions=None
    ) -> None:
        """Columnar twin of observe(): feed a bulk write's columns through
        all TopN rules of its measure (closes the row-vs-bulk semantic
        split, ref one-write-path banyand/measure/write_standalone.go:348).

        Measures with no rules pay one registry scan and return; rule
        accumulation matches observe() row-for-row (same window routing,
        late-drop, counters bound, watermark and flush behavior).
        ``sids``/``versions`` enable version-merged accumulation
        (_accumulate): rewrites of the same (series, ts) replace."""
        with self._obs_lock:
            self._observe_columns_locked(
                m, ts_millis, tags, fields, sids, versions
            )
        self._drain_emits()

    def _observe_columns_locked(
        self, m: Measure, ts_millis, tags, fields, sids=None, versions=None
    ) -> None:
        import numpy as np

        rules = [
            r
            for r in self.engine.registry.list_topn(m.group)
            if r.source_measure == m.name
        ]
        if not rules:
            return
        ts = np.asarray(ts_millis, dtype=np.int64)
        n = ts.shape[0]
        if n == 0:
            return

        as_str = _key_str  # one canonical string domain (module helper)

        # batch-level decode, shared across rules (starts/ts once; tag
        # string columns memoized per tag)
        starts_all = (ts - (ts % self.window_millis)).tolist()
        tsl = ts.tolist()
        sidl = (
            np.asarray(sids, dtype=np.int64).tolist()
            if sids is not None
            else None
        )
        verl = (
            np.asarray(versions, dtype=np.int64).tolist()
            if versions is not None
            else None
        )
        str_cols: dict[str, list] = {}

        def col_of(t: str) -> list:
            col = str_cols.get(t)
            if col is None:
                tv = tags.get(t)
                if tv is None:
                    col = [""] * n
                elif hasattr(tv, "codes"):  # dictionary-encoded column
                    sd = np.asarray(
                        [as_str(v) for v in tv.values], dtype=object
                    )
                    col = sd[np.asarray(tv.codes)].tolist()
                else:
                    col = [as_str(v) for v in tv]
                str_cols[t] = col
            return col

        for rule in rules:
            key = (m.group, rule.name)
            starts = starts_all
            fvals = fields.get(rule.field_name)
            fvals = (
                np.asarray(fvals, dtype=np.float64).tolist()
                if fvals is not None
                else [0.0] * n
            )
            # per-source-series counters + extra group-by dims
            gtags = rule_key_tags(rule, m)
            cols = [col_of(t) for t in gtags]
            crit = self._cached_criteria(key, rule)
            crit_tags = None
            if crit is not None:
                # string-domain columns for every referenced tag (the
                # same _key_str domain the canonicalized tree carries)
                crit_tags = {t: col_of(t) for t in _crit_tag_names(crit)}
            wins = self._windows[key]
            wm = self._watermark.get(key, 0)
            for i in range(n):
                if crit_tags is not None and not _row_matches(
                    {t: col[i] for t, col in crit_tags.items()}, crit
                ):
                    continue
                start = starts[i]
                win = wins.get(start)
                if win is None:
                    win = wins[start] = self._new_window(start)
                    self._evict_over_lru(key, rule)
                ent = tuple(c[i] for c in cols)
                if self._accumulate(
                    win, rule, ent, fvals[i],
                    sidl[i] if sidl is not None else None,
                    tsl[i],
                    verl[i] if verl is not None else None,
                ):
                    win.dirty = True
                if tsl[i] > wm:
                    wm = tsl[i]
            self._watermark[key] = wm
            self._flush_closed(key, rule)

    def _flush_closed(self, key: tuple, rule: TopNAggregation) -> None:
        """Emit every DIRTY window the watermark has passed, KEEPING its
        state: a late row re-dirties the window and the next flush
        re-emits it with a higher version (the result measure's
        (series, window) dedup replaces the earlier rows)."""
        wm = self._watermark.get(key, 0)
        for start, win in self._windows[key].items():
            if (
                win.dirty
                and start + self.window_millis + self.lateness_millis <= wm
            ):
                win.dirty = False
                self._emit(key[0], rule, win)

    def flush_all_windows(self) -> None:
        """Emit every dirty window (shutdown / test hook); state kept."""
        with self._obs_lock:
            for (group, rname), wins in list(self._windows.items()):
                rule = next(
                    (r for r in self.engine.registry.list_topn(group) if r.name == rname),
                    None,
                )
                if rule is None:
                    continue
                for win in wins.values():
                    if win.dirty:
                        win.dirty = False
                        self._emit(group, rule, win)
        self._drain_emits()

    def _emit(self, group: str, rule: TopNAggregation, win: _Window) -> None:
        """Rank + QUEUE one window's counters (called with _obs_lock
        held); the actual result-measure write happens lock-free in
        _drain_emits."""
        if not win.sums:
            return
        directions = (
            ("desc", "asc")
            if rule.field_value_sort == "all"
            else (rule.field_value_sort,)
        )
        points = []
        # count-0 counters are fully-retracted version-merge residue:
        # an entity with no surviving rows must not rank (its earlier
        # emission, if any, is replaced by nothing — acceptable residue,
        # the re-emit path only covers entities that still exist)
        ranked = sorted(
            (kv for kv in win.sums.items() if kv[1][1] > 0),
            key=lambda kv: kv[1][0],
        )
        for direction in directions:
            chosen = (
                ranked[-rule.counters_number :][::-1]
                if direction == "desc"
                else ranked[: rule.counters_number]
            )
            # store up to counters_number; final N is applied at query
            self._emit_seq += 1
            for ent, (total, _cnt) in chosen:
                points.append(
                    DataPointValue(
                        ts_millis=win.start,
                        tags={
                            "topn_name": rule.name,
                            "sort": direction,
                            "entity": _SEP.join(ent),
                        },
                        fields={"value": total},
                        version=self._emit_seq,
                    )
                )
        self._pending_emits.append((group, tuple(points)))

    def _drain_emits(self) -> None:
        """Write queued emissions with NO manager lock held.  Concurrent
        drainers may interleave batches; the result measure's (series,
        window-start) max-version dedup makes arrival order irrelevant."""
        while True:
            with self._obs_lock:
                if not self._pending_emits:
                    return
                group, points = self._pending_emits.pop(0)
            self.engine.ensure_result_measure(group)
            self.engine.write(
                WriteRequest(group, RESULT_MEASURE, points),
                _internal=True,
            )


def query_topn(
    engine: "MeasureEngine",
    group: str,
    rule_name: str,
    time_range: TimeRange,
    *,
    n: int = 10,
    direction: str = "desc",
    agg: str = "sum",
    conditions: tuple = (),
) -> list[tuple[tuple, float]]:
    """Re-rank across windows (topn_post_processor.go analog).

    conditions: (tag, op, value) filters over the counter key dims
    (entity tags + rule group-by extras); "" counters compare as None.
    Distinct-best step (topn_plan_distinct.go): each DISPLAYED entity
    (the source measure's entity prefix) keeps its extreme surviving
    window value in the query direction; the aggregation then applies
    over that single distinct item — sum/max/min/mean all equal it,
    count is 1."""
    from banyandb_tpu.api.model import Aggregation, Condition, GroupBy, LogicalExpression

    rule = next(
        (r for r in engine.registry.list_topn(group) if r.name == rule_name),
        None,
    )
    if rule is None:
        raise KeyError(f"topn rule {rule_name} not found")
    src = engine.registry.get_measure(
        rule.source_group or group, rule.source_measure
    )
    key_tags = rule_key_tags(rule, src)
    ent_n = len(src.entity.tag_names)
    for name, _op, _v in conditions:
        if name not in key_tags:
            raise ValueError(f"TopN condition on unknown tag {name!r}")

    extreme = "max" if direction == "desc" else "min"
    req = QueryRequest(
        groups=(group,),
        name=RESULT_MEASURE,
        time_range=time_range,
        criteria=LogicalExpression(
            "and",
            Condition("topn_name", "eq", rule_name),
            Condition("sort", "eq", direction),
        ),
        group_by=GroupBy(("entity",)),
        agg=Aggregation(extreme, "value"),
        limit=0,
    )
    res = engine.query(req)
    key = f"{extreme}(value)"

    # conditions evaluate through the SAME canonical string domain and
    # evaluator as ingest-time rule criteria (no second implementation)
    conds_canon = tuple(
        Condition(nm, op, _canon_cond_value(v)) for nm, op, v in conditions
    )

    def cond_ok(full: tuple) -> bool:
        by = dict(zip(key_tags, full))
        return all(_row_matches(by, c) for c in conds_canon)

    best: dict[tuple, float] = {}
    for g, v in zip(res.groups, res.values[key]):
        full = tuple(g[0].split(_SEP))
        if not cond_ok(full):
            continue
        disp = full[:ent_n]
        cur = best.get(disp)
        if cur is None or (v > cur if direction == "desc" else v < cur):
            best[disp] = v
    # entity tie-break: equal values must rank identically here and in
    # the worker pool's concat re-rank (cluster/workers.py), where ties
    # would otherwise follow worker index instead of engine group order
    pairs = sorted(
        best.items(),
        key=lambda kv: (kv[1], kv[0]),
        reverse=(direction == "desc"),
    )
    if agg == "count":  # one distinct item per entity reaches the agg
        return [(ent, 1.0) for ent, _ in pairs[:n]]
    return pairs[:n]
