"""TopN pre-aggregation: ingest-time streaming top/bottom-N.

Analog of banyand/measure/topn.go (topNProcessorManager :94, streaming
processor :340): measure writes flow through per-rule tumbling time
windows; on window close the per-group aggregates are ranked and the
winners land as data points in the shared ``_top_n_result`` measure,
which the normal (TPU) query path then serves; TopN queries re-rank
across windows (topn_post_processor.go analog).

Windows are tiny (counters_number bounded), so window accumulation is a
dict of float sums host-side; the heavy path — querying the result
measure — rides the standard device executor.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

from banyandb_tpu.api.model import (
    DataPointValue,
    QueryRequest,
    TimeRange,
    WriteRequest,
)
from banyandb_tpu.api.schema import (
    Entity,
    FieldSpec,
    FieldType,
    Measure,
    TagSpec,
    TagType,
    TopNAggregation,
)

if TYPE_CHECKING:  # pragma: no cover
    from banyandb_tpu.models.measure import MeasureEngine

RESULT_MEASURE = "_top_n_result"
_SEP = "\x01"


def result_measure_schema(group: str) -> Measure:
    """The shared result measure (storage-and-format.md §3.5 analog)."""
    return Measure(
        group=group,
        name=RESULT_MEASURE,
        tags=(
            TagSpec("topn_name", TagType.STRING),
            TagSpec("sort", TagType.STRING),
            TagSpec("entity", TagType.STRING),
        ),
        fields=(FieldSpec("value", FieldType.FLOAT),),
        entity=Entity(("topn_name", "sort", "entity")),
    )


@dataclass
class _Window:
    start: int
    sums: dict  # entity tuple -> [sum, count]


class TopNProcessorManager:
    """Per-engine manager: routes measure writes into rule windows."""

    def __init__(
        self,
        engine: "MeasureEngine",
        *,
        window_millis: int = 60_000,
        lateness_millis: int = 0,
    ):
        self.engine = engine
        self.window_millis = window_millis
        self.lateness_millis = lateness_millis
        # (group, rule name) -> {window_start -> _Window}
        self._windows: dict[tuple, dict[int, _Window]] = defaultdict(dict)
        self._watermark: dict[tuple, int] = {}
        self._closed_until: dict[tuple, int] = {}  # drop-late boundary
        self._emit_seq = 0

    def observe(self, m: Measure, p: DataPointValue) -> None:
        """Feed one written point through all TopN rules of its measure."""
        for rule in self.engine.registry.list_topn(m.group):
            if rule.source_measure != m.name:
                continue
            key = (m.group, rule.name)
            start = p.ts_millis - (p.ts_millis % self.window_millis)
            if start < self._closed_until.get(key, 0):
                # Tumbling-window contract: data later than the watermark's
                # closed boundary is dropped (re-opening a closed window
                # would emit a duplicate (series, ts) result row that
                # dedup resolves arbitrarily).
                continue
            win = self._windows[key].get(start)
            if win is None:
                win = self._windows[key][start] = _Window(start, {})
            ent = tuple(
                str(p.tags.get(t, "")) for t in rule.group_by_tag_names
            ) or (str(p.tags.get(m.entity.tag_names[0], "")),)
            acc = win.sums.get(ent)
            if acc is None:
                if len(win.sums) >= rule.counters_number:
                    continue  # bounded counters (heap-capacity analog)
                acc = win.sums[ent] = [0.0, 0]
            acc[0] += float(p.fields.get(rule.field_name, 0))
            acc[1] += 1
            wm = self._watermark.get(key, 0)
            if p.ts_millis > wm:
                self._watermark[key] = p.ts_millis
            self._flush_closed(key, rule)

    def observe_columns(self, m: Measure, ts_millis, tags, fields) -> None:
        """Columnar twin of observe(): feed a bulk write's columns through
        all TopN rules of its measure (closes the row-vs-bulk semantic
        split, ref one-write-path banyand/measure/write_standalone.go:348).

        Measures with no rules pay one registry scan and return; rule
        accumulation matches observe() row-for-row (same window routing,
        late-drop, counters bound, watermark and flush behavior)."""
        import numpy as np

        rules = [
            r
            for r in self.engine.registry.list_topn(m.group)
            if r.source_measure == m.name
        ]
        if not rules:
            return
        ts = np.asarray(ts_millis, dtype=np.int64)
        n = ts.shape[0]
        if n == 0:
            return

        def as_str(v) -> str:
            if v is None:
                return ""
            if isinstance(v, bytes):
                return v.decode(errors="replace")
            return str(v)

        # batch-level decode, shared across rules (starts/ts once; tag
        # string columns memoized per tag)
        starts_all = (ts - (ts % self.window_millis)).tolist()
        tsl = ts.tolist()
        str_cols: dict[str, list] = {}

        def col_of(t: str) -> list:
            col = str_cols.get(t)
            if col is None:
                tv = tags.get(t)
                if tv is None:
                    col = [""] * n
                elif hasattr(tv, "codes"):  # dictionary-encoded column
                    sd = np.asarray(
                        [as_str(v) for v in tv.values], dtype=object
                    )
                    col = sd[np.asarray(tv.codes)].tolist()
                else:
                    col = [as_str(v) for v in tv]
                str_cols[t] = col
            return col

        for rule in rules:
            key = (m.group, rule.name)
            starts = starts_all
            fvals = fields.get(rule.field_name)
            fvals = (
                np.asarray(fvals, dtype=np.float64).tolist()
                if fvals is not None
                else [0.0] * n
            )
            gtags = tuple(rule.group_by_tag_names) or (m.entity.tag_names[0],)
            cols = [col_of(t) for t in gtags]
            wins = self._windows[key]
            wm = self._watermark.get(key, 0)
            horizon = self.window_millis + self.lateness_millis
            # windows close as the watermark advances THROUGH the batch
            # (row-path parity: a late row after a mid-batch closure is
            # dropped, not re-accumulated); track the earliest open
            # window's close time so the flush check is O(1) per row
            next_close = min((s + horizon for s in wins), default=None)
            closed = self._closed_until.get(key, 0)
            for i in range(n):
                start = starts[i]
                if start < closed:
                    continue  # tumbling-window late-drop (see observe())
                win = wins.get(start)
                if win is None:
                    win = wins[start] = _Window(start, {})
                    close_at = start + horizon
                    if next_close is None or close_at < next_close:
                        next_close = close_at
                ent = tuple(c[i] for c in cols)
                acc = win.sums.get(ent)
                if acc is None:
                    if len(win.sums) >= rule.counters_number:
                        continue  # bounded counters (heap-capacity analog)
                    acc = win.sums[ent] = [0.0, 0]
                acc[0] += fvals[i]
                acc[1] += 1
                if tsl[i] > wm:
                    wm = tsl[i]
                    self._watermark[key] = wm
                # row-path parity: observe() runs _flush_closed after
                # EVERY point, so a window already at-or-past the
                # watermark's close boundary (late row into a window the
                # watermark has overtaken) emits immediately and
                # subsequent late rows drop — not only when wm advances
                if next_close is not None and wm >= next_close:
                    self._flush_closed(key, rule)
                    closed = self._closed_until.get(key, 0)
                    next_close = min(
                        (s + horizon for s in wins), default=None
                    )
            self._watermark[key] = wm

    def _flush_closed(self, key: tuple, rule: TopNAggregation) -> None:
        wm = self._watermark.get(key, 0)
        closed = [
            s
            for s in self._windows[key]
            if s + self.window_millis + self.lateness_millis <= wm
        ]
        for start in closed:
            self._closed_until[key] = max(
                self._closed_until.get(key, 0), start + self.window_millis
            )
            self._emit(key[0], rule, self._windows[key].pop(start))

    def flush_all_windows(self) -> None:
        """Close every open window (shutdown / test hook)."""
        for (group, rname), wins in list(self._windows.items()):
            rule = next(
                (r for r in self.engine.registry.list_topn(group) if r.name == rname),
                None,
            )
            if rule is None:
                continue
            for start in list(wins):
                self._emit(group, rule, wins.pop(start))

    def _emit(self, group: str, rule: TopNAggregation, win: _Window) -> None:
        if not win.sums:
            return
        self.engine.ensure_result_measure(group)
        directions = (
            ("desc", "asc")
            if rule.field_value_sort == "all"
            else (rule.field_value_sort,)
        )
        points = []
        ranked = sorted(win.sums.items(), key=lambda kv: kv[1][0])
        for direction in directions:
            chosen = (
                ranked[-rule.counters_number :][::-1]
                if direction == "desc"
                else ranked[: rule.counters_number]
            )
            # store up to counters_number; final N is applied at query
            self._emit_seq += 1
            for ent, (total, _cnt) in chosen:
                points.append(
                    DataPointValue(
                        ts_millis=win.start,
                        tags={
                            "topn_name": rule.name,
                            "sort": direction,
                            "entity": _SEP.join(ent),
                        },
                        fields={"value": total},
                        version=self._emit_seq,
                    )
                )
        self.engine.write(
            WriteRequest(group, RESULT_MEASURE, tuple(points)),
            _internal=True,
        )


def query_topn(
    engine: "MeasureEngine",
    group: str,
    rule_name: str,
    time_range: TimeRange,
    *,
    n: int = 10,
    direction: str = "desc",
    agg: str = "sum",
) -> list[tuple[tuple, float]]:
    """Re-rank across windows (topn_post_processor.go analog)."""
    from banyandb_tpu.api.model import Aggregation, Condition, GroupBy, LogicalExpression

    req = QueryRequest(
        groups=(group,),
        name=RESULT_MEASURE,
        time_range=time_range,
        criteria=LogicalExpression(
            "and",
            Condition("topn_name", "eq", rule_name),
            Condition("sort", "eq", direction),
        ),
        group_by=GroupBy(("entity",)),
        agg=Aggregation(agg, "value"),
        limit=0,
    )
    res = engine.query(req)
    key = f"{agg}(value)"
    pairs = [
        (tuple(g[0].split(_SEP)), v)
        for g, v in zip(res.groups, res.values[key])
    ]
    pairs.sort(key=lambda kv: kv[1], reverse=(direction == "desc"))
    return pairs[:n]
