"""TopN pre-aggregation: ingest-time streaming top/bottom-N.

Analog of banyand/measure/topn.go (topNProcessorManager :94, streaming
processor :340): measure writes flow through per-rule tumbling time
windows; on window close the per-group aggregates are ranked and the
winners land as data points in the shared ``_top_n_result`` measure,
which the normal (TPU) query path then serves; TopN queries re-rank
across windows (topn_post_processor.go analog).

Windows are tiny (counters_number bounded), so window accumulation is a
dict of float sums host-side; the heavy path — querying the result
measure — rides the standard device executor.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass
from typing import TYPE_CHECKING, Optional

from banyandb_tpu.api.model import (
    DataPointValue,
    QueryRequest,
    TimeRange,
    WriteRequest,
)
from banyandb_tpu.api.schema import (
    Entity,
    FieldSpec,
    FieldType,
    Measure,
    TagSpec,
    TagType,
    TopNAggregation,
)

if TYPE_CHECKING:  # pragma: no cover
    from banyandb_tpu.models.measure import MeasureEngine

RESULT_MEASURE = "_top_n_result"
_SEP = "\x01"


def result_measure_schema(group: str) -> Measure:
    """The shared result measure (storage-and-format.md §3.5 analog)."""
    return Measure(
        group=group,
        name=RESULT_MEASURE,
        tags=(
            TagSpec("topn_name", TagType.STRING),
            TagSpec("sort", TagType.STRING),
            TagSpec("entity", TagType.STRING),
        ),
        fields=(FieldSpec("value", FieldType.FLOAT),),
        entity=Entity(("topn_name", "sort", "entity")),
    )


@dataclass
class _Window:
    start: int
    sums: dict  # entity tuple -> [sum, count]


class TopNProcessorManager:
    """Per-engine manager: routes measure writes into rule windows."""

    def __init__(
        self,
        engine: "MeasureEngine",
        *,
        window_millis: int = 60_000,
        lateness_millis: int = 0,
    ):
        self.engine = engine
        self.window_millis = window_millis
        self.lateness_millis = lateness_millis
        # (group, rule name) -> {window_start -> _Window}
        self._windows: dict[tuple, dict[int, _Window]] = defaultdict(dict)
        self._watermark: dict[tuple, int] = {}
        self._closed_until: dict[tuple, int] = {}  # drop-late boundary
        self._emit_seq = 0

    def observe(self, m: Measure, p: DataPointValue) -> None:
        """Feed one written point through all TopN rules of its measure."""
        for rule in self.engine.registry.list_topn(m.group):
            if rule.source_measure != m.name:
                continue
            key = (m.group, rule.name)
            start = p.ts_millis - (p.ts_millis % self.window_millis)
            if start < self._closed_until.get(key, 0):
                # Tumbling-window contract: data later than the watermark's
                # closed boundary is dropped (re-opening a closed window
                # would emit a duplicate (series, ts) result row that
                # dedup resolves arbitrarily).
                continue
            win = self._windows[key].get(start)
            if win is None:
                win = self._windows[key][start] = _Window(start, {})
            ent = tuple(
                str(p.tags.get(t, "")) for t in rule.group_by_tag_names
            ) or (str(p.tags.get(m.entity.tag_names[0], "")),)
            acc = win.sums.get(ent)
            if acc is None:
                if len(win.sums) >= rule.counters_number:
                    continue  # bounded counters (heap-capacity analog)
                acc = win.sums[ent] = [0.0, 0]
            acc[0] += float(p.fields.get(rule.field_name, 0))
            acc[1] += 1
            wm = self._watermark.get(key, 0)
            if p.ts_millis > wm:
                self._watermark[key] = p.ts_millis
            self._flush_closed(key, rule)

    def _flush_closed(self, key: tuple, rule: TopNAggregation) -> None:
        wm = self._watermark.get(key, 0)
        closed = [
            s
            for s in self._windows[key]
            if s + self.window_millis + self.lateness_millis <= wm
        ]
        for start in closed:
            self._closed_until[key] = max(
                self._closed_until.get(key, 0), start + self.window_millis
            )
            self._emit(key[0], rule, self._windows[key].pop(start))

    def flush_all_windows(self) -> None:
        """Close every open window (shutdown / test hook)."""
        for (group, rname), wins in list(self._windows.items()):
            rule = next(
                (r for r in self.engine.registry.list_topn(group) if r.name == rname),
                None,
            )
            if rule is None:
                continue
            for start in list(wins):
                self._emit(group, rule, wins.pop(start))

    def _emit(self, group: str, rule: TopNAggregation, win: _Window) -> None:
        if not win.sums:
            return
        self.engine.ensure_result_measure(group)
        directions = (
            ("desc", "asc")
            if rule.field_value_sort == "all"
            else (rule.field_value_sort,)
        )
        points = []
        ranked = sorted(win.sums.items(), key=lambda kv: kv[1][0])
        for direction in directions:
            chosen = (
                ranked[-rule.counters_number :][::-1]
                if direction == "desc"
                else ranked[: rule.counters_number]
            )
            # store up to counters_number; final N is applied at query
            self._emit_seq += 1
            for ent, (total, _cnt) in chosen:
                points.append(
                    DataPointValue(
                        ts_millis=win.start,
                        tags={
                            "topn_name": rule.name,
                            "sort": direction,
                            "entity": _SEP.join(ent),
                        },
                        fields={"value": total},
                        version=self._emit_seq,
                    )
                )
        self.engine.write(
            WriteRequest(group, RESULT_MEASURE, tuple(points)),
            _internal=True,
        )


def query_topn(
    engine: "MeasureEngine",
    group: str,
    rule_name: str,
    time_range: TimeRange,
    *,
    n: int = 10,
    direction: str = "desc",
    agg: str = "sum",
) -> list[tuple[tuple, float]]:
    """Re-rank across windows (topn_post_processor.go analog)."""
    from banyandb_tpu.api.model import Aggregation, Condition, GroupBy, LogicalExpression

    req = QueryRequest(
        groups=(group,),
        name=RESULT_MEASURE,
        time_range=time_range,
        criteria=LogicalExpression(
            "and",
            Condition("topn_name", "eq", rule_name),
            Condition("sort", "eq", direction),
        ),
        group_by=GroupBy(("entity",)),
        agg=Aggregation(agg, "value"),
        limit=0,
    )
    res = engine.query(req)
    key = f"{agg}(value)"
    pairs = [
        (tuple(g[0].split(_SEP)), v)
        for g, v in zip(res.groups, res.values[key])
    ]
    pairs.sort(key=lambda kv: kv[1], reverse=(direction == "desc"))
    return pairs[:n]
