"""Data-model engines: Measure, Stream, Trace, Property
(the reference's banyand/{measure,stream,trace,property} analogs)."""
