"""Data- and liaison-role server processes (pkg/cmdsetup/{data,liaison}.go
analog): the multi-process cluster form of the standalone server.

Role topology mirrors the reference (SURVEY §1): liaisons are the user
gateway — they own schema CRUD (pushed to data nodes over the schema
plane), route writes by entity shard, and scatter/merge queries; data
nodes own storage shards behind the gRPC bus.

    # data nodes (one per process/host)
    python -m banyandb_tpu.server --role data --root /var/n0 --port 18912

    # discovery file listing the data nodes
    [{"name": "n0", "addr": "10.0.0.1:18912", "roles": ["data"]}, ...]

    # liaison (user gateway; bydbctl targets this address)
    python -m banyandb_tpu.server --role liaison --root /var/l \
        --port 17912 --discovery nodes.json --replicas 1

Both classes are the in-process composition roots the reference builds
in cmdsetup: tests boot real multi-node clusters by instantiating them
directly (the pkg/test/setup trick), production runs one per process.
"""

from __future__ import annotations

import threading
from pathlib import Path

from banyandb_tpu.api.schema import SchemaRegistry
from banyandb_tpu.cluster.bus import LocalBus, Topic
from banyandb_tpu.cluster.data_node import DataNode
from banyandb_tpu.cluster.discovery import FileDiscovery
from banyandb_tpu.cluster.liaison import Liaison
from banyandb_tpu.cluster.rpc import GrpcBusServer, GrpcTransport


class DataServer:
    """Data role: a DataNode behind a gRPC bus + lifecycle loops."""

    def __init__(self, root: str | Path, *, name: str = "", port: int = 0):
        self.root = Path(root)
        self.registry = SchemaRegistry(self.root)
        self.name = name or self.root.name or "data"
        self.node = DataNode(self.name, self.registry, self.root / "data")
        self.grpc = GrpcBusServer(self.node.bus, port=port)

    @property
    def addr(self) -> str:
        return self.grpc.addr

    def start(self) -> "DataServer":
        # data nodes run the scan kernels: bind the plan-signature store
        # and warm recorded + builtin plans before the first query lands
        from banyandb_tpu.query.precompile import default_registry

        # the partition fault site needs this process's node identity
        from banyandb_tpu.cluster import faults

        faults.set_local_node(self.name)
        reg = default_registry()
        reg.attach_store(self.root / "plan-registry.json")
        reg.warm_async()
        self.grpc.start()
        self.node.start_lifecycle()
        return self

    def stop(self) -> None:
        from banyandb_tpu.query.precompile import default_registry

        default_registry().shutdown()
        self.node.stop_lifecycle()
        self.grpc.stop()


class _LiaisonMeasureAdapter:
    """Engine-shaped facade over the liaison's distributed measure plane,
    so WireServices (built against engine call signatures) serves the
    cluster unchanged — the liaison/grpc/measure.go role."""

    def __init__(self, liaison):
        self._l = liaison

    def query(self, req, shard_ids=None):
        return self._l.query_measure(req)

    def write(self, req, _internal: bool = False) -> int:
        return self._l.write_measure(req)

    def flush(self, group=None) -> list:
        # parts materialize on data nodes' own lifecycle loops; the
        # liaison holds no local measure storage to flush
        return []


class _LiaisonStreamAdapter:
    def __init__(self, liaison, registry):
        self._l = liaison
        self._reg = registry

    def query(self, req, shard_ids=None):
        return self._l.query_stream(req)

    def write(self, group: str, name: str, elements) -> int:
        import base64

        from banyandb_tpu.api.schema import _to_jsonable

        return self._l.write_stream(
            group, name, _to_jsonable(self._reg.get_stream(group, name)),
            [
                {
                    "element_id": e.element_id,
                    "ts": e.ts_millis,
                    "tags": e.tags,
                    "body": base64.b64encode(e.body).decode(),
                }
                for e in elements
            ],
        )


class _LiaisonTraceAdapter:
    def __init__(self, liaison, registry):
        self._l = liaison
        self._reg = registry

    def get_trace(self, group: str, name: str):
        return self._reg.get_trace(group, name)

    def query(self, req, *, shard_ids=None, tracer=None):
        return self._l.query_trace(req, tracer=tracer)

    def query_by_trace_id(self, group: str, name: str, trace_id: str):
        return self._l.query_trace_by_id(group, name, trace_id)

    def write(self, group: str, name: str, spans, *, ordered_tags=()) -> int:
        import base64

        from banyandb_tpu.api.schema import _to_jsonable

        return self._l.write_trace(
            group, name, _to_jsonable(self._reg.get_trace(group, name)),
            [
                {
                    "ts": s.ts_millis,
                    "tags": s.tags,
                    "span": base64.b64encode(s.span).decode(),
                }
                for s in spans
            ],
            ordered_tags=tuple(ordered_tags),
        )


class LiaisonServer:
    """Liaison role: user-facing surfaces over the cluster fabric.

    Serves the same user topics as the standalone server (health,
    registry, writes, BydbQL, trace lookup) so bydbctl works unchanged,
    plus — via engine-shaped adapters — the reference-proto gRPC wire
    and the HTTP gateway/console.  Every handler delegates to the
    Liaison's distributed paths: schema CRUD pushes to all data nodes,
    writes route by shard with replica fan-out + handoff, queries
    scatter and merge.
    """

    PROBE_INTERVAL_S = 5.0

    def __init__(
        self,
        root: str | Path,
        discovery_file: str | Path,
        *,
        port: int = 0,
        replicas: int = 0,
        wire_port: int | None = None,
        http_port: int | None = None,
        auth_file: str | None = None,
        slow_query_ms: float | None = None,
    ):
        from banyandb_tpu.admin.accesslog import AccessLog
        from banyandb_tpu.obs import SlowQueryRecorder
        from banyandb_tpu.utils.envflag import env_float

        self.root = Path(root)
        self.registry = SchemaRegistry(self.root)
        self.transport = GrpcTransport()
        if slow_query_ms is None:
            slow_query_ms = env_float(
                "BYDB_SLOW_QUERY_MS", AccessLog.DEFAULT_SLOW_QUERY_MS
            )
        self.slow_query_ms = slow_query_ms
        self.slowlog = SlowQueryRecorder()
        # multi-tenant QoS at the gateway (docs/robustness.md
        # "Multi-tenant QoS"): the liaison is the cluster's ingest/query
        # ingress, so per-tenant quotas and weighted admission gate here
        from banyandb_tpu.qos.plane import global_qos

        self.qos = global_qos()
        self.liaison = Liaison(
            self.registry,
            self.transport,
            discovery=FileDiscovery(discovery_file),
            replicas=replicas,
            handoff_root=str(self.root / "handoff"),
            # epoch-versioned placement survives liaison restarts (and
            # is how a straggling second liaison catches up after a
            # stale-epoch rejection)
            placement_store=str(self.root / "placement.json"),
        )
        # elastic-cluster control plane (docs/robustness.md): operator
        # rebalance surface + the background replica-repair loop
        from banyandb_tpu.cluster.rebalance import Rebalancer, ReplicaRepairer

        self.rebalancer = Rebalancer(self.liaison)
        self.repairer = ReplicaRepairer(self.liaison)
        from banyandb_tpu.utils.envflag import env_float

        self.repair_interval_s = env_float("BYDB_REPAIR_INTERVAL_S", 30.0)
        self._repair_thread: threading.Thread | None = None
        # schema plane: EVERY create/update on this liaison's registry —
        # whatever surface it arrived on (bus topic, proto wire, HTTP
        # gateway) — pushes to all data nodes (liaison/grpc/registry.go
        # behavior); acks are recorded per object for barrier callers
        self._sync_acks: dict = {}
        self.registry.watch(self._on_schema_put)
        self.bus = LocalBus()
        self._register()
        self.grpc = GrpcBusServer(self.bus, port=port)
        # engine-shaped trace facade: QL execution, the proto wire and
        # the self-trace sink all share it
        self._trace_adapter = _LiaisonTraceAdapter(self.liaison, self.registry)
        from banyandb_tpu.obs.selftrace import SelfTraceSink

        self.self_trace = SelfTraceSink(
            self._trace_adapter, self.registry, node="liaison"
        )
        self.wire = None
        self.http = None
        if wire_port is not None or http_port is not None:
            from banyandb_tpu.api.grpc_server import WireServices

            self._wire_services = WireServices(
                self.registry,
                _LiaisonMeasureAdapter(self.liaison),
                _LiaisonStreamAdapter(self.liaison, self.registry),
                trace_engine=self._trace_adapter,
                node_info={"name": "liaison", "roles": ("liaison",)},
                cluster_view_fn=self._cluster_view,
            )
        if wire_port is not None:
            from banyandb_tpu.api.grpc_server import WireServer

            self.wire = WireServer(
                self._wire_services, port=wire_port, auth_file=auth_file
            )
        if http_port is not None:
            from banyandb_tpu.api.auth import AuthReloader
            from banyandb_tpu.api.http_gateway import HttpGateway

            http_auth = None
            if auth_file:
                http_auth = (
                    self.wire.auth
                    if self.wire is not None and self.wire.auth is not None
                    else AuthReloader(auth_file)
                )
            self.http = HttpGateway(
                self._wire_services, port=http_port, auth=http_auth,
                slowlog=self.slowlog,
            )
        self._stop = threading.Event()
        self._probe_thread: threading.Thread | None = None

    def _on_schema_put(self, kind: str, obj, revision: int) -> None:
        try:
            acks = self.liaison.sync_schema(kind, obj)
            self._sync_acks[(kind, self.registry._key(obj))] = acks
        except Exception:  # noqa: BLE001 - a down fabric must not fail
            # the local registry write; nodes converge via handoff/gossip
            import logging

            logging.getLogger(__name__).exception(
                "schema push failed for %s", kind
            )

    def _cluster_view(self) -> dict:
        nodes = [
            {"name": n.name, "grpc_address": n.addr, "roles": list(n.roles)}
            for n in self.liaison.selector.nodes
        ]
        return {
            "tire2": {
                "registered": nodes,
                "active": sorted(self.liaison.alive),
                "evictable": sorted(
                    {n.name for n in self.liaison.selector.nodes}
                    - self.liaison.alive
                ),
            }
        }

    @property
    def addr(self) -> str:
        return self.grpc.addr

    # -- user surface -------------------------------------------------------
    def _register(self) -> None:
        from banyandb_tpu.server import (
            TOPIC_METRICS,
            TOPIC_QL,
            TOPIC_QOS,
            TOPIC_REGISTRY,
            TOPIC_SLOWLOG,
        )

        b = self.bus
        b.subscribe(
            Topic.HEALTH,
            lambda env: {
                "status": "ok",
                "role": "liaison",
                "alive": sorted(self.liaison.alive),
            },
        )
        b.subscribe(TOPIC_REGISTRY, self._registry_op)
        b.subscribe(TOPIC_METRICS, self._metrics)
        b.subscribe(TOPIC_QOS, self._qos)
        b.subscribe(TOPIC_SLOWLOG, self._slowlog)
        b.subscribe(Topic.MEASURE_WRITE, self._measure_write)
        b.subscribe(Topic.STREAM_WRITE, self._stream_write)
        b.subscribe(Topic.TRACE_WRITE, self._trace_write)
        b.subscribe(Topic.TRACE_QUERY_BY_ID, self._trace_query_by_id)
        b.subscribe(TOPIC_QL, self._ql)
        # streaming-aggregation control plane: the liaison broadcasts a
        # dashboard-signature registration to every alive data node
        # (windows are node-local; each node backfills its own shards)
        b.subscribe("streamagg", self._streamagg)
        # elastic-cluster operator surface (cli.py rebalance
        # plan|apply|status; docs/robustness.md "Elastic cluster")
        b.subscribe("rebalance", self._rebalance)

    def _metrics(self, env: dict):
        """Liaison /metrics: the process-global meter, with the QoS
        admission gauges and tenant-labeled cache-partition rows
        refreshed first — the liaison is the cluster's admission
        ingress, so sheds/queue depth surface HERE."""
        from banyandb_tpu.obs.metrics import global_meter
        from banyandb_tpu.storage.cache import partition_stats

        meter = global_meter()
        self.qos.export_gauges(meter)
        for tenant, st in partition_stats().items():
            for k in ("hits", "misses", "evictions", "entries", "bytes"):
                meter.gauge_set(
                    f"serving_cache_{k}", float(st[k]), {"tenant": tenant}
                )
        return {"prometheus": meter.prometheus_text()}

    def _qos(self, env: dict):
        """QoS introspection (cli.py qos), liaison edition — same reply
        shape as the standalone handler (no protector here: in-flight
        byte charges live on the write-owning roles)."""
        from banyandb_tpu.storage.cache import partition_stats

        return {
            "qos": self.qos.stats(),
            "cache_partitions": partition_stats(),
            "inflight_bytes": {},
        }

    def _rebalance(self, env: dict):
        from banyandb_tpu.cluster.rebalance import RebalancePlan

        op = env.get("op", "status")
        if op == "plan":
            plan = self.rebalancer.plan(
                env.get("nodes") or None,
                replicas=env.get("replicas"),
            )
            return {"plan": plan.to_json()}
        if op == "apply":
            plan = (
                RebalancePlan.from_json(env["plan"])
                if env.get("plan")
                else self.rebalancer.plan(
                    env.get("nodes") or None, replicas=env.get("replicas")
                )
            )
            return {"stats": self.rebalancer.apply(plan)}
        if op == "repair":
            return {"stats": self.repairer.run_once()}
        if op == "status":
            return {
                "status": self.rebalancer.status(),
                "repair": self.repairer.status(),
            }
        raise ValueError(f"bad rebalance op {op!r}")

    def _streamagg(self, env: dict):
        # same op surface as the standalone/data-node handlers (default
        # op=stats), fanned out to the alive data nodes
        op = env.get("op", "stats")
        if op == "register":
            return {
                "acks": self.liaison.register_streamagg(
                    env["group"],
                    env["measure"],
                    key_tags=tuple(env.get("key_tags", ())),
                    fields=tuple(env.get("fields", ())),
                    window_millis=env.get("window_millis"),
                    max_windows=env.get("max_windows"),
                )
            }
        if op == "unregister":
            # the autoreg eviction path reaches the liaison role too:
            # drop the broadcast registration AND the remembered copy so
            # probe() stops re-sending it to rejoining nodes
            return {
                "acks": self.liaison.unregister_streamagg(
                    env["group"],
                    env["measure"],
                    key_tags=tuple(env.get("key_tags", ())),
                    fields=tuple(env.get("fields", ())),
                    window_millis=env.get("window_millis"),
                )
            }
        if op == "stats":
            out = {}
            for n in self.liaison.selector.nodes:
                if n.name not in self.liaison.alive:
                    continue
                out[n.name] = self.liaison.transport.call(
                    n.addr, "streamagg", {"op": "stats"}, timeout=10.0
                ).get("streamagg")
            return {"streamagg": out}
        raise ValueError(f"bad streamagg op {op!r}")

    def _registry_op(self, env: dict):
        """Schema CRUD lands in the liaison registry, then pushes to every
        data node over the schema plane (liaison/grpc/registry.go analog;
        down nodes converge via handoff replay / gossip)."""
        from banyandb_tpu.api import schema as schema_mod
        from banyandb_tpu.api.schema import Stream, Trace

        op, kind = env["op"], env["kind"]
        if op == "create":
            cls = schema_mod._KINDS[kind]
            obj = schema_mod._from_jsonable(cls, env["item"])
            create = {
                "group": self.registry.create_group,
                "measure": self.registry.create_measure,
                "index_rule": self.registry.create_index_rule,
                "topn": self.registry.create_topn,
            }[kind]
            rev = create(obj)
            # the registry watcher already pushed synchronously; surface
            # its per-node acks to the caller
            acks = self._sync_acks.get((kind, self.registry._key(obj)), {})
            return {"revision": rev, "acks": {n: a.get("revision") for n, a in acks.items()}}
        if op == "create_stream":
            obj = schema_mod._from_jsonable(Stream, env["item"])
            return {"revision": self.registry.create_stream(obj)}
        if op == "create_trace":
            obj = schema_mod._from_jsonable(Trace, env["item"])
            return {"revision": self.registry.create_trace(obj)}
        if op == "list":
            if kind == "group":
                items = self.registry.list_groups()
            elif kind == "measure":
                items = self.registry.list_measures(env["group"])
            else:
                raise KeyError(kind)
            return {"items": [schema_mod._to_jsonable(i) for i in items]}
        raise KeyError(f"bad registry op {op}")

    def _measure_write(self, env: dict):
        from banyandb_tpu.cluster import serde

        req = serde.write_request_from_json(env["request"])
        # per-tenant ingest quota at the gateway: over-rate sheds with
        # the retryable ServerBusy wire kind before any fan-out work
        self.qos.admit_write(req.group, len(req.points))
        return {"written": self.liaison.write_measure(req)}

    def _stream_write(self, env: dict):
        from banyandb_tpu.api.schema import _to_jsonable

        self.qos.admit_write(env["group"], len(env["elements"]))
        n = self.liaison.write_stream(
            env["group"], env["name"],
            _to_jsonable(self.registry.get_stream(env["group"], env["name"])),
            env["elements"],
        )
        return {"written": n}

    def _trace_write(self, env: dict):
        from banyandb_tpu.api.schema import _to_jsonable

        self.qos.admit_write(env["group"], len(env["spans"]))
        n = self.liaison.write_trace(
            env["group"], env["name"],
            _to_jsonable(self.registry.get_trace(env["group"], env["name"])),
            env["spans"],
            ordered_tags=tuple(env.get("ordered_tags", ())),
        )
        return {"written": n}

    def _trace_query_by_id(self, env: dict):
        from banyandb_tpu.cluster import serde

        spans = self.liaison.query_trace_by_id(
            env["group"], env["name"], env["trace_id"]
        )
        return {"spans": serde.spans_to_json(spans)}

    def _slowlog(self, env: dict):
        from banyandb_tpu.obs.recorder import slowlog_topic_reply

        return slowlog_topic_reply(self.slowlog, env, self.slow_query_ms)

    def _ql(self, env: dict):
        import time as _time

        from banyandb_tpu import bydbql
        from banyandb_tpu.obs import Tracer
        from banyandb_tpu.server import result_to_json

        catalog, req = bydbql.parse_with_catalog(
            env["ql"], env.get("params", ())
        )
        # always-on liaison-side tracer (node subtrees only attach when
        # req.trace rode the scatter): slow distributed queries land in
        # the flight recorder with whatever tree exists
        tracer = Tracer(f"liaison:{catalog}")
        deadline_ms = env.get("deadline_ms")
        adm = self.qos.admit_query(
            req.groups[0] if req.groups else "",
            deadline_s=(
                float(deadline_ms) / 1000.0 if deadline_ms else None
            ),
        )
        from banyandb_tpu.qos import tenant_scope

        with adm, tenant_scope(adm.tenant):
            with tracer.span("qos") as sp:
                sp.tag("tenant", adm.tenant)
                if adm.queued_ms >= 1.0:
                    sp.tag("queued_ms", round(adm.queued_ms, 2))
            t0 = _time.perf_counter()
            if catalog == "measure":
                res = self.liaison.query_measure(req, tracer=tracer)
            elif catalog == "stream":
                res = self.liaison.query_stream(req, tracer=tracer)
            elif catalog == "trace":
                from banyandb_tpu.query import ql_exec

                res = ql_exec.execute_trace_ql(
                    self._trace_adapter, req, tracer=tracer
                )
            else:
                raise ValueError(
                    f"liaison QL serves measure/stream/trace catalogs; "
                    f"{catalog} queries use the dedicated topics"
                )
            ms = (_time.perf_counter() - t0) * 1000
        tree = tracer.finish()

        def render_plan():
            # untraced slow query: render the DISTRIBUTED plan post-hoc
            # (only past the threshold, never on the hot path)
            from banyandb_tpu.query import logical

            if catalog == "measure":
                m = self.registry.get_measure(req.groups[0], req.name)
                return logical.analyze_measure_distributed(
                    m, req, sorted(self.liaison.alive)
                ).explain()
            if catalog == "trace":
                from banyandb_tpu.models.trace import classify_plan

                t = self.registry.get_trace(req.groups[0], req.name)
                kind = classify_plan(req, t.trace_id_tag)[0]
                return (
                    f"trace plan={kind} "
                    f"order_by={req.order_by_tag or '-'} "
                    f"limit={req.limit} offset={req.offset}"
                )
            s = self.registry.get_stream(req.groups[0], req.name)
            return logical.analyze_stream(s, req).explain()

        from banyandb_tpu.obs.recorder import record_slow_query
        from banyandb_tpu.obs.tracer import attach_tree

        record_slow_query(
            self.slowlog, self.slow_query_ms,
            engine=catalog,
            group=req.groups[0] if req.groups else "",
            name=req.name,
            duration_ms=ms,
            rows=len(res.data_points) or len(res.groups),
            span_tree=tree, ql=env["ql"],
            plan=(res.trace or {}).get("plan"),
            plan_fn=render_plan,
            tenant=adm.tenant,
        )
        # dogfood loop: slow/sampled span trees become trace rows in
        # _monitoring.self_query via the cluster's own trace write path
        self.self_trace.offer(
            engine=catalog,
            group=req.groups[0] if req.groups else "",
            name=req.name,
            duration_ms=ms,
            tree=tree,
            tenant=adm.tenant,
            ql=env["ql"],
        )
        attach_tree(res, req, tree)
        return {"result": result_to_json(res)}

    # -- lifecycle ----------------------------------------------------------
    def _probe_loop(self) -> None:
        while not self._stop.wait(self.PROBE_INTERVAL_S):
            try:
                self.liaison.refresh_nodes()
                self.liaison.probe()
            except Exception:  # noqa: BLE001 - keep probing
                import logging

                logging.getLogger(__name__).exception("liaison probe failed")

    def _repair_loop(self) -> None:
        """Anti-entropy (docs/robustness.md "Elastic cluster"): every
        interval, compare per-shard part manifests across each replica
        chain and re-ship what a replica is missing.  Skipped while a
        rebalance holds the mover lock — the move's own delta round
        covers convergence there."""
        while not self._stop.wait(self.repair_interval_s):
            try:
                if self.rebalancer._lock.acquire(blocking=False):
                    try:
                        self.repairer.run_once()
                    finally:
                        self.rebalancer._lock.release()
            except Exception:  # noqa: BLE001 - keep repairing
                import logging

                logging.getLogger(__name__).exception("replica repair failed")

    def start(self) -> "LiaisonServer":
        from banyandb_tpu.cluster import faults

        faults.set_local_node("liaison")
        self.grpc.start()
        if self.wire is not None:
            self.wire.start()
        if self.http is not None:
            self.http.start()
        self.liaison.probe()
        self._stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="liaison-probe", daemon=True
        )
        self._probe_thread.start()
        if self.repair_interval_s > 0:
            self._repair_thread = threading.Thread(
                target=self._repair_loop, name="bydb-repair", daemon=True
            )
            self._repair_thread.start()
        self.self_trace.start()
        return self

    def stop(self) -> None:
        self.self_trace.stop()
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=10)
        if self._repair_thread is not None:
            self._repair_thread.join(timeout=10)
            self._repair_thread = None
        if self.http is not None:
            self.http.stop()
        if self.wire is not None:
            self.wire.stop()
        self.grpc.stop()
        self.transport.close()
