"""Data- and liaison-role server processes (pkg/cmdsetup/{data,liaison}.go
analog): the multi-process cluster form of the standalone server.

Role topology mirrors the reference (SURVEY §1): liaisons are the user
gateway — they own schema CRUD (pushed to data nodes over the schema
plane), route writes by entity shard, and scatter/merge queries; data
nodes own storage shards behind the gRPC bus.

    # data nodes (one per process/host)
    python -m banyandb_tpu.server --role data --root /var/n0 --port 18912

    # discovery file listing the data nodes
    [{"name": "n0", "addr": "10.0.0.1:18912", "roles": ["data"]}, ...]

    # liaison (user gateway; bydbctl targets this address)
    python -m banyandb_tpu.server --role liaison --root /var/l \
        --port 17912 --discovery nodes.json --replicas 1

Both classes are the in-process composition roots the reference builds
in cmdsetup: tests boot real multi-node clusters by instantiating them
directly (the pkg/test/setup trick), production runs one per process.
"""

from __future__ import annotations

import threading
from pathlib import Path

from banyandb_tpu.api.schema import SchemaRegistry
from banyandb_tpu.cluster.bus import LocalBus, Topic
from banyandb_tpu.cluster.data_node import DataNode
from banyandb_tpu.cluster.discovery import FileDiscovery
from banyandb_tpu.cluster.liaison import Liaison
from banyandb_tpu.cluster.rpc import GrpcBusServer, GrpcTransport


class DataServer:
    """Data role: a DataNode behind a gRPC bus + lifecycle loops."""

    def __init__(self, root: str | Path, *, name: str = "", port: int = 0):
        self.root = Path(root)
        self.registry = SchemaRegistry(self.root)
        self.name = name or self.root.name or "data"
        self.node = DataNode(self.name, self.registry, self.root / "data")
        self.grpc = GrpcBusServer(self.node.bus, port=port)

    @property
    def addr(self) -> str:
        return self.grpc.addr

    def start(self) -> "DataServer":
        self.grpc.start()
        self.node.start_lifecycle()
        return self

    def stop(self) -> None:
        self.node.stop_lifecycle()
        self.grpc.stop()


class LiaisonServer:
    """Liaison role: user-facing bus surface over the cluster fabric.

    Serves the same user topics as the standalone server (health,
    registry, writes, BydbQL, trace lookup) so bydbctl works unchanged —
    but every handler delegates to the Liaison's distributed paths:
    schema CRUD pushes to all data nodes, writes route by shard with
    replica fan-out + handoff, queries scatter and merge.
    """

    PROBE_INTERVAL_S = 5.0

    def __init__(
        self,
        root: str | Path,
        discovery_file: str | Path,
        *,
        port: int = 0,
        replicas: int = 0,
    ):
        self.root = Path(root)
        self.registry = SchemaRegistry(self.root)
        self.transport = GrpcTransport()
        self.liaison = Liaison(
            self.registry,
            self.transport,
            discovery=FileDiscovery(discovery_file),
            replicas=replicas,
            handoff_root=str(self.root / "handoff"),
        )
        self.bus = LocalBus()
        self._register()
        self.grpc = GrpcBusServer(self.bus, port=port)
        self._stop = threading.Event()
        self._probe_thread: threading.Thread | None = None

    @property
    def addr(self) -> str:
        return self.grpc.addr

    # -- user surface -------------------------------------------------------
    def _register(self) -> None:
        from banyandb_tpu.server import TOPIC_QL, TOPIC_REGISTRY

        b = self.bus
        b.subscribe(
            Topic.HEALTH,
            lambda env: {
                "status": "ok",
                "role": "liaison",
                "alive": sorted(self.liaison.alive),
            },
        )
        b.subscribe(TOPIC_REGISTRY, self._registry_op)
        b.subscribe(Topic.MEASURE_WRITE, self._measure_write)
        b.subscribe(Topic.STREAM_WRITE, self._stream_write)
        b.subscribe(Topic.TRACE_WRITE, self._trace_write)
        b.subscribe(Topic.TRACE_QUERY_BY_ID, self._trace_query_by_id)
        b.subscribe(TOPIC_QL, self._ql)

    def _registry_op(self, env: dict):
        """Schema CRUD lands in the liaison registry, then pushes to every
        data node over the schema plane (liaison/grpc/registry.go analog;
        down nodes converge via handoff replay / gossip)."""
        from banyandb_tpu.api import schema as schema_mod
        from banyandb_tpu.api.schema import Stream, Trace

        op, kind = env["op"], env["kind"]
        if op == "create":
            cls = schema_mod._KINDS[kind]
            obj = schema_mod._from_jsonable(cls, env["item"])
            create = {
                "group": self.registry.create_group,
                "measure": self.registry.create_measure,
                "index_rule": self.registry.create_index_rule,
                "topn": self.registry.create_topn,
            }[kind]
            rev = create(obj)
            acks = self.liaison.sync_schema(kind, obj)
            return {"revision": rev, "acks": {n: a.get("revision") for n, a in acks.items()}}
        if op == "create_stream":
            obj = schema_mod._from_jsonable(Stream, env["item"])
            rev = self.registry.create_stream(obj)
            self.liaison.sync_schema("stream", obj)
            return {"revision": rev}
        if op == "create_trace":
            obj = schema_mod._from_jsonable(Trace, env["item"])
            rev = self.registry.create_trace(obj)
            self.liaison.sync_schema("trace", obj)
            return {"revision": rev}
        if op == "list":
            if kind == "group":
                items = self.registry.list_groups()
            elif kind == "measure":
                items = self.registry.list_measures(env["group"])
            else:
                raise KeyError(kind)
            return {"items": [schema_mod._to_jsonable(i) for i in items]}
        raise KeyError(f"bad registry op {op}")

    def _measure_write(self, env: dict):
        from banyandb_tpu.cluster import serde

        req = serde.write_request_from_json(env["request"])
        return {"written": self.liaison.write_measure(req)}

    def _stream_write(self, env: dict):
        from banyandb_tpu.api.schema import _to_jsonable

        n = self.liaison.write_stream(
            env["group"], env["name"],
            _to_jsonable(self.registry.get_stream(env["group"], env["name"])),
            env["elements"],
        )
        return {"written": n}

    def _trace_write(self, env: dict):
        from banyandb_tpu.api.schema import _to_jsonable

        n = self.liaison.write_trace(
            env["group"], env["name"],
            _to_jsonable(self.registry.get_trace(env["group"], env["name"])),
            env["spans"],
            ordered_tags=tuple(env.get("ordered_tags", ())),
        )
        return {"written": n}

    def _trace_query_by_id(self, env: dict):
        from banyandb_tpu.cluster import serde

        spans = self.liaison.query_trace_by_id(
            env["group"], env["name"], env["trace_id"]
        )
        return {"spans": serde.spans_to_json(spans)}

    def _ql(self, env: dict):
        from banyandb_tpu import bydbql
        from banyandb_tpu.server import result_to_json

        catalog, req = bydbql.parse_with_catalog(
            env["ql"], env.get("params", ())
        )
        if catalog == "measure":
            res = self.liaison.query_measure(req)
        elif catalog == "stream":
            res = self.liaison.query_stream(req)
        else:
            raise ValueError(
                f"liaison QL serves measure/stream catalogs; {catalog} "
                "queries use the dedicated topics"
            )
        return {"result": result_to_json(res)}

    # -- lifecycle ----------------------------------------------------------
    def _probe_loop(self) -> None:
        while not self._stop.wait(self.PROBE_INTERVAL_S):
            try:
                self.liaison.refresh_nodes()
                self.liaison.probe()
            except Exception:  # noqa: BLE001 - keep probing
                import logging

                logging.getLogger(__name__).exception("liaison probe failed")

    def start(self) -> "LiaisonServer":
        self.grpc.start()
        self.liaison.probe()
        self._stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="liaison-probe", daemon=True
        )
        self._probe_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=10)
        self.grpc.stop()
        self.transport.close()
