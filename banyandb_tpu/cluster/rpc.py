"""Message transports: in-process and gRPC.

The gRPC transport uses generic (codegen-free) handlers on one method
``/banyandb.Bus/Call`` carrying JSON envelopes — the analog of the
reference's bus-over-gRPC (banyand/queue/pub + sub) with topic dispatch
on the server side.  Chunked part sync rides the same method with binary
chunks base64'd inside the envelope (a streaming method can replace this
without changing the Bus surface).
"""

from __future__ import annotations

import json
import threading
import time
from concurrent import futures
from typing import Optional

from banyandb_tpu.cluster import faults
from banyandb_tpu.cluster.bus import LocalBus
from banyandb_tpu.obs import metrics as obs_metrics

_METHOD = "/banyandb.Bus/Call"


def _observe_rpc(side: str, topic: str, t0: float) -> None:
    """Stage-labelled fabric latency: rpc_client_ms / rpc_server_ms per
    topic.  Handle lookup is the meter's lock-free fast path; observe
    happens after the call completes, never under a transport lock."""
    obs_metrics.global_meter().histogram(
        f"rpc_{side}_ms", {"topic": topic}
    ).observe((time.perf_counter() - t0) * 1000)


class TransportError(RuntimeError):
    """kind: "error" (default), "shed" — the remote rejected the call to
    shed load (DiskFull/ServerBusy); "deadline" — the remote refused
    work whose propagated deadline already expired; or "stale_epoch" —
    the remote fenced a write stamped with a superseded placement epoch
    (cluster/placement.py): the SENDER must refresh its map and retry.
    Shed, deadline and stale-epoch rejecting nodes are healthy and must
    not be treated as dead."""

    def __init__(self, msg: str, kind: str = "error"):
        super().__init__(msg)
        self.kind = kind


# write-admission exception class names serialized as shed rejections
_SHED_TYPES = ("DiskFull", "ServerBusy")


def _error_kind(e: Exception) -> str:
    """Classify a handler exception for the wire: shed rejections,
    deadline refusals and stale-epoch fences are structured (the caller
    must NOT evict the node); everything else is a hard error."""
    name = type(e).__name__
    if name in _SHED_TYPES:
        return "shed"
    if name == "DeadlineExceeded":
        return "deadline"
    if name == "StaleEpoch":
        return "stale_epoch"
    return "error"


class LocalTransport:
    """In-process transport: addr "local:<name>" -> LocalBus.

    The standalone wiring AND the multi-node-in-one-process test trick
    (pkg/test/setup analog) both ride this.
    """

    def __init__(self):
        self._buses: dict[str, LocalBus] = {}
        self._lock = threading.Lock()

    def register(self, name: str, bus: LocalBus) -> str:
        with self._lock:
            self._buses[name] = bus
        return f"local:{name}"

    def unregister(self, name: str) -> None:
        with self._lock:
            self._buses.pop(name, None)

    def call(self, addr: str, topic: str, envelope: dict, timeout: float = 30.0) -> dict:
        assert addr.startswith("local:"), addr
        faults.maybe_fail_rpc(addr, topic)
        bus = self._buses.get(addr[6:])
        if bus is None:
            raise TransportError(f"node {addr} unreachable")
        t0 = time.perf_counter()
        try:
            return bus.handle(topic, envelope)
        except Exception as e:
            # mirror the gRPC transport's shed/deadline classification;
            # all other exceptions keep propagating raw (standalone-equal
            # behavior)
            kind = _error_kind(e)
            if kind != "error":
                raise TransportError(
                    f"{type(e).__name__}: {e}", kind=kind
                ) from e
            raise
        finally:
            _observe_rpc("client", topic, t0)


def prespawn_pool(pool) -> None:
    """Start every worker thread of a ThreadPoolExecutor NOW.

    Executor workers normally spawn lazily on first submit, which (a)
    adds thread-creation latency to the first RPCs a fresh server
    receives and (b) makes the thread population nondeterministic — the
    bdsan per-test thread-parity check needs a server's threads to exist
    when the server starts, not when the first request lands."""
    import threading as _t

    n = pool._max_workers
    barrier = _t.Barrier(n + 1)

    def hold():
        try:
            barrier.wait(timeout=10)
        except _t.BrokenBarrierError:  # pragma: no cover - degraded start
            pass

    for _ in range(n):
        pool.submit(hold)
    try:
        barrier.wait(timeout=10)
    except _t.BrokenBarrierError:  # pragma: no cover - degraded start
        pass


class GrpcBusServer:
    """Serves a LocalBus over gRPC generic handlers (sub.NewServer analog).

    TLS: pass cert_file+key_file for server TLS with HOT RELOAD
    (pkg/tls/reloader.go analog) — rotated PEM files take effect on the
    next handshake via utils/tls_reloader.CertReloader."""

    def __init__(
        self,
        bus: LocalBus,
        port: int = 0,
        host: str = "127.0.0.1",
        *,
        cert_file: Optional[str] = None,
        key_file: Optional[str] = None,
        sync_install=None,
        extra_handlers=(),
    ):
        """sync_install: optional callback enabling the streaming
        ChunkedSyncService on this server (cluster/chunked_sync.py).
        extra_handlers: additional generic RPC handlers to co-host (e.g.
        property repair/gossip, cluster/property_repair_rpc.py)."""
        import grpc

        self.bus = bus

        def call_behavior(request: bytes, context) -> bytes:
            msg = json.loads(request)
            t0 = time.perf_counter()
            try:
                reply = self.bus.handle(msg["topic"], msg["envelope"])
                return json.dumps({"ok": True, "reply": reply}).encode()
            except Exception as e:  # noqa: BLE001 - errors cross the wire
                return json.dumps(
                    {
                        "ok": False,
                        "kind": _error_kind(e),
                        "error": f"{type(e).__name__}: {e}",
                    }
                ).encode()
            finally:
                _observe_rpc("server", msg.get("topic", "?"), t0)

        handler = grpc.method_handlers_generic_handler(
            "banyandb.Bus",
            {
                "Call": grpc.unary_unary_rpc_method_handler(
                    call_behavior,
                    request_deserializer=lambda b: b,
                    response_serializer=lambda b: b,
                )
            },
        )

        # The reference-shaped internal fabric (cluster/v1/rpc.proto:188,
        # banyand/queue/sub): Send is a bidi stream of topic-addressed
        # envelopes (bodies are this bus's JSON envelopes), HealthCheck
        # answers per-service status.  Wire shape matches upstream; the
        # body codec is this framework's envelope JSON rather than the
        # per-topic protos of api/data.
        from banyandb_tpu.api import pb as _pb

        cl = _pb.cluster_rpc_pb2
        wr = _pb.model_write_pb2

        def send_behavior(req_iter, context):
            for req in req_iter:
                try:
                    reply = self.bus.handle(
                        req.topic, json.loads(req.body or b"{}")
                    )
                    yield cl.SendResponse(
                        message_id=req.message_id,
                        body=json.dumps(reply).encode(),
                        status=wr.STATUS_SUCCEED,
                    )
                except Exception as e:  # noqa: BLE001 - errors cross the wire
                    shed = type(e).__name__ in _SHED_TYPES
                    yield cl.SendResponse(
                        message_id=req.message_id,
                        error=f"{type(e).__name__}: {e}",
                        status=(
                            wr.STATUS_INTERNAL_ERROR
                            if not shed
                            else wr.STATUS_DISK_FULL
                        ),
                    )

        def health_behavior(req, context):
            known = req.service_name in self.bus.topics() or not req.service_name
            return cl.HealthCheckResponse(
                service_name=req.service_name,
                status=wr.STATUS_SUCCEED if known else wr.STATUS_NOT_FOUND,
                error="" if known else f"unknown topic {req.service_name}",
            )

        cluster_service = grpc.method_handlers_generic_handler(
            "banyandb.cluster.v1.Service",
            {
                "Send": grpc.stream_stream_rpc_method_handler(
                    send_behavior,
                    request_deserializer=cl.SendRequest.FromString,
                    response_serializer=lambda m: m.SerializeToString(),
                ),
                "HealthCheck": grpc.unary_unary_rpc_method_handler(
                    health_behavior,
                    request_deserializer=cl.HealthCheckRequest.FromString,
                    response_serializer=lambda m: m.SerializeToString(),
                ),
            },
        )
        # the server does NOT own a pool it is merely handed: keep the
        # reference so stop() can join the workers (grpc never shuts a
        # caller-provided executor down — idle worker threads would
        # otherwise outlive every stopped server, a leak the bdsan
        # thread-parity check catches)
        self._pool = futures.ThreadPoolExecutor(max_workers=8)
        self._server = grpc.server(
            self._pool,
            options=[("grpc.max_receive_message_length", 64 * 1024 * 1024),
                     ("grpc.max_send_message_length", 64 * 1024 * 1024)],
        )
        self._server.add_generic_rpc_handlers((handler, cluster_service))
        if sync_install is not None:
            from banyandb_tpu.cluster import chunked_sync

            self._server.add_generic_rpc_handlers(
                (chunked_sync.generic_handler(sync_install),)
            )
        if extra_handlers:
            self._server.add_generic_rpc_handlers(tuple(extra_handlers))
        self.tls_reloader = None
        if cert_file and key_file:
            # hot-reloading credentials (pkg/tls/reloader.go:55 analog):
            # rotated PEMs take effect on the next handshake, no restart
            from banyandb_tpu.utils.tls_reloader import CertReloader

            self.tls_reloader = CertReloader(cert_file, key_file)
            self.port = self._server.add_secure_port(
                f"{host}:{port}", self.tls_reloader.server_credentials()
            )
        else:
            self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.addr = f"{host}:{self.port}"

    def start(self) -> None:
        prespawn_pool(self._pool)
        self._server.start()

    def stop(self, grace: float = 1.0) -> None:
        self._server.stop(grace).wait()
        self._pool.shutdown(wait=True)


class GrpcTransport:
    """Client side: per-address channels (banyand/queue/pub analog).

    TLS: pass ca_file (PEM of the server cert / CA) to dial with
    credentials; optionally override the expected server name for
    self-signed certs."""

    def __init__(
        self,
        *,
        ca_file: Optional[str] = None,
        server_name_override: Optional[str] = None,
    ):
        self._channels: dict[str, object] = {}
        self._lock = threading.Lock()
        self._ca_file = ca_file
        self._server_name_override = server_name_override

    def _stub(self, addr: str):
        """-> (unary-unary stub, the channel it rides) for addr.  The
        channel is returned so a failing call can evict exactly the
        channel it used (see _evict)."""
        import grpc

        with self._lock:
            ch = self._channels.get(addr)
            if ch is None:
                options = [
                    ("grpc.max_receive_message_length", 64 * 1024 * 1024),
                    ("grpc.max_send_message_length", 64 * 1024 * 1024),
                ]
                if self._ca_file:
                    from pathlib import Path as _P

                    creds = grpc.ssl_channel_credentials(
                        _P(self._ca_file).read_bytes()
                    )
                    if self._server_name_override:
                        options.append(
                            (
                                "grpc.ssl_target_name_override",
                                self._server_name_override,
                            )
                        )
                    ch = grpc.secure_channel(addr, creds, options=options)
                else:
                    ch = grpc.insecure_channel(addr, options=options)
                self._channels[addr] = ch
            return (
                ch.unary_unary(
                    _METHOD,
                    request_serializer=lambda b: b,
                    response_deserializer=lambda b: b,
                ),
                ch,
            )

    def channel(self, addr: str):
        """Raw grpc channel for streaming services (chunked sync)."""
        return self._stub(addr)[1]

    def evict(self, addr: str) -> None:
        """Public eviction for STREAMING users: a failed SyncPart stream
        never passes through call(), so its wedged channel would survive
        the UNAVAILABLE-eviction below and poison every retry against a
        restarted peer (same gVisor-class wedge, see _evict).  Dropping
        the cache entry makes the next dial fresh; the old channel is
        released when its last user lets go."""
        with self._lock:
            self._channels.pop(addr, None)

    def _evict(self, addr: str, failed) -> None:
        """Drop the channel a call just failed on so the next call dials
        a fresh one.  A channel whose connect wedged can stay in
        TRANSIENT_FAILURE long after the peer is reachable — observed on
        gVisor-class kernels, where a dial racing the server's bind
        establishes at the TCP layer but the client event engine misses
        the writability event, burning the full connect timeout per
        retry — while a fresh dial to the same address connects
        instantly.  Evicting on UNAVAILABLE bounds the damage to one
        failed call.  Identity-checked (a concurrent re-dial's healthy
        replacement is never dropped) and NOT closed: a streaming user
        (chunked sync holds channels via .channel()) may still ride it,
        and close() would cancel its in-flight RPCs — the dropped
        channel is released when its last user lets go."""
        with self._lock:
            if self._channels.get(addr) is failed:
                del self._channels[addr]

    def call(self, addr: str, topic: str, envelope: dict, timeout: float = 30.0) -> dict:
        import grpc

        faults.maybe_fail_rpc(addr, topic)
        stub, ch = self._stub(addr)
        payload = json.dumps({"topic": topic, "envelope": envelope}).encode()
        t0 = time.perf_counter()
        try:
            raw = stub(payload, timeout=timeout)
        except grpc.RpcError as e:
            if e.code() == grpc.StatusCode.UNAVAILABLE:
                self._evict(addr, ch)
            # a client-enforced deadline says the CALL was too slow, not
            # that the peer is dead — callers clamping timeouts to a
            # query budget (liaison _QueryGuard) must not evict healthy
            # nodes over their own budget running out
            kind = (
                "deadline"
                if e.code() == grpc.StatusCode.DEADLINE_EXCEEDED
                else "error"
            )
            raise TransportError(
                f"rpc to {addr} failed: {e.code()}", kind=kind
            ) from e
        finally:
            _observe_rpc("client", topic, t0)
        msg = json.loads(raw)
        if not msg.get("ok"):
            raise TransportError(
                msg.get("error", "remote error"),
                kind=msg.get("kind", "error"),
            )
        return msg["reply"]

    def close(self) -> None:
        with self._lock:
            for ch in self._channels.values():
                ch.close()
            self._channels.clear()
