"""Liaison role: user gateway + distributed query planner
(banyand/liaison + banyand/dquery analog).

- Writes: points route by (measure entity -> seriesID -> shard), fan out
  to the shard's replica set (pkg/node/round_robin.go contract).
- Aggregate queries: per-shard primary-alive nodes; each node maps its
  shard subset to Partials on device; liaison reduces
  (measure_exec.combine_partials) and finalizes.  Percentile runs two
  rounds so every node's histogram shares the global range.
- Raw queries: scatter, merge rows, order + limit.
- Health checking: per-call failover to the next replica, plus an
  explicit probe() to refresh the alive set (pub.go:301,364 analog).
"""

from __future__ import annotations

import dataclasses
import threading
import time
import uuid
from typing import Optional

from banyandb_tpu.api.model import Aggregation, QueryRequest, QueryResult, WriteRequest
from banyandb_tpu.api.schema import SchemaRegistry
from banyandb_tpu.cluster import serde
from banyandb_tpu.cluster.bus import Topic
from banyandb_tpu.cluster.node import NodeInfo
from banyandb_tpu.cluster.placement import PlacementMap, PlacementSelector
from banyandb_tpu.cluster.rpc import TransportError
from banyandb_tpu.obs.tracer import NOOP_TRACER, Tracer
from banyandb_tpu.query import measure_exec
from banyandb_tpu.utils import hashing
from banyandb_tpu.utils.envflag import env_float

# RPC deadline tiers (the rpc-timeout contract, docs/linting.md): every
# fabric call states the stall it tolerates.  Probes stay snappy so the
# alive set converges; control-plane pushes are bounded so a dead peer
# can't wedge schema rollout; data-plane queries get room for real
# scans; bulk part sync moves whole files.
_RPC_PROBE_S = 5.0
_RPC_CONTROL_S = 10.0
_RPC_WRITE_S = 15.0
_RPC_QUERY_S = 30.0
_RPC_SYNC_S = 120.0


class _QueryGuard:
    """Per-query deadline budget + degradation accumulator
    (docs/robustness.md).

    The WHOLE distributed query shares one budget: every scatter RPC's
    timeout is clamped to the remaining budget and the envelope carries
    ``deadline_ms`` (remaining at send) so data nodes refuse
    already-expired work — one slow node eats its own slice of the
    budget, never wedges the query past it.  Nodes whose data could not
    be reached (dead, shedding, out of budget) accumulate in ``nodes``
    and surface as the response's ``unavailable_nodes`` marker."""

    __slots__ = ("budget_s", "t_end", "nodes")

    def __init__(self, budget_s: float):
        self.budget_s = budget_s
        self.t_end = time.monotonic() + budget_s
        self.nodes: dict[str, str] = {}  # node name -> reason

    def remaining_s(self) -> float:
        return self.t_end - time.monotonic()

    def expired(self) -> bool:
        return self.remaining_s() <= 0

    def rpc_timeout(self) -> float:
        return max(min(_RPC_QUERY_S, self.remaining_s()), 0.001)

    def deadline_ms(self) -> float:
        return self.remaining_s() * 1000.0

    def mark(self, node_name: str, reason: str) -> None:
        self.nodes.setdefault(node_name, reason)

    @property
    def degraded(self) -> bool:
        return bool(self.nodes)


def _sort_merged_rows(rows: list, req, *, default_desc: bool = True) -> None:
    """Order scattered rows at the liaison merge: by tag value when the
    query orders by an indexed tag (rows missing the tag always sort
    last, regardless of direction), else by timestamp.

    default_desc picks the no-order_by direction per catalog: streams
    default newest-first, measures oldest-first (the reference's
    limit/offset golden pins measure ASC — must match the engines so
    cluster and standalone paginate identically)."""
    if req.order_by_tag:
        tag = req.order_by_tag

        def key(d):
            v = d.get("tags", {}).get(tag)
            # type-ranked key: numerics before strings, never cross-compare
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                return (1, 0, str(v))
            return (0, v, "")

        rows.sort(key=key, reverse=(req.order_by_dir == "desc"))
        # stable second pass: missing-tag rows to the tail either way
        rows.sort(key=lambda d: d.get("tags", {}).get(tag, None) is None)
    else:
        if req.order_by_ts:
            desc = req.order_by_ts == "desc"
        else:
            desc = default_desc
        rows.sort(key=lambda d: d["timestamp"], reverse=desc)


class Liaison:
    def __init__(
        self,
        registry: SchemaRegistry,
        transport,
        nodes: list[NodeInfo] = (),
        *,
        replicas: int = 0,
        discovery=None,
        handoff_root: Optional[str] = None,
        query_budget_s: Optional[float] = None,
        placement_store: "Optional[str]" = None,
    ):
        self.registry = registry
        self.transport = transport
        self.replicas = replicas
        self.discovery = discovery
        # one deadline budget per distributed query (every scatter leg
        # shares it; BYDB_QUERY_DEADLINE_S overrides the _RPC_QUERY_S
        # default)
        self.query_budget_s = (
            query_budget_s
            if query_budget_s is not None
            else env_float("BYDB_QUERY_DEADLINE_S", _RPC_QUERY_S)
        )
        if discovery is not None:
            nodes = discovery.nodes()
        # Explicit epoch-versioned placement (cluster/placement.py,
        # docs/robustness.md "Elastic cluster").  The initial map has
        # no explicit chains, so routing equals the historical
        # round-robin byte-for-byte; a persisted store restores the
        # last cutover's map (epochs survive liaison restarts).
        # `placement`/`selector`/`_dual` follow the same concurrency
        # contract as `alive`: immutable snapshots REBOUND under
        # _placement_lock, read lock-free everywhere else.
        self._placement_lock = threading.Lock()
        from pathlib import Path as _Path

        self._placement_store = (
            _Path(placement_store) if placement_store else None
        )
        stored = (
            PlacementMap.load(self._placement_store)
            if self._placement_store is not None
            else None
        )
        self.placement = stored or PlacementMap.initial(
            [n.name for n in nodes], replicas
        )
        self.selector = PlacementSelector(list(nodes), self.placement)
        # dual-route window (rebalance catch-up): shard -> extra owner
        # names that receive every write ALONGSIDE the current chain
        self._dual: dict[int, tuple[str, ...]] = {}
        # membership change observed by refresh_nodes() but NOT applied
        # to the chains (an explicit rebalance plan owns data movement)
        self.pending_topology: Optional[tuple[str, ...]] = None
        from banyandb_tpu.obs.metrics import global_meter

        global_meter().gauge_set(
            "placement_epoch", float(self.placement.epoch)
        )
        # `alive` is read lock-free all over the query/write planes and
        # written from the probe thread AND every RPC worker that sees a
        # dead peer: it is therefore treated as an immutable snapshot —
        # writers REBIND a fresh set under _alive_lock (never mutate in
        # place), readers see either the old or the new reference
        self._alive_lock = threading.Lock()
        self.alive: set[str] = {n.name for n in nodes}
        # newest schema content pushed per (kind, key) — the barrier's
        # trusted "node is ahead" witness (see sync_schema)
        self._schema_latest: dict[tuple[str, str], str] = {}
        # streamagg registrations this liaison has broadcast, keyed by
        # signature identity: nodes that were down at register time (or
        # that join later) receive them when probe() sees them alive —
        # a restarting node's own persisted registry only covers
        # signatures it had already received
        self._streamagg_regs: dict[tuple, dict] = {}
        self._streamagg_sent: dict[str, set] = {}  # node -> sig keys
        self._streamagg_lock = threading.Lock()  # guards the two above
        self.handoff = None
        if handoff_root:
            from banyandb_tpu.cluster.handoff import HandoffController

            self.handoff = HandoffController(handoff_root)

    def refresh_nodes(self) -> bool:
        """Re-read discovery on membership change — WITHOUT re-placing
        shards (discovery/{file,dns} polling loop analog).

        The addr book updates so joined nodes are reachable (schema
        sync, rebalance part shipping) and departed nodes stop being
        dialable, but the placement chains keep serving at the current
        epoch: silently rebuilding the shard->node mapping on a node-set
        change would reroute reads onto nodes that hold NO data (the
        pre-placement hazard this method used to have).  A membership
        change only PROPOSES — ``pending_topology`` records the new node
        set; an explicit rebalance plan+apply (cluster/rebalance.py)
        moves the parts and cuts the epoch over."""
        if self.discovery is None or not self.discovery.refresh():
            return False
        nodes = self.discovery.nodes()
        with self._placement_lock:
            self.selector = PlacementSelector(nodes, self.placement)
            names = tuple(sorted(n.name for n in nodes))
            self.pending_topology = (
                names if names != self.placement.nodes else None
            )
        self.probe()
        return True

    # -- placement lifecycle (cluster/rebalance.py drives these) -------------
    def begin_dual_route(self, adds: "dict[int, tuple[str, ...]]") -> None:
        """Open the rebalance catch-up window: writes for each listed
        shard fan to the current chain AND the named new owners, so no
        row acked during a move exists only on the losing side."""
        with self._placement_lock:
            self._dual = {int(s): tuple(a) for s, a in adds.items() if a}

    def end_dual_route(self) -> None:
        with self._placement_lock:
            self._dual = {}

    def dual_route_shards(self) -> list[int]:
        return list(self._dual)

    def _write_replica_set(self, shard: int) -> list[NodeInfo]:
        """Write-plane replica set: the chain plus any dual-route adds
        for this shard (reads keep using the chain alone until
        cutover — old owners hold everything mid-move)."""
        out = self.selector.replica_set(shard)
        extra = self._dual.get(shard, ())
        if extra:
            have = {n.name for n in out}
            for nm in extra:
                node = self.selector.node_by_name(nm)
                if node is not None and node.name not in have:
                    out.append(node)
                    have.add(nm)
        return out

    def cutover(self, plan) -> int:
        """Atomically switch to the plan's placement map: epoch bump,
        persisted store, dual-route window closed.  The caller
        (Rebalancer.apply) broadcasts the new epoch AFTER this returns
        — RPC fan-out never happens under the placement lock."""
        with self._placement_lock:
            if plan.base_epoch != self.placement.epoch:
                raise RuntimeError(
                    f"cutover refused: plan base epoch {plan.base_epoch} "
                    f"!= current {self.placement.epoch}"
                )
            new = plan.placement()
            self.placement = new
            self.selector = PlacementSelector(list(self.selector.nodes), new)
            self._dual = {}
            names = tuple(sorted(n.name for n in self.selector.nodes))
            self.pending_topology = names if names != new.nodes else None
            if self._placement_store is not None:
                new.save(self._placement_store)
        from banyandb_tpu.obs.metrics import global_meter

        global_meter().gauge_set("placement_epoch", float(new.epoch))
        return new.epoch

    def broadcast_placement(self) -> dict[str, int]:
        """Push the current epoch to every alive node (the cutover
        fence).  Nodes missed here still learn the epoch from the next
        fenced envelope — the broadcast only tightens the window."""
        p = self.placement
        acks: dict[str, int] = {}
        for n in self.selector.nodes:
            if n.name not in self.alive:
                continue
            try:
                r = self.transport.call(
                    n.addr, "placement",
                    {"op": "set", "epoch": p.epoch},
                    timeout=_RPC_CONTROL_S,
                )
                acks[n.name] = int(r.get("epoch", 0))
            except TransportError:
                continue
        return acks

    def _reload_placement(self) -> bool:
        """A stale-epoch rejection means THIS liaison routes on a
        superseded map (another liaison cut over).  Re-read the shared
        placement store; -> True when a fresher map was adopted."""
        if self._placement_store is None:
            return False
        fresh = PlacementMap.load(self._placement_store)
        if fresh is None:
            return False
        with self._placement_lock:
            if fresh.epoch <= self.placement.epoch:
                return False
            self.placement = fresh
            self.selector = PlacementSelector(
                list(self.selector.nodes), fresh
            )
            self._dual = {}
        from banyandb_tpu.obs.metrics import global_meter

        global_meter().gauge_set("placement_epoch", float(fresh.epoch))
        return True

    def _stamp_epoch(self, env: dict) -> dict:
        """Fenced envelope: every write/scatter RPC carries the sender's
        placement epoch so data nodes can reject superseded writers."""
        return dict(env, placement_epoch=self.placement.epoch)

    @staticmethod
    def _stamp_tenant(env: dict, group: str) -> dict:
        """Tenant identity rides every write/scatter envelope
        (docs/robustness.md "Multi-tenant QoS") so data nodes partition
        their serving caches without re-deriving from the group."""
        from banyandb_tpu.qos.tenancy import tenant_of_group

        env["tenant"] = tenant_of_group(group)
        return env

    def _mark_dead(self, name: str) -> None:
        """Drop a peer from the alive snapshot (rebind, never mutate:
        concurrent lock-free readers hold the old reference)."""
        with self._alive_lock:
            self.alive = self.alive - {name}

    # -- health -------------------------------------------------------------
    def probe(self) -> set[str]:
        alive = set()
        for n in self.selector.nodes:
            try:
                r = self.transport.call(
                    n.addr, Topic.HEALTH.value, {}, timeout=_RPC_PROBE_S
                )
                if r.get("status") == "ok":
                    alive.add(n.name)
            except TransportError:
                pass
        with self._alive_lock:
            self.alive = alive
        # streamagg catch-up: any alive node missing a broadcast
        # registration gets it now (idempotent server-side); keyed on
        # sent-state, not on the down->up transition, so a failed send
        # retries at the next probe
        for node in self.selector.nodes:
            if node.name not in alive:
                continue
            with self._streamagg_lock:
                todo = [
                    (key, env)
                    for key, env in self._streamagg_regs.items()
                    if key not in self._streamagg_sent.get(node.name, ())
                ]
            for key, env in todo:  # RPCs OUTSIDE the lock
                try:
                    self.transport.call(
                        node.addr, "streamagg", env, timeout=_RPC_SYNC_S
                    )
                except TransportError:
                    continue  # node flapped: retry at the next probe
                with self._streamagg_lock:
                    self._streamagg_sent.setdefault(
                        node.name, set()
                    ).add(key)
        # Hinted-handoff replay (handoff_controller.go:82): drain the spool
        # of EVERY alive node with pending entries — keyed on pending, not
        # on the down->up transition, so a partially failed replay retries
        # at the next probe instead of stranding the spool.
        if self.handoff is not None:
            for node in self.selector.nodes:
                if node.name in alive and self.handoff.pending(node.name):
                    self.handoff.replay(
                        node.name,
                        # spooled envelopes include write fan-out from
                        # the _replicate failure path: give replay the
                        # write budget, or a heavy spooled write that
                        # would succeed live strands the whole spool
                        # (replay stops at the first failure).  The
                        # epoch is re-stamped at REPLAY time: a spooled
                        # repair copy from before a rebalance cutover
                        # must not wedge the spool on the stale-epoch
                        # fence (the delivery is an idempotent repair,
                        # not a new acked write)
                        lambda topic, env, addr=node.addr: self.transport.call(
                            addr, topic, self._stamp_epoch(env),
                            timeout=_RPC_WRITE_S,
                        ),
                    )
        return alive

    # -- schema push + barrier ---------------------------------------------
    def sync_schema(self, kind: str, obj) -> dict[str, dict]:
        """Push one schema object to all nodes; down nodes get the sync
        spooled through hinted handoff (they catch up at recovery).

        -> {node: ack} where ack carries the node's LOCAL revision AND
        the object's content hash + identity.  Revisions are per-node
        counters (no shared etcd sequence), so a node that restarted
        with an older registry can report a coincidentally-equal number
        — the barrier therefore verifies CONTENT, not counters.
        """
        from banyandb_tpu.api.schema import SchemaRegistry, _to_jsonable

        env = {"kind": kind, "item": _to_jsonable(obj)}
        want_hash = SchemaRegistry.object_hash(obj)
        key = self.registry._key(obj)
        # newest content THIS liaison pushed per object: the barrier's
        # only trusted "node is ahead" witness (node-local revision
        # counters can be bumped by stale handoff replays)
        self._schema_latest[(kind, key)] = want_hash
        acks: dict[str, dict] = {}
        for n in self.selector.nodes:
            if n.name not in self.alive:
                if self.handoff is not None:
                    self.handoff.spool(n.name, Topic.SCHEMA_SYNC.value, env)
                continue
            try:
                r = self.transport.call(
                    n.addr, Topic.SCHEMA_SYNC.value, env,
                    timeout=_RPC_CONTROL_S,
                )
                acks[n.name] = {
                    "revision": r.get("revision", 0),
                    "obj_rev": r.get("obj_rev", 0),
                    "hash": want_hash,
                    "kind": kind,
                    "key": key,
                }
            except TransportError:
                self._mark_dead(n.name)
                if self.handoff is not None:
                    self.handoff.spool(n.name, Topic.SCHEMA_SYNC.value, env)
                else:
                    raise
        return acks

    def schema_barrier(self, acks: dict[str, dict], timeout_s: float = 10.0) -> bool:
        """Block until every acked node serves the synced object with the
        EXPECTED CONTENT HASH (schema/v1/barrier.proto +
        barrier_cluster.go analog).  A node that stops answering counts
        as BEHIND — unreachable is exactly the window the barrier exists
        to close.  Returns False on timeout."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        addr_of = {n.name: n.addr for n in self.selector.nodes}
        while True:
            behind = []
            for name, ack in acks.items():
                try:
                    r = self.transport.call(
                        addr_of[name],
                        Topic.SCHEMA_GET.value,
                        {"kind": ack["kind"], "key": ack["key"]},
                        timeout=5,
                    )
                    # Passed when the node serves the acked content, or
                    # the NEWEST content this liaison has pushed for the
                    # key (a later sync superseded this ack — the node is
                    # ahead).  Node-local revision counters are never
                    # trusted: a stale handoff replay can bump them past
                    # the ack while serving older content.
                    latest = self._schema_latest.get(
                        (ack["kind"], ack["key"])
                    )
                    got = r.get("hash")
                    fresh = got == ack["hash"] or (
                        latest is not None and got == latest
                    )
                    if not fresh:
                        behind.append(name)
                except TransportError:
                    behind.append(name)
            if not behind:
                return True
            if _time.monotonic() >= deadline:
                return False
            _time.sleep(0.05)

    def forget_streamagg_sent(self, node_name: str) -> None:
        """Drop the sent-state for one node so the next probe() re-sends
        every remembered streamagg registration.  Callers that restart a
        node IN PLACE (the worker pool's crash-restart path) use this:
        the fresh process re-registers from its persisted registry, but
        registrations broadcast while it was down exist only here."""
        with self._streamagg_lock:
            self._streamagg_sent.pop(node_name, None)

    # -- streaming aggregation control plane (query/streamagg.py) -----------
    def register_streamagg(
        self,
        group: str,
        measure: str,
        key_tags,
        fields,
        window_millis: Optional[int] = None,
        max_windows: Optional[int] = None,
        origin: str = "manual",
    ) -> dict[str, dict]:
        """Broadcast one materialized dashboard signature to every alive
        data node (windows are node-local per shard; each node backfills
        its own parts, so the scatter's per-shard folds merge like scan
        partials).  -> {node: ack}.  Down nodes re-register themselves
        at restart from their persisted streamagg registry."""
        env = {
            "op": "register",
            "group": group,
            "measure": measure,
            "key_tags": list(key_tags),
            "fields": list(fields),
            "window_millis": window_millis,
            "max_windows": max_windows,
            "origin": origin,
        }
        key = (
            group, measure, tuple(sorted(key_tags)),
            tuple(sorted(fields)), window_millis,
        )
        # remembered for probe()'s catch-up: nodes down right now (and
        # nodes joining later) receive the registration when they are
        # next seen alive — their own persisted registry only covers
        # signatures they had already received
        with self._streamagg_lock:
            self._streamagg_regs[key] = env
        acks: dict[str, dict] = {}
        for n in self.selector.nodes:
            if n.name not in self.alive:
                continue
            # sync-tier timeout: registration backfills from the node's
            # existing parts, which can be a real scan
            acks[n.name] = self.transport.call(
                n.addr, "streamagg", env, timeout=_RPC_SYNC_S
            )
            with self._streamagg_lock:
                self._streamagg_sent.setdefault(n.name, set()).add(key)
        return acks

    def unregister_streamagg(
        self,
        group: str,
        measure: str,
        key_tags,
        fields,
        window_millis: Optional[int] = None,
    ) -> dict[str, dict]:
        """Broadcast a signature drop (the autoreg eviction path) and
        FORGET the remembered registration so probe() stops re-sending
        it to rejoining nodes.  -> {node: ack}."""
        env = {
            "op": "unregister",
            "group": group,
            "measure": measure,
            "key_tags": list(key_tags),
            "fields": list(fields),
            "window_millis": window_millis,
        }
        with self._streamagg_lock:
            drop = [
                key
                for key in self._streamagg_regs
                if key[0] == group
                and key[1] == measure
                and key[2] == tuple(sorted(key_tags))
                and key[3] == tuple(sorted(fields))
                and (window_millis is None or key[4] == window_millis)
            ]
            for key in drop:
                self._streamagg_regs.pop(key, None)
                for sent in self._streamagg_sent.values():
                    sent.discard(key)
        acks: dict[str, dict] = {}
        for n in self.selector.nodes:
            if n.name not in self.alive:
                continue
            acks[n.name] = self.transport.call(
                n.addr, "streamagg", env, timeout=_RPC_SYNC_S
            )
        return acks

    # -- liaison write queue (wqueue.go:75 analog) --------------------------
    def enable_write_queue(self, spool_root, **kw):
        """Switch measure writes to the batching plane: buffered parts per
        (group, shard), sealed + shipped over streaming chunked sync.
        Requires a transport exposing .channel(addr) (GrpcTransport)."""
        from banyandb_tpu.cluster import chunked_sync, wqueue

        def shipper(group: str, shard: int, part_dir):
            """Ship to the FULL replica set (same durability contract as
            the synchronous path).  Any replica failure raises so the
            sealed part stays spooled and retries next tick.  Delivered
            replicas are recorded in a sidecar next to the spooled part,
            so a retry after partial delivery ships only to replicas
            still missing the part — no duplicate installs (and no TopN
            double-observation) on nodes that already have it."""
            import json as _json

            record = part_dir.parent / "delivered.json"
            delivered: set[str] = set()
            if record.exists():
                try:
                    delivered = set(_json.loads(record.read_text()))
                except (OSError, ValueError):
                    delivered = set()
            errors = []
            # write-plane set: dual-route adds receive sealed parts too
            # (re-reading it per attempt means a retry AFTER a cutover
            # ships to the new owners)
            for node in self._write_replica_set(shard):
                if node.name in delivered:
                    continue
                if node.name not in self.alive:
                    errors.append(f"{node.name} down")
                    continue
                try:
                    chan = self.transport.channel(node.addr)
                    chunked_sync.sync_part_dirs(
                        chan, [part_dir], group=group, shard_id=shard,
                        # the epoch fence rides the stream topic: a
                        # straggling shipper's sealed part from before
                        # a cutover is rejected instead of installed on
                        # an owner post-cutover reads never route to
                        placement_epoch=self.placement.epoch,
                    )
                    delivered.add(node.name)
                    from banyandb_tpu.utils import fs as _fs

                    _fs.atomic_write_json(record, sorted(delivered))
                except TransportError as e:
                    # the streaming wire has no structured kind channel:
                    # the fence's message marker identifies a stale-
                    # epoch rejection (cluster/placement.py EpochRecord)
                    if "refresh the placement map" in str(e):
                        # fenced: refresh the map; the part stays
                        # spooled and the retry re-reads the CURRENT
                        # replica set (post-cutover owners)
                        self._reload_placement()
                        errors.append(f"{node.name}: {e}")
                        continue
                    self._mark_dead(node.name)
                    # drop the stream's channel: a wedged one would
                    # otherwise poison every retry after the node
                    # returns (rpc.GrpcTransport.evict)
                    evict = getattr(self.transport, "evict", None)
                    if evict is not None:
                        evict(node.addr)
                    errors.append(f"{node.name}: {e}")
            if errors or not delivered:
                raise TransportError(
                    f"part ship incomplete (delivered to {sorted(delivered)}): {errors}"
                )

        self.wqueue = wqueue.WriteQueue(self.registry, spool_root, shipper, **kw)
        self.wqueue.start()
        return self.wqueue

    def write_measure_queued(self, req: WriteRequest) -> int:
        """Buffered write path: rows land in the liaison write queue and
        reach data nodes as sealed parts on the next seal/ship tick."""
        if getattr(self, "wqueue", None) is None:
            raise RuntimeError("write queue not enabled (enable_write_queue)")
        return self.wqueue.append(req)

    def write_stream_queued(self, group: str, name: str, elements) -> int:
        """Stream twin of write_measure_queued: elements buffer into
        sealed payload parts shipped over chunked sync."""
        if getattr(self, "wqueue", None) is None:
            raise RuntimeError("write queue not enabled (enable_write_queue)")
        return self.wqueue.append_stream(group, name, elements)

    # -- writes -------------------------------------------------------------
    def write_measure(self, req: WriteRequest) -> int:
        """-> number of distinct points accepted (each counted once,
        regardless of replica fan-out).

        Durability contract: a point is accepted only if at least ONE
        replica durably received it over the wire.  Known-down replicas
        get their copies spooled through hinted handoff (so a recovered
        node catches up on everything missed, not just in-flight
        failures); the spool is a bounded cache, never the only copy —
        zero reachable replicas for a shard raises."""
        m = self.registry.get_measure(req.group, req.name)
        shard_num = self.registry.get_group(req.group).resource_opts.shard_num

        def shard_of(p):
            entity = [req.name.encode()] + [
                hashing.entity_bytes(p.tags[t]) for t in m.entity.tag_names
            ]
            return hashing.shard_id(hashing.series_id(entity), shard_num)

        by_node, spool_points, addr_of = self._route_items(req.points, shard_of)
        accepted = len(req.points)

        def env_for(points):
            return self._stamp_tenant({
                "request": serde.write_request_to_json(
                    WriteRequest(req.group, req.name, tuple(points))
                )
            }, req.group)

        self._deliver_writes(
            Topic.MEASURE_WRITE.value,
            {n: env_for(p) for n, p in by_node.items()},
            addr_of,
            {n: env_for(p) for n, p in spool_points.items()},
        )
        return accepted

    def _deliver_writes(
        self,
        topic: str,
        by_node_env: dict[str, dict],
        addr_of: dict[str, str],
        spool_env: dict[str, dict],
    ) -> None:
        """Shared write-plane delivery contract (all three models):
        - in-flight TransportError marks the node dead + spools (ordering
          preserved via the handoff spool);
        - a node SHEDDING LOAD (structured kind="shed" on the transport
          error: DiskFull/ServerBusy) is NOT dead: it stays alive, its
          copy is spooled so handoff replay repairs the gap once the
          node drains (replay keeps failed entries, so a still-full disk
          just retries later), and the retryable rejection propagates to
          the caller when no replica accepted;
        - zero successful wire deliveries -> raise (a spool alone is a
          bounded cache, not durable storage);
        - ANY stale-epoch rejection (kind="stale_epoch") FAILS the whole
          write, even when another replica already accepted it: the
          targets were all computed from a superseded placement map, so
          an ack here could cover a row no post-cutover read would ever
          route to.  The copy is NOT spooled (replaying a fenced write
          is exactly the double-apply the fence exists to stop), the
          placement store is re-read, and the retryable rejection
          propagates — the caller's retry re-routes on the fresh map,
          and the stray accepted copy collapses in version dedup (or
          sits unrouted on a node the new map no longer reads);
        - known-down replica copies (spool_env) land in the spool so a
          recovered node replays the whole outage window."""
        delivered_to: set[str] = set()
        failed: dict[str, dict] = {}
        rejected_names: set[str] = set()  # shed/stale: healthy nodes
        first_stale: Optional[TransportError] = None
        first_rejection: Optional[TransportError] = None
        for name, env in by_node_env.items():
            try:
                self.transport.call(
                    addr_of[name], topic, self._stamp_epoch(env),
                    timeout=_RPC_WRITE_S,
                )
                delivered_to.add(name)
            except TransportError as e:
                kind = getattr(e, "kind", "error")
                if kind == "stale_epoch":
                    rejected_names.add(name)
                    first_stale = first_stale or e
                    first_rejection = first_rejection or e
                    continue  # never spooled: the copy is fenced
                failed[name] = env  # spooled below (shed AND dead alike)
                if kind in ("shed", "deadline"):
                    # a shedding OR deadline-rejecting node is healthy
                    # (rpc.py contract): its budget ran out, the node
                    # did not.  Spool the copy and surface the retryable
                    # rejection — marking it dead would evict a healthy
                    # replica over the sender's own clock.
                    rejected_names.add(name)
                    first_rejection = first_rejection or e
                else:
                    self._mark_dead(name)
        if first_stale is not None:
            # catch up to the cutover that fenced us, then fail the
            # write retryably EVEN IF a (equally stale-routed) replica
            # accepted it — only a retry on the fresh map reaches the
            # owners post-cutover reads actually route to
            self._reload_placement()
            raise first_stale
        if not delivered_to and rejected_names and set(failed) <= rejected_names:
            # every replica rejected retryably (shed load / stale
            # epoch): surface the structured rejection itself rather
            # than a generic unreachable error
            raise first_rejection
        if not delivered_to and failed:
            raise TransportError(
                f"write reached no replica (failed: {sorted(failed)})"
            )
        if self.handoff is not None:
            for name, env in {**failed, **spool_env}.items():
                try:
                    self.handoff.spool(name, topic, env)
                except OSError:
                    # the spool is a bounded repair cache, never the ack
                    # copy: a full/torn spool disk must not fail a write
                    # that already reached a replica
                    import logging

                    logging.getLogger("banyandb.liaison").exception(
                        "handoff spool failed for %s (entry dropped)", name
                    )
        elif failed:
            raise TransportError(
                f"replica write failed with no handoff: {sorted(failed)}"
            )

    # -- queries ------------------------------------------------------------
    def _shard_assignment(
        self,
        group: str,
        stages: tuple[str, ...] = (),
        guard: Optional[_QueryGuard] = None,
    ) -> dict[NodeInfo, list[int]]:
        """Per-shard node assignment, stage-aware (ResolveStage analog).

        `guard` (query paths only): a shard whose whole replica set is
        down DEGRADES the query — the shard is skipped and its down
        replicas land in guard.nodes — instead of failing it outright.
        Zero assignable shards still raise: an empty answer that looks
        merely "degraded" would hide a total outage.

        Untiered groups (no stages configured or requested): each shard
        goes to its replica-chain primary — one node per shard, so
        replicated data is never read twice.

        Tiered groups: every requested stage (default: all the group's
        configured stages) contributes its own full shard assignment over
        that stage's nodes — tier migration MOVES rows between tiers, so
        a row lives in exactly one tier and the cross-tier union stays
        duplicate-free.  Within a stage, shard -> replica-chain primary
        when the chain reaches the stage; otherwise a deterministic
        spread over the stage's nodes (migrated shards need not follow
        the write-time chain)."""
        opts = self.registry.get_group(group).resource_opts
        shard_num = opts.shard_num
        stage_list = tuple(stages) or tuple(opts.stages)

        def stage_nodes(stage: Optional[str]) -> set[str]:
            return {
                n.name
                for n in self.selector.nodes
                if n.name in self.alive
                and (stage is None or n.serves_stage(stage))
            }

        def assign_into(
            assignment, eligible: set[str], label: str, fallback: bool
        ) -> None:
            ordered = sorted(eligible)
            for shard in range(shard_num):
                try:
                    node = self.selector.primary(shard, eligible)
                except RuntimeError:
                    # off-chain spread is only sound for tiered stages,
                    # where migration places shards off the write-time
                    # chain; untiered data lives on chain nodes only, so
                    # a dead chain must error — or, with a degradation
                    # guard, skip the shard and name its down replicas
                    if not fallback or not ordered:
                        if guard is not None:
                            for rep in self.selector.replica_set(shard):
                                if rep.name not in eligible:
                                    guard.mark(rep.name, "unreachable")
                            continue
                        raise TransportError(
                            f"shard {shard} has no alive replica for {label}"
                        ) from None
                    node = next(
                        n for n in self.selector.nodes
                        if n.name == ordered[shard % len(ordered)]
                    )
                entry = assignment.setdefault(node.name, (node, []))
                if shard not in entry[1]:
                    entry[1].append(shard)

        assignment: dict[str, tuple[NodeInfo, list[int]]] = {}
        if not stage_list:
            assign_into(assignment, stage_nodes(None), "any stage", fallback=False)
        else:
            missing = []
            for stage in stage_list:
                eligible = stage_nodes(stage)
                if not eligible:
                    missing.append(stage)
                    continue
                assign_into(assignment, eligible, f"stage {stage!r}", fallback=True)
            if missing and (stages or not assignment):
                # explicitly requested stages must not silently vanish;
                # group-configured stages may have no nodes yet as long
                # as SOME tier answered
                raise TransportError(
                    f"no alive node serves stages {missing}"
                )
        if guard is not None and guard.nodes and not assignment:
            raise TransportError(
                "no shard has an alive replica "
                f"(down: {sorted(guard.nodes)})"
            )
        return {node: shards for node, shards in assignment.values()}

    # -- degraded-tolerant scatter (docs/robustness.md) ---------------------
    def _scatter_one(
        self, topic, node, shards, env_of, guard, t, on_reply, retry,
        timeout_cap_s: float | None = None, attempt: int = 0,
    ) -> None:
        """One scatter leg under the query guard: deadline-clamped
        timeout, deadline_ms stamped on the envelope, structured failure
        handling.  `retry` (list or None) collects hard-failed legs for
        the caller's failover rounds; shed/deadline rejections mark the
        node unavailable without eviction (it is healthy).
        `timeout_cap_s` further clamps the RPC timeout — the last-chance
        same-node retry uses it so a genuinely dead node costs seconds,
        not the whole remaining budget.  `attempt` is the failover round
        index, tagged on the span so a trace shows exactly which
        replicas a leg walked."""
        if guard.expired():
            guard.mark(node.name, "deadline")
            return
        # remaining budget (deadline_ms) AND the absolute wall deadline:
        # the absolute form still fires after the request sat in the
        # receiver's executor queue (same-DC clock skew caveat applies)
        env = self._stamp_epoch(dict(
            env_of(shards),
            deadline_ms=guard.deadline_ms(),
            deadline_unix_ms=time.time() * 1000.0 + guard.deadline_ms(),
        ))
        if t is not NOOP_TRACER:
            # the caller holds a REAL tracer (serving surfaces always
            # do): ask the node for its span subtree even when the user
            # request is untraced — the graft feeds the slow-query
            # recorder and serve-path classification, and rides only
            # the bus reply, never the user-facing result
            env["want_subtree"] = True
        with t.span(f"scatter:{node.name}") as sp:
            sp.tag("shards", list(shards))
            if attempt:
                sp.tag("attempt", attempt)
            timeout = guard.rpc_timeout()
            if timeout_cap_s is not None:
                timeout = min(timeout, timeout_cap_s)
            try:
                r = self.transport.call(
                    node.addr, topic, env, timeout=timeout
                )
            except TransportError as e:
                sp.error(str(e))
                kind = getattr(e, "kind", "error")
                if kind in ("shed", "deadline"):
                    guard.mark(node.name, kind)
                    return
                if kind == "stale_epoch":
                    # the node fenced this leg: WE route on a superseded
                    # placement map.  Adopt the fresh map and hand the
                    # shards to the failover walk, which re-places them
                    # on the new map's owners — the fencing node is
                    # healthy and must never be evicted for our
                    # staleness.
                    self._reload_placement()
                    if retry is not None:
                        retry.append((node, list(shards)))
                    else:
                        guard.mark(node.name, kind)
                    return
                self._mark_dead(node.name)
                if retry is not None:
                    retry.append((node, list(shards)))
                else:
                    guard.mark(node.name, "unreachable")
                return
            # the node ran its own tracer; graft its subtree so the
            # response carries ONE merged span tree
            sp.attach(r.get("trace"))
            on_reply(node, shards, r, sp)

    def _scatter(
        self, topic, assignment, env_of, guard, tracer, on_reply,
        *, failover: bool = True,
    ) -> None:
        """Scatter with EXHAUSTIVE failover: a leg that hard-fails gets
        its shards re-placed on the next surviving replica, round after
        round, until every replica in each shard's chain has been tried
        or the query's deadline budget runs out — never just one round.
        Each shard's tried-and-failed set grows monotonically, so the
        walk terminates; per-attempt span tags (`attempt`) record the
        path.  A shard whose whole chain failed gets one LAST-CHANCE
        capped retry against its original node (a wedged-channel dial
        heals on the fresh dial `call()`'s eviction forces) and then
        degrades the response instead of failing it.

        `failover=False` for TIERED groups: the failover walk follows
        the untiered replica chain, which for a failed warm-tier leg
        could re-place shards onto a hot node that already answered —
        double-counting rows.  Tiered legs degrade directly instead."""
        t = tracer if tracer is not None else NOOP_TRACER
        retry: list[tuple[NodeInfo, list[int]]] = (
            [] if failover else None  # type: ignore[assignment]
        )
        for node, shards in assignment.items():
            self._scatter_one(
                topic, node, shards, env_of, guard, t, on_reply, retry
            )
        if not retry:
            return
        from banyandb_tpu.obs.metrics import global_meter

        meter = global_meter()
        tried: dict[int, set[str]] = {}  # shard -> failed node names
        origin: dict[int, NodeInfo] = {}  # shard -> first-assigned node
        for node, shards in retry:
            for s in shards:
                origin.setdefault(s, node)
        attempt = 0
        pending = retry
        while pending:
            attempt += 1
            meter.counter_add("failover_attempts", 1.0)
            for node, shards in pending:
                for s in shards:
                    tried.setdefault(s, set()).add(node.name)
            placed: dict[str, tuple[NodeInfo, list[int]]] = {}
            exhausted: list[int] = []
            for node, shards in pending:
                for s in shards:
                    # bdlint: disable=retry-backoff -- the failover walk
                    # dials a DIFFERENT replica each round (the tried
                    # set grows monotonically, so it terminates);
                    # sleeping between rounds would only burn the
                    # query's deadline budget, not protect any endpoint
                    try:
                        alt = self.selector.primary(
                            s, self.alive - tried[s]
                        )
                    except RuntimeError:
                        exhausted.append(s)
                        continue
                    placed.setdefault(alt.name, (alt, []))[1].append(s)
            if guard.expired():
                # out of budget: every un-replaced shard degrades with
                # its last failed node named
                for node, shards in pending:
                    guard.mark(node.name, "unreachable")
                return
            next_retry: list[tuple[NodeInfo, list[int]]] = []
            for alt, alt_shards in placed.values():
                # the replacement leg may itself fail: it joins the
                # next round with this node added to the tried set
                self._scatter_one(
                    topic, alt, alt_shards, env_of, guard, t, on_reply,
                    next_retry, attempt=attempt,
                )
            if exhausted:
                # whole chain walked: one last-chance retry against the
                # ORIGINAL node on a capped timeout — a transient
                # transport failure (the wedged-channel dial this
                # kernel occasionally hands out; call() already evicted
                # it) heals on a fresh dial, and a query leg is
                # idempotent.  Terminal: a second failure degrades.
                last_chance: dict[str, tuple[NodeInfo, list[int]]] = {}
                for s in exhausted:
                    node = origin[s]
                    last_chance.setdefault(node.name, (node, []))[1].append(s)
                for node, shards in last_chance.values():
                    self._scatter_one(
                        topic, node, shards, env_of, guard, t, on_reply,
                        None, timeout_cap_s=3.0, attempt=attempt,
                    )
            pending = next_retry

    def _failover_ok(self, group: str, stages: tuple[str, ...]) -> bool:
        """Replica-chain failover is sound only when the query runs
        untiered (no stages requested AND none configured)."""
        try:
            configured = self.registry.get_group(group).resource_opts.stages
        except KeyError:
            configured = ()
        return not (tuple(stages) or tuple(configured))

    def _finish_degraded(self, res, guard, tracer, engine: str) -> None:
        """Stamp the explicit partial-result markers: wire/JSON fields,
        span tags on the tracer's current span, and the
        query_degraded_total counter."""
        if guard is None or not guard.degraded:
            return
        res.degraded = True
        res.unavailable_nodes = sorted(guard.nodes)
        if tracer is not None:
            sp = tracer.current()
            if sp is not None:
                sp.tag("degraded", True)
                sp.tag("unavailable_nodes", sorted(guard.nodes))
                sp.tag(
                    "degraded_reasons",
                    {n: r for n, r in sorted(guard.nodes.items())},
                )
        from banyandb_tpu.obs.metrics import global_meter

        global_meter().counter_add(
            "query_degraded", 1.0, {"engine": engine}
        )

    def _scatter_partials(
        self,
        req: QueryRequest,
        assignment: dict[NodeInfo, list[int]],
        hist_range: Optional[tuple[float, float]],
        tracer=None,
        guard: Optional[_QueryGuard] = None,
        failover: bool = True,
    ) -> list[measure_exec.Partials]:
        if guard is None:
            guard = _QueryGuard(self.query_budget_s)
        env_base = self._stamp_tenant({
            "request": serde.query_request_to_json(req),
            "hist_range": list(hist_range) if hist_range else None,
        }, req.groups[0] if req.groups else "")
        out = []

        def env_of(shards):
            return dict(env_base, shards=shards)

        def on_reply(node, shards, r, sp):
            out.append(serde.partials_from_json(r["partials"]))

        self._scatter(
            Topic.MEASURE_QUERY_PARTIAL.value,
            assignment, env_of, guard, tracer, on_reply, failover=failover,
        )
        return out

    def enable_mesh_fastpath(self, mesh, engines_by_node: dict) -> None:
        """Switch supported aggregate queries onto the collective plane
        (psum/pmin/pmax over the mesh, parallel/mesh_query.py) when the
        data-node engines share this process.  Unsupported query shapes
        fall back to scatter partials per call
        (pkg/query/vectorized/measure/adapter.go:43 analog)."""
        from banyandb_tpu.parallel.mesh_query import MeshExecutor

        self.mesh_exec = MeshExecutor(mesh, engines_by_node)

    def query_measure(self, req: QueryRequest, tracer=None) -> QueryResult:
        """Distributed measure query.  `tracer`: span sink threaded from
        the serving surface (LiaisonServer passes one for the slow-query
        recorder); when None and req.trace is set the liaison owns a
        local tracer.  Node subtrees merge under the scatter spans, so
        `trace=true` responses carry ONE cluster-wide span tree."""
        own_tracer = tracer is None and req.trace
        if own_tracer:
            tracer = Tracer("liaison:measure")
        t = tracer if tracer is not None else NOOP_TRACER
        group = req.groups[0]
        m = self.registry.get_measure(group, req.name)
        guard = _QueryGuard(self.query_budget_s)
        failover = self._failover_ok(group, req.stages)
        with t.span("plan") as ps:
            assignment = self._shard_assignment(group, req.stages, guard=guard)
            ps.tag("nodes", sorted(n.name for n in assignment))

        def _attach_tree(res) -> QueryResult:
            if own_tracer and req.trace:
                res.trace = dict(res.trace or {})
                res.trace["span_tree"] = tracer.finish()
            return res

        mesh_exec = getattr(self, "mesh_exec", None)
        if mesh_exec is not None and (req.agg or req.group_by):
            from banyandb_tpu.parallel.mesh_query import MeshUnsupported

            try:
                with t.span("mesh_execute"):
                    res = mesh_exec.execute(m, req, assignment)
                self._attach_distributed_plan(
                    res, m, req, assignment,
                    combine="mesh psum/pmin/pmax collectives (fast path)",
                )
                return _attach_tree(res)
            except MeshUnsupported:
                pass  # general scatter path below

        if not (req.agg or req.group_by or req.top):
            # Raw scatter-gather.  Nodes scan ONLY their assigned shards
            # (replicated rows must not repeat) and return the first
            # offset+limit rows each; global offset applies after merge.
            off = req.offset or 0
            limit = req.limit or 100
            node_req = dataclasses.replace(req, offset=0, limit=off + limit)
            rows: list[dict] = []
            req_json = serde.query_request_to_json(node_req)

            def env_of(shards):
                return self._stamp_tenant(
                    {"request": req_json, "shards": shards}, group
                )

            def on_reply(node, shards, r, sp):
                sp.tag("rows", len(r["data_points"]))
                rows.extend(r["data_points"])

            self._scatter(
                Topic.MEASURE_QUERY_RAW.value,
                assignment, env_of, guard, tracer, on_reply,
                failover=failover,
            )
            with t.span("merge") as ms:
                _sort_merged_rows(rows, req, default_desc=False)  # ASC
                ms.tag("rows", len(rows))
            res = QueryResult()
            res.data_points = rows[off : off + limit]
            self._attach_distributed_plan(
                res, m, req, assignment, combine="row merge (host ts sort)"
            )
            self._finish_degraded(res, guard, tracer, "measure")
            return _attach_tree(res)

        want_percentile = bool(req.agg and req.agg.function == "percentile")
        hist_range = None
        if want_percentile:
            # Round A: field stats only (agg=min keeps want_minmax on).
            stats_req = dataclasses.replace(
                req, agg=Aggregation("min", req.agg.field_name), top=None
            )
            with t.span("range_round"):
                # tracer threads through: the round's per-node scatter
                # spans (and node subtrees) nest under range_round
                stats = self._scatter_partials(
                    stats_req, assignment, None, tracer=tracer, guard=guard,
                    failover=failover,
                )
            lo, hi = float("inf"), float("-inf")
            for p in stats:
                st = p.field_stats.get(req.agg.field_name)
                if st:
                    lo, hi = min(lo, st[0]), max(hi, st[1])
            if lo > hi:
                lo, hi = 0.0, 1.0
            hist_range = (lo, max(hi - lo, 1e-6))

        partials = self._scatter_partials(
            req, assignment, hist_range, tracer=tracer, guard=guard,
            failover=failover,
        )
        if not partials:
            # EVERY leg was lost (dead/shed/deadline): an aggregate built
            # from nothing is not a degraded answer, it is a failure —
            # raise with the per-node reasons instead of fabricating 0s
            raise TransportError(
                f"no node answered the scatter: {dict(guard.nodes)}",
                kind=(
                    "deadline"
                    if set(guard.nodes.values()) == {"deadline"}
                    else "error"
                ),
            )
        res = measure_exec.finalize_partials(
            m, req, partials,
            span=t.current() if tracer is not None else None,
        )
        self._attach_distributed_plan(
            res, m, req, assignment,
            combine="host combine_partials (f64 Kahan)",
            percentile="two-round range agreement" if want_percentile else "",
        )
        self._finish_degraded(res, guard, tracer, "measure")
        return _attach_tree(res)

    def _attach_distributed_plan(
        self, res, m, req, assignment, *, combine: str, percentile: str = ""
    ) -> None:
        """Distributed plan tree rides the in-band trace, labeled with the
        combine leg that ACTUALLY ran (measure_plan_distributed.go +
        dquery/measure.go:104 analog)."""
        if not req.trace:
            return
        from banyandb_tpu.query import logical

        plan = logical.analyze_measure_distributed(
            m, req, [n.name for n in assignment]
        )
        plan.props["combine"] = combine
        if percentile:
            plan.props["percentile"] = percentile
        res.trace = dict(res.trace or {})
        res.trace["plan"] = plan.explain()


    def _route_items(self, items, shard_of) -> tuple[dict, dict, dict]:
        """items -> (by_node, spool_items, addr_of); raises when an item's
        shard has no alive replica (same contract as write_measure)."""
        by_node: dict[str, list] = {}
        spool_items: dict[str, list] = {}
        addr_of: dict[str, str] = {}
        for item in items:
            shard = shard_of(item)
            # write plane: the chain plus any dual-route adds (a live
            # rebalance fans writes to old AND new owners)
            replicas = self._write_replica_set(shard)
            targets = [n for n in replicas if n.name in self.alive]
            if not targets:
                raise TransportError(f"no alive replica for shard {shard}")
            for node in targets:
                by_node.setdefault(node.name, []).append(item)
                addr_of[node.name] = node.addr
            if self.handoff is not None:
                for node in replicas:
                    if node.name not in self.alive:
                        spool_items.setdefault(node.name, []).append(item)
        return by_node, spool_items, addr_of

    # -- stream plane (liaison stream svc analog) ---------------------------
    def write_stream(self, group: str, name: str, stream_schema: dict, elements: list[dict]) -> int:
        """Route elements by entity-hash shard; schema piggybacks so data
        nodes lazily learn the stream spec."""
        shard_num = self.registry.get_group(group).resource_opts.shard_num
        entity_tags = stream_schema["entity"]

        def shard_of(e):
            entity = [name.encode()] + [
                hashing.entity_bytes(e["tags"][t]) for t in entity_tags
            ]
            return hashing.shard_id(hashing.series_id(entity), shard_num)

        by_node, spool_items, addr_of = self._route_items(elements, shard_of)

        def env_for(elems):
            return self._stamp_tenant(
                {"group": group, "name": name, "schema": stream_schema,
                 "elements": elems},
                group,
            )

        self._deliver_writes(
            Topic.STREAM_WRITE.value,
            {n: env_for(e) for n, e in by_node.items()},
            addr_of,
            {n: env_for(e) for n, e in spool_items.items()},
        )
        return len(elements)

    def query_stream(self, req: QueryRequest, tracer=None) -> QueryResult:
        own_tracer = tracer is None and req.trace
        if own_tracer:
            tracer = Tracer("liaison:stream")
        t = tracer if tracer is not None else NOOP_TRACER
        guard = _QueryGuard(self.query_budget_s)
        assignment = self._shard_assignment(
            req.groups[0], req.stages, guard=guard
        )
        off = req.offset or 0
        limit = req.limit or 100
        node_req = dataclasses.replace(req, offset=0, limit=off + limit)
        rows: list[dict] = []
        req_json = serde.query_request_to_json(node_req)

        def env_of(shards):
            return self._stamp_tenant(
                {"request": req_json, "shards": shards},
                req.groups[0] if req.groups else "",
            )

        def on_reply(node, shards, r, sp):
            sp.tag("rows", len(r["data_points"]))
            rows.extend(r["data_points"])

        self._scatter(
            Topic.STREAM_QUERY.value,
            assignment, env_of, guard, tracer, on_reply,
            failover=self._failover_ok(req.groups[0], req.stages),
        )
        with t.span("merge") as ms:
            _sort_merged_rows(rows, req)
            ms.tag("rows", len(rows))
        res = QueryResult()
        # decode back to the native engine contract (body/tags as bytes):
        # cluster and standalone callers see identical shapes
        import base64

        for dp in rows[off : off + limit]:
            dp = dict(dp)
            dp["body"] = base64.b64decode(dp.get("body", ""))
            dp["tags"] = serde.tags_from_json(dp["tags"])
            res.data_points.append(dp)
        self._finish_degraded(res, guard, tracer, "stream")
        if own_tracer and req.trace:
            res.trace = dict(res.trace or {})
            res.trace["span_tree"] = tracer.finish()
        return res

    # -- trace plane (liaison trace svc analog) -----------------------------
    def write_trace(
        self, group: str, name: str, trace_schema: dict, spans: list[dict],
        ordered_tags: tuple[str, ...] = (),
    ) -> int:
        from banyandb_tpu.models.trace import trace_shard_id

        shard_num = self.registry.get_group(group).resource_opts.shard_num
        tid_tag = trace_schema["trace_id_tag"]
        by_node, spool_items, addr_of = self._route_items(
            spans,
            lambda s: trace_shard_id(str(s["tags"][tid_tag]), shard_num),
        )

        def env_for(batch):
            return self._stamp_tenant({
                "group": group, "name": name, "schema": trace_schema,
                "spans": batch, "ordered_tags": list(ordered_tags),
            }, group)

        self._deliver_writes(
            Topic.TRACE_WRITE.value,
            {n: env_for(b) for n, b in by_node.items()},
            addr_of,
            {n: env_for(b) for n, b in spool_items.items()},
        )
        return len(spans)

    def query_trace_by_id(self, group: str, name: str, trace_id: str) -> list[dict]:
        """Single-shard lookup: route to the trace's shard owner."""
        from banyandb_tpu.models.trace import trace_shard_id

        shard_num = self.registry.get_group(group).resource_opts.shard_num
        shard = trace_shard_id(trace_id, shard_num)
        node = self.selector.primary(shard, self.alive)
        r = self.transport.call(
            node.addr,
            Topic.TRACE_QUERY_BY_ID.value,
            {"group": group, "name": name, "trace_id": trace_id},
            timeout=_RPC_QUERY_S,
        )
        import base64

        # native engine contract: span payloads come back as bytes
        return [
            {**s, "span": base64.b64decode(s.get("span", ""))}
            for s in r["spans"]
        ]

    def query_trace_ordered(
        self,
        group: str,
        name: str,
        order_tag: str,
        time_range,
        *,
        lo=None,
        hi=None,
        asc: bool = False,
        limit: int = 20,
        stages: tuple[str, ...] = (),
    ) -> list[str]:
        """Distributed ordered-trace retrieval (TraceService.Query with a
        TYPE_TREE order, trace_analyzer.go:104 ordered path): scatter the
        sidx scan to every data node, k-way merge per-node (key, id)
        results at the liaison.  A trace lives wholly on one shard, so
        cross-node duplicates only arise from replicas — dedup by id
        keeps the first (correctly-ordered) occurrence."""
        import heapq

        assignment = self._shard_assignment(group, stages)
        streams = []
        for node in assignment:
            r = self.transport.call(
                node.addr,
                Topic.TRACE_QUERY_ORDERED.value,
                {
                    "group": group, "name": name, "order_tag": order_tag,
                    "begin": time_range.begin_millis,
                    "end": time_range.end_millis,
                    "lo": lo, "hi": hi, "asc": asc, "limit": limit,
                },
                timeout=_RPC_QUERY_S,
            )
            streams.append([(int(k), tid) for k, tid in r["results"]])
        merged = heapq.merge(*streams, key=lambda kt: kt[0] if asc else -kt[0])
        out: list[str] = []
        for _k, tid in merged:
            if tid in out:
                continue
            out.append(tid)
            if len(out) >= limit:
                break
        return out

    def query_trace(self, req: QueryRequest, tracer=None) -> QueryResult:
        """Full trace query surface, distributed (TraceService.Query
        analog): the complete QueryRequest scatters to shard owners over
        TRACE_QUERY_EXEC under the query guard (deadline budget,
        exhaustive failover, degraded markers); per-node span rows merge
        at the liaison — sidx (key, trace_id) partial merge on ordered
        plans, deterministic (ts, trace_id, span) order otherwise — with
        global limit+offset applied post-merge (each node pre-trims to
        offset+limit).  Trace-id plans scatter only to the ids' hash-
        shard owners; a trace lives wholly on one shard."""
        import base64

        from banyandb_tpu.models.trace import (
            _DEFAULT_LIMITS,
            _row_order,
            classify_plan,
            trace_shard_id,
        )

        own_tracer = tracer is None and req.trace
        if own_tracer:
            tracer = Tracer("liaison:trace")
        t = tracer if tracer is not None else NOOP_TRACER
        group = req.groups[0]
        tid_tag = self.registry.get_trace(group, req.name).trace_id_tag
        kind, tids, _lo, _hi, _residual = classify_plan(req, tid_tag)
        off = max(req.offset or 0, 0)
        limit = req.limit or _DEFAULT_LIMITS[kind]
        guard = _QueryGuard(self.query_budget_s)
        assignment = self._shard_assignment(group, req.stages, guard=guard)
        if kind == "by_id":
            shard_num = self.registry.get_group(group).resource_opts.shard_num
            owned = {trace_shard_id(tid, shard_num) for tid in tids}
            assignment = {
                node: kept
                for node, shards in assignment.items()
                if (kept := [s for s in shards if s in owned])
            }
        # one batch per scatter leg: the ordered merge dedups replica /
        # failover double-reports by trace id, first batch wins
        batches: list[list[dict]] = []
        node_req = dataclasses.replace(req, offset=0, limit=off + limit)
        req_json = serde.query_request_to_json(node_req)

        def env_of(shards):
            return self._stamp_tenant(
                {"request": req_json, "shards": shards}, group
            )

        def on_reply(node, shards, r, sp):
            sp.tag("rows", len(r["data_points"]))
            # decode back to the native engine contract here: the merge
            # keys compare raw span bytes, not base64 text
            batch = []
            for dp in r["data_points"]:
                dp = dict(dp)
                dp["span"] = base64.b64decode(dp.get("span", ""))
                dp["tags"] = serde.tags_from_json(dp["tags"])
                batch.append(dp)
            batches.append(batch)

        if assignment:
            self._scatter(
                Topic.TRACE_QUERY_EXEC.value,
                assignment, env_of, guard, tracer, on_reply,
                failover=self._failover_ok(group, req.stages),
            )
        res = QueryResult()
        with t.span("merge") as ms:
            if kind == "ordered":
                res.data_points = _merge_ordered_trace_rows(
                    batches, asc=(req.order_by_dir != "desc"),
                    offset=off, limit=limit,
                )
            else:
                rows = [dp for batch in batches for dp in batch]
                rows.sort(key=_row_order)
                res.data_points = rows[off : off + limit]
            ms.tag("rows", len(res.data_points))
        self._finish_degraded(res, guard, tracer, "trace")
        if own_tracer and req.trace:
            res.trace = dict(res.trace or {})
            res.trace["span_tree"] = tracer.finish()
        return res


def _merge_ordered_trace_rows(
    batches: list[list[dict]], *, asc: bool, offset: int, limit: int
) -> list[dict]:
    """sidx-ordered partial merge: group each leg's span rows per trace
    (every row carries its trace's sidx key), order traces globally by
    (key, id) with the walk's direction and tie-break, dedup replica
    overlap by trace id (first leg wins), then page on TRACES — the same
    limit/offset unit as the standalone sidx walk."""
    groups: dict[str, tuple[int, list[dict]]] = {}
    for batch in batches:
        batch_tids: set[str] = set()
        for dp in batch:
            tid = dp.get("trace_id", "")
            if tid in groups and tid not in batch_tids:
                continue  # replica double-report: an earlier leg won
            batch_tids.add(tid)
            ent = groups.get(tid)
            if ent is None:
                ent = (int(dp.get("key", 0)), [])
                groups[tid] = ent
            ent[1].append(dp)
    traces = sorted(
        groups.items(),
        key=lambda kv: ((kv[1][0] if asc else -kv[1][0]), kv[0]),
    )
    out: list[dict] = []
    for _tid, (_k, spans) in traces[offset : offset + limit]:
        out.extend(spans)
    return out


class ChunkedSyncClient:
    """Ship a sealed part to a data node (pub/chunked_sync.go analog):
    logical files, 1 MiB chunks, CRC32 per chunk."""

    CHUNK = 1 << 20

    def __init__(self, transport, addr: str):
        self.transport = transport
        self.addr = addr

    def sync_part(
        self,
        part_dir,
        *,
        group: str,
        segment: str,
        segment_start_millis: int,
        shard: str,
        meta_patch: Optional[dict] = None,
        placement_epoch: Optional[int] = None,
    ) -> str:
        """meta_patch: extra keys merged into the shipped metadata.json
        (not the on-disk original) — tier migration uses it to stamp
        catalog/ordered_tags on engine-flushed parts so the receiver
        routes and aux-indexes them like wqueue-sealed ones.
        placement_epoch: optional epoch fence (cluster/placement.py) —
        receivers reject sessions stamped with a superseded epoch."""
        import json as _json
        import zlib
        import base64
        from pathlib import Path

        part_dir = Path(part_dir)
        session = uuid.uuid4().hex
        base = {
            "session": session,
            "group": group,
            "segment": segment,
            "segment_start_millis": segment_start_millis,
            "shard": shard,
        }
        if placement_epoch is not None:
            base["placement_epoch"] = placement_epoch
        self.transport.call(
            self.addr, Topic.SYNC_PART.value, dict(base, phase="begin"),
            timeout=_RPC_SYNC_S,
        )
        for f in sorted(part_dir.iterdir()):
            data = f.read_bytes()
            if meta_patch and f.name == "metadata.json":
                data = _json.dumps(
                    {**_json.loads(data), **meta_patch}
                ).encode()
            for off in range(0, max(len(data), 1), self.CHUNK):
                blob = data[off : off + self.CHUNK]
                self.transport.call(
                    self.addr,
                    Topic.SYNC_PART.value,
                    dict(
                        base,
                        phase="chunk",
                        file=f.name,
                        offset=off,
                        data=base64.b64encode(blob).decode(),
                        crc32=zlib.crc32(blob),
                    ),
                    timeout=_RPC_SYNC_S,
                )
        r = self.transport.call(
            self.addr, Topic.SYNC_PART.value, dict(base, phase="finish"),
            timeout=_RPC_SYNC_S,
        )
        return r["introduced"]
