"""Live shard rebalancing + replica repair (docs/robustness.md
"Elastic cluster").

Three layers, smallest first:

1. ``plan_rebalance`` — a PURE function (target topology -> minimal
   move list, golden-testable): keeps every current chain member that
   survives in the target node set (up to its fair-share cap), fills
   deficits with the least-loaded target nodes, and emits one
   ``ShardMove`` per shard whose chain changes.  The plan carries the
   base epoch it was computed against; applying a plan whose base
   epoch no longer matches is refused (stale plan).

2. ``Rebalancer`` — the mover.  Executes a plan with zero acked-write
   loss under live ingest:

   - opens the liaison's DUAL-ROUTE window (writes fan to the old
     chain AND the shard's new owners),
   - flushes source memtables, pulls each source part over the bus in
     1 MiB CRC'd chunks and re-ships it to the new owner through the
     existing chunked part-sync install path (``Topic.SYNC_PART``) —
     receiver installs are digest/uuid idempotent, so a re-ship after
     a mid-move crash is a free no-op,
   - runs a second DELTA round (flush + manifest diff) to catch rows
     sealed while the bulk round ran,
   - CUTS OVER: atomically swaps the liaison's placement to the plan's
     map (epoch+1), persists it, closes the dual-route window, and
     broadcasts the new epoch so every data node fences stale writers.

   Old owners keep their (now-unreachable-by-routing) part copies;
   retention ages them out.  Queries route on the OLD placement until
   cutover and the NEW placement after — both views hold every row, so
   results are byte-identical before/during/after the move.

3. ``ReplicaRepairer`` — anti-entropy.  Per shard, compares part-digest
   manifests across the replica chain and re-ships parts a replica is
   missing (node restored from disk loss, missed wqueue ship, ...);
   converges to digest-identical manifests because installs dedupe on
   the same keys the manifests carry.
"""

from __future__ import annotations

import base64
import threading
import time
import uuid
import zlib
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

from banyandb_tpu.cluster.bus import Topic
from banyandb_tpu.cluster.node import NodeInfo
from banyandb_tpu.cluster.placement import PlacementMap
from banyandb_tpu.cluster.rpc import TransportError

# bulk part moves ride the sync tier (whole files over the bus)
_RPC_SYNC_S = 120.0
_RPC_CONTROL_S = 10.0
_PULL_CHUNK = 1 << 20


@dataclass(frozen=True)
class ShardMove:
    """One shard's chain change: which nodes gain the shard (and must
    receive its parts before cutover) and which lose it."""

    shard: int
    add: tuple[str, ...]
    remove: tuple[str, ...]

    def to_json(self) -> dict:
        return {
            "shard": self.shard,
            "add": list(self.add),
            "remove": list(self.remove),
        }


@dataclass(frozen=True)
class RebalancePlan:
    base_epoch: int  # epoch the plan was computed against (fence)
    target_nodes: tuple[str, ...]
    replicas: int
    chains: tuple[tuple[str, ...], ...]
    moves: tuple[ShardMove, ...] = field(default=())

    @property
    def new_epoch(self) -> int:
        return self.base_epoch + 1

    def placement(self) -> PlacementMap:
        return PlacementMap(
            epoch=self.new_epoch,
            nodes=tuple(sorted(self.target_nodes)),
            replicas=self.replicas,
            chains=self.chains,
        )

    def to_json(self) -> dict:
        return {
            "base_epoch": self.base_epoch,
            "new_epoch": self.new_epoch,
            "target_nodes": list(self.target_nodes),
            "replicas": self.replicas,
            "chains": [list(c) for c in self.chains],
            "moves": [m.to_json() for m in self.moves],
        }

    @classmethod
    def from_json(cls, d: dict) -> "RebalancePlan":
        return cls(
            base_epoch=int(d["base_epoch"]),
            target_nodes=tuple(d["target_nodes"]),
            replicas=int(d["replicas"]),
            chains=tuple(tuple(c) for c in d["chains"]),
            moves=tuple(
                ShardMove(int(m["shard"]), tuple(m["add"]), tuple(m["remove"]))
                for m in d.get("moves", ())
            ),
        )


def plan_rebalance(
    placement: PlacementMap,
    target_nodes: Sequence[str],
    *,
    num_shards: int,
    replicas: Optional[int] = None,
) -> RebalancePlan:
    """Pure plan: current placement + target topology -> explicit chains
    for shards ``0..num_shards-1`` and the minimal move list.

    Stability first, then exact balance: every current chain member
    that survives in the target set is kept in place (chain order
    preserved, so surviving primaries stay primaries), then over-quota
    nodes shed slots one swap per shard per sweep — the LAST chain
    position first, replaced by the most-under-quota node — until every
    node is at its fair share (``total_slots // n`` with the remainder
    spread by name order).  A join therefore takes exactly its quota,
    from distinct shards, with the minimal number of slot moves; a
    leave frees exactly its chain slots.  Deterministic: same inputs ->
    same plan, pinned by the golden in tests/test_rebalance.py.
    """
    target = sorted(dict.fromkeys(target_nodes))
    if not target:
        raise ValueError("rebalance target is empty")
    if num_shards <= 0:
        raise ValueError("num_shards must be positive")
    reps = placement.replicas if replicas is None else int(replicas)
    want_n = min(reps + 1, len(target))
    total = num_shards * want_n
    fair_lo, n_hi = divmod(total, len(target))
    quota = {
        n: fair_lo + (1 if i < n_hi else 0)
        for i, n in enumerate(target)
    }
    load = {n: 0 for n in target}
    kept: list[list[str]] = []
    for shard in range(num_shards):
        keep: list[str] = []
        for nm in placement.chain(shard):
            if nm in load and len(keep) < want_n:
                keep.append(nm)
                load[nm] += 1
        kept.append(keep)
    # shed overload: one swap per shard per sweep spreads the churn so
    # a joiner's slots come from DISTINCT shards (it can hold only one
    # slot per chain)
    changed = True
    while changed:
        changed = False
        for keep in kept:
            for pos in range(len(keep) - 1, -1, -1):
                nm = keep[pos]
                if load[nm] <= quota[nm]:
                    continue
                under = [
                    n for n in target
                    if n not in keep and load[n] < quota[n]
                ]
                if not under:
                    continue
                repl = min(under, key=lambda n: (load[n] - quota[n], n))
                keep[pos] = repl
                load[nm] -= 1
                load[repl] += 1
                changed = True
                break
    for keep in kept:  # fill deficits (e.g. replicas raised / node left)
        while len(keep) < want_n:
            nm = min(
                (n for n in target if n not in keep),
                key=lambda n: (load[n] - quota[n], n),
            )
            keep.append(nm)
            load[nm] += 1
    moves = []
    for shard, chain in enumerate(kept):
        old = placement.chain(shard)
        add = tuple(n for n in chain if n not in old)
        remove = tuple(n for n in old if n not in chain)
        if add or remove:
            moves.append(ShardMove(shard, add, remove))
    return RebalancePlan(
        base_epoch=placement.epoch,
        target_nodes=tuple(target),
        replicas=reps,
        chains=tuple(tuple(c) for c in kept),
        moves=tuple(moves),
    )


# -- part movement over the existing sync wire --------------------------------


def shard_manifest(
    transport, node: NodeInfo, shard: int, timeout: float = _RPC_SYNC_S
) -> "tuple[dict[str, dict], int]":
    """One node's per-shard part manifest -> ({digest_key: entry},
    skipped) — `skipped` counts parts the node's merge loop rewrote
    mid-listing (the mover re-runs with a fresh manifest, exactly like
    a gone pull)."""
    r = transport.call(
        node.addr, "rebalance", {"op": "manifest", "shard": shard},
        timeout=timeout,
    )
    return {e["key"]: e for e in r["parts"]}, int(r.get("skipped", 0))


def ship_part(
    transport,
    src: NodeInfo,
    dst: NodeInfo,
    entry: dict,
    *,
    epoch: int,
    chunk: int = _PULL_CHUNK,
) -> str:
    """Pull one part from `src` (whole-part bundle when small, 1 MiB
    CRC'd chunks otherwise) and install it on `dst` through the chunked
    part-sync topic.  -> "moved" when `dst` actually introduced it,
    "deduped" when the install deduped (the part was already there —
    the free re-ship), "gone" when the source's merge loop rewrote the
    part between manifest and pull (its rows live on in the merged
    part; the caller re-manifests and ships that instead)."""
    session = uuid.uuid4().hex
    base = {
        "session": session,
        "group": entry["group"],
        "segment": entry["segment"],
        "segment_start_millis": int(entry["segment_start"]),
        "shard": f"shard-{int(entry['shard'])}",
        "placement_epoch": epoch,
    }
    pull_base = {
        "op": "pull",
        "catalog": entry["catalog"],
        "group": entry["group"],
        "segment_start": int(entry["segment_start"]),
        "shard": int(entry["shard"]),
        "part": entry["part"],
    }
    # fast path: whole-part bundle (1 pull + 1 push) — per-RPC latency,
    # not bandwidth, dominates small-part moves; oversize parts fall
    # back to the per-file 1 MiB chunk loop below.  Pulled BEFORE the
    # receiver session opens so a merged-away part costs nothing there.
    bundle = transport.call(
        src.addr, "rebalance", dict(pull_base, op="pull_all"),
        timeout=_RPC_SYNC_S,
    )
    if bundle.get("gone"):
        return "gone"
    transport.call(
        dst.addr, Topic.SYNC_PART.value, dict(base, phase="begin"),
        timeout=_RPC_SYNC_S,
    )
    if not bundle.get("truncated"):
        # forward the pulled base64 VERBATIM (decode once for the CRCs
        # only — re-encoding identical bytes would double the work and
        # the transient memory per part)
        transport.call(
            dst.addr,
            Topic.SYNC_PART.value,
            dict(
                base,
                phase="files",
                files=bundle["files"],
                crc32s={
                    f: zlib.crc32(base64.b64decode(data))
                    for f, data in bundle["files"].items()
                },
            ),
            timeout=_RPC_SYNC_S,
        )
    else:
        for fname in sorted(entry["files"]):
            size = int(entry["files"][fname])
            off = 0
            while True:
                r = transport.call(
                    src.addr,
                    "rebalance",
                    dict(pull_base, file=fname, offset=off, length=chunk),
                    timeout=_RPC_SYNC_S,
                )
                if r.get("gone"):
                    # merged away mid-stream: drop the receiver session
                    transport.call(
                        dst.addr, Topic.SYNC_PART.value,
                        dict(base, phase="abort"), timeout=_RPC_SYNC_S,
                    )
                    return "gone"
                blob = base64.b64decode(r["data"])
                transport.call(
                    dst.addr,
                    Topic.SYNC_PART.value,
                    dict(
                        base,
                        phase="chunk",
                        file=fname,
                        offset=off,
                        data=base64.b64encode(blob).decode(),
                        crc32=zlib.crc32(blob),
                    ),
                    timeout=_RPC_SYNC_S,
                )
                off += len(blob)
                if r.get("eof", True) or off >= size:
                    break
    r = transport.call(
        dst.addr, Topic.SYNC_PART.value, dict(base, phase="finish"),
        timeout=_RPC_SYNC_S,
    )
    return "deduped" if r.get("duplicate") else "moved"


class Rebalancer:
    """Plan + execute live shard moves against a Liaison."""

    def __init__(self, liaison):
        self.liaison = liaison
        self._lock = threading.Lock()  # one move at a time
        self._state_lock = threading.Lock()  # guards _last/_active
        self._last: dict = {}
        self._active = False

    # -- planning ------------------------------------------------------------
    def num_shards(self) -> int:
        """Widest shard count over the registry's groups: the explicit
        chain range a plan must cover."""
        widest = 0
        for g in self.liaison.registry.list_groups():
            widest = max(widest, g.resource_opts.shard_num)
        return widest

    def plan(
        self,
        target_nodes: Optional[Sequence[str]] = None,
        replicas: Optional[int] = None,
    ) -> RebalancePlan:
        """Target defaults to the liaison's CURRENT addr book — after a
        discovery membership change, that is exactly the joined/left
        topology ``refresh_nodes`` recorded without re-placing."""
        if target_nodes is None:
            target_nodes = [n.name for n in self.liaison.selector.nodes]
        n = self.num_shards()
        if n == 0:
            raise RuntimeError("no groups registered; nothing to place")
        plan = plan_rebalance(
            self.liaison.placement, target_nodes,
            num_shards=n, replicas=replicas,
        )
        from banyandb_tpu.obs.metrics import global_meter

        global_meter().gauge_set(
            "rebalance_shards_to_move", float(len(plan.moves))
        )
        return plan

    # -- execution -----------------------------------------------------------
    def apply(
        self,
        plan: RebalancePlan,
        *,
        mid_move: Optional[Callable[[], None]] = None,
        tracer=None,
    ) -> dict:
        """Execute `plan` to cutover.  ``mid_move`` (tests/chaos): called
        between the bulk and delta ship rounds — the window where a
        crash/kill must be survivable.  Raises on unrecoverable failure
        with the dual-route window CLOSED and the old placement intact;
        already-shipped parts are harmless (installs dedupe) and a
        retried apply re-ships only what is missing."""
        from banyandb_tpu.obs.metrics import global_meter
        from banyandb_tpu.obs.tracer import Tracer

        # wait out a background repair tick holding the mover lock (the
        # liaison's bydb-repair loop); only a genuinely concurrent APPLY
        # should refuse
        if not self._lock.acquire(timeout=120):
            raise RuntimeError("a rebalance is already in progress")
        t = tracer or Tracer("rebalance")
        meter = global_meter()
        stats = {
            "base_epoch": plan.base_epoch,
            "new_epoch": plan.new_epoch,
            "shards_moved": len(plan.moves),
            "parts_planned": 0,
            "parts_moved": 0,
            "parts_deduped": 0,
            "parts_vanished": 0,
            "rounds": 0,
        }
        try:
            with self._state_lock:
                self._active = True
            if plan.base_epoch != self.liaison.placement.epoch:
                raise RuntimeError(
                    f"stale plan: base epoch {plan.base_epoch} != current "
                    f"{self.liaison.placement.epoch}; re-plan and retry"
                )
            with t.span("rebalance") as rs:
                rs.tag("moves", len(plan.moves))
                rs.tag("new_epoch", plan.new_epoch)
                adds = {
                    m.shard: m.add for m in plan.moves if m.add
                }
                # late joiners need the schema BEFORE parts/writes land
                with t.span("schema_sync"):
                    self._sync_schema_to_new_owners(plan)
                with t.span("dual_route"):
                    self.liaison.begin_dual_route(adds)
                try:
                    with t.span("ship:bulk"):
                        self._ship_round(plan, stats)
                        stats["rounds"] += 1
                    if mid_move is not None:
                        mid_move()
                    # delta round: rows sealed while the bulk round ran
                    # (and anything a mid-move crash interrupted).  A
                    # round where a source's merge loop rewrote parts
                    # under the manifest ("gone" pulls) is re-run with a
                    # fresh manifest — cutover only happens after a
                    # round in which nothing vanished, so merged-away
                    # rows always ship via their merged part.
                    for extra in range(5):
                        vanished_before = stats["parts_vanished"]
                        with t.span("ship:delta"):
                            self._ship_round(plan, stats)
                            stats["rounds"] += 1
                        if stats["parts_vanished"] == vanished_before:
                            break
                    else:
                        raise TransportError(
                            "rebalance could not converge: parts kept "
                            "vanishing under merge churn across 5 delta "
                            "rounds"
                        )
                    # the liaison's own write queue, when enabled, may
                    # hold sealed-but-unshipped parts routed at the old
                    # placement: drain before the epoch fence goes up
                    wq = getattr(self.liaison, "wqueue", None)
                    if wq is not None:
                        wq.flush(force=True)
                except BaseException:
                    self.liaison.end_dual_route()
                    raise
                with t.span("cutover") as cs:
                    new_epoch = self.liaison.cutover(plan)
                    cs.tag("epoch", new_epoch)
                # fence every node (outside all locks: RPC fan-out);
                # nodes missed here learn the epoch from the next fenced
                # envelope that reaches them
                self.liaison.broadcast_placement()
            stats["ok"] = True
            return stats
        finally:
            with self._state_lock:
                self._active = False
                self._last = stats
            meter.gauge_set("placement_epoch", float(self.liaison.placement.epoch))
            self._lock.release()

    def _sync_schema_to_new_owners(self, plan: RebalancePlan) -> None:
        """A node that JOINED after schema creation has an empty
        registry — installing a shipped part (or serving its shards
        post-cutover) needs the group/measure/stream/trace specs.  Push
        the liaison's full schema store to every node that gains a
        shard, groups first (everything references its group).
        Idempotent: SCHEMA_SYNC is a put."""
        from banyandb_tpu.api.schema import _to_jsonable

        liaison = self.liaison
        store = liaison.registry._store
        kinds = ["group"] + [k for k in store if k != "group"]
        gaining = sorted({nm for m in plan.moves for nm in m.add})
        for nm in gaining:
            node = liaison.selector.node_by_name(nm)
            if node is None or nm not in liaison.alive:
                continue
            for kind in kinds:
                for obj in store.get(kind, {}).values():
                    liaison.transport.call(
                        node.addr,
                        Topic.SCHEMA_SYNC.value,
                        {"kind": kind, "item": _to_jsonable(obj)},
                        timeout=_RPC_CONTROL_S,
                    )

    def _ship_round(self, plan: RebalancePlan, stats: dict) -> None:
        """One flush + manifest + ship pass over every move."""
        liaison = self.liaison
        transport = liaison.transport
        # flush ALL models on the nodes that source moves, so memtable
        # rows are parts before the manifest snapshot
        sources = set()
        for m in plan.moves:
            for nm in liaison.placement.chain(m.shard):
                sources.add(nm)
        for nm in sorted(sources):
            node = liaison.selector.node_by_name(nm)
            if node is None or nm not in liaison.alive:
                continue
            try:
                transport.call(
                    node.addr, "rebalance", {"op": "flush"},
                    timeout=_RPC_SYNC_S,
                )
            except TransportError:
                continue  # dead source: its replicas cover the manifest
        from banyandb_tpu.obs.metrics import global_meter

        meter = global_meter()
        for m in plan.moves:
            if not m.add:
                continue
            old_chain = liaison.placement.chain(m.shard)
            holders = [
                liaison.selector.node_by_name(nm)
                for nm in old_chain
                if nm in liaison.alive
                and liaison.selector.node_by_name(nm) is not None
            ]
            if not holders:
                raise TransportError(
                    f"shard {m.shard}: no alive holder to move parts from"
                )
            # union manifest across alive holders (independent flushes
            # mean holders can each own parts the others lack); a
            # holder-side mid-listing merge counts as vanishing so the
            # convergence loop runs another round
            union: dict[str, tuple[NodeInfo, dict]] = {}
            for h in holders:
                try:
                    entries, skipped = shard_manifest(transport, h, m.shard)
                except TransportError:
                    liaison._mark_dead(h.name)
                    continue
                stats["parts_vanished"] += skipped
                for key, entry in entries.items():
                    union.setdefault(key, (h, entry))
            for nm in m.add:
                dst = liaison.selector.node_by_name(nm)
                if dst is None:
                    raise TransportError(
                        f"shard {m.shard}: new owner {nm} not in addr book"
                    )
                try:
                    have, _skipped = shard_manifest(transport, dst, m.shard)
                except TransportError:
                    have = {}
                missing = [k for k in union if k not in have]
                stats["parts_planned"] += len(missing)
                meter.counter_add(
                    "rebalance_parts_planned", float(len(missing))
                )
                for key in missing:
                    holder, entry = union[key]
                    outcome = self._ship_with_holder_failover(
                        holders, holder, dst, entry
                    )
                    if outcome == "moved":
                        stats["parts_moved"] += 1
                        meter.counter_add("rebalance_parts_moved", 1.0)
                    elif outcome == "gone":
                        stats["parts_vanished"] += 1
                    else:
                        stats["parts_deduped"] += 1

    def _ship_with_holder_failover(
        self, holders, holder: NodeInfo, dst: NodeInfo, entry: dict
    ) -> str:
        """Ship one part, failing over to the other alive holders when
        the preferred one dies mid-pull (the mover's own read
        failover).  -> ship_part's outcome; "gone" is returned only
        from the part's OWN holder (other holders have differently-
        named parts for the same keys)."""
        liaison = self.liaison
        last: Optional[TransportError] = None
        tried = []
        for src in [holder] + [h for h in holders if h.name != holder.name]:
            if src.name not in liaison.alive:
                continue
            tried.append(src.name)
            try:
                return ship_part(
                    liaison.transport, src, dst, entry,
                    epoch=liaison.placement.epoch,
                )
            except TransportError as e:
                last = e
                kind = getattr(e, "kind", "error")
                if kind == "error":
                    liaison._mark_dead(src.name)
                continue
        raise TransportError(
            f"part {entry['part']} (shard {entry['shard']}) unshippable: "
            f"tried {tried}: {last}"
        )

    def status(self) -> dict:
        with self._state_lock:
            last = dict(self._last)
            active = self._active
        p = self.liaison.placement
        return {
            "epoch": p.epoch,
            "nodes": list(p.nodes),
            "replicas": p.replicas,
            "explicit_chains": len(p.chains),
            "dual_route_shards": sorted(self.liaison.dual_route_shards()),
            "active": active,
            "last_apply": last,
            "pending_topology": sorted(self.liaison.pending_topology or ()),
        }


class ReplicaRepairer:
    """Anti-entropy over the replica chains: re-ship parts a replica is
    missing so replication factor >= 2 self-heals after a node is
    restored from loss (docs/robustness.md "Elastic cluster")."""

    def __init__(self, liaison):
        self.liaison = liaison
        self._state_lock = threading.Lock()
        self.last: dict = {}

    def run_once(self) -> dict:
        from banyandb_tpu.obs.metrics import global_meter

        liaison = self.liaison
        meter = global_meter()
        stats = {"shards_checked": 0, "parts_shipped": 0, "parts_deduped": 0,
                 "errors": 0}
        widest = 0
        for g in liaison.registry.list_groups():
            widest = max(widest, g.resource_opts.shard_num)
        for shard in range(widest):
            chain = liaison.placement.chain(shard)
            members = [
                liaison.selector.node_by_name(nm)
                for nm in chain
                if nm in liaison.alive
                and liaison.selector.node_by_name(nm) is not None
            ]
            if len(members) < 2:
                continue  # nothing to compare against
            stats["shards_checked"] += 1
            manifests: dict[str, dict[str, dict]] = {}
            for node in members:
                try:
                    manifests[node.name], _skipped = shard_manifest(
                        liaison.transport, node, shard
                    )
                except TransportError:
                    stats["errors"] += 1
            if len(manifests) < 2:
                continue
            union: dict[str, tuple[NodeInfo, dict]] = {}
            for node in members:
                for key, entry in manifests.get(node.name, {}).items():
                    union.setdefault(key, (node, entry))
            for node in members:
                have = manifests.get(node.name)
                if have is None:
                    continue
                for key, (holder, entry) in union.items():
                    if key in have or holder.name == node.name:
                        continue
                    try:
                        outcome = ship_part(
                            liaison.transport, holder, node, entry,
                            epoch=liaison.placement.epoch,
                        )
                    except TransportError:
                        stats["errors"] += 1
                        continue
                    if outcome == "moved":
                        stats["parts_shipped"] += 1
                        meter.counter_add("repair_parts_shipped", 1.0)
                    elif outcome == "deduped":
                        stats["parts_deduped"] += 1
                    # "gone": merged away mid-repair — the next interval
                    # compares fresh manifests and ships the merged part
        stats["ts"] = time.time()
        with self._state_lock:
            self.last = stats
        return stats

    def status(self) -> dict:
        with self._state_lock:
            return dict(self.last)
