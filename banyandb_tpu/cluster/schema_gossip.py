"""Schema anti-entropy gossip.

Analog of the reference's gossip-backed schema distribution
(banyand/metadata/schema/schemaserver + pkg/schema/cache.go watch/sync):
the primary distribution path here is liaison push + hinted handoff, but
a node that missed pushes AND lost its spool would never converge.  The
gossiper closes that hole: each round it picks a random peer, exchanges
per-object content digests, and pulls objects it LACKS (absent keys —
the catch-up case).  Same-key content conflicts are never auto-resolved
(no comparable cross-node revision exists); they are surfaced in the
round report for the liaison to re-push authoritatively.
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Optional

from banyandb_tpu.cluster.rpc import TransportError

log = logging.getLogger("banyandb.schema-gossip")

TOPIC_SCHEMA_DIGEST = "schema-digest"
TOPIC_SCHEMA_PULL = "schema-pull"


def register_handlers(bus, registry) -> None:
    """Mount the gossip topics on a node's bus."""
    from banyandb_tpu.api import schema as schema_mod

    bus.subscribe(
        TOPIC_SCHEMA_DIGEST,
        lambda env: {
            "digests": registry.digests(),
            "tombstones": registry.tombstones(),
        },
    )

    def pull(env):
        item = registry.export_object(env["kind"], env["key"])
        if item is None:
            raise KeyError(f"{env['kind']} {env['key']} not found")
        return {"item": item}

    bus.subscribe(TOPIC_SCHEMA_PULL, pull)
    # needed to APPLY pulled objects locally
    assert schema_mod  # imported for _from_jsonable at apply time


class SchemaGossiper:
    def __init__(self, registry, transport, peers, *, interval_s: float = 30.0):
        """peers: list[NodeInfo] excluding self."""
        self.registry = registry
        self.transport = transport
        self.peers = list(peers)
        self.interval_s = interval_s
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.pulled = 0
        self.deleted = 0
        self.conflicts: set[tuple[str, str]] = set()  # standing conflicts
        # dedup so a standing conflict doesn't re-append every round

    def run_once(self, peer=None) -> dict:
        """One reconcile round against one (random) peer.
        -> {"pulled": [...], "conflicts": [...]}"""
        from banyandb_tpu.api import schema as schema_mod

        if peer is None:
            if not self.peers:
                return {"pulled": [], "deleted": [], "conflicts": []}
            peer = random.choice(self.peers)
        try:
            resp = self.transport.call(
                peer.addr, TOPIC_SCHEMA_DIGEST, {}, timeout=10
            )
            remote = resp["digests"]
            remote_tombs = resp.get("tombstones", {})
        except TransportError as e:
            log.debug("digest fetch from %s failed: %s", peer.name, e)
            return {"pulled": [], "deleted": [], "conflicts": []}
        local = self.registry.digests()
        local_tombs = self.registry.tombstones()
        pulled, deleted, conflicts = [], [], []
        # deletions first: a peer's tombstone beats our live copy OF THE
        # SAME CONTENT (the delete happened after we received it); a
        # differing local object is a newer create and survives
        for kind, graves in remote_tombs.items():
            for key, buried_hash in graves.items():
                if key in local.get(kind, {}):
                    if self.registry.apply_tombstone(kind, key, buried_hash):
                        deleted.append((kind, key))
        for kind, remote_keys in remote.items():
            local_keys = local.get(kind, {})
            graves = local_tombs.get(kind, {})
            for key, rhash in remote_keys.items():
                if graves.get(key) == rhash:
                    # exactly the content WE deleted; never resurrect it
                    # (a recreate has a different hash and pulls normally;
                    # an IDENTICAL recreate stays buried until the liaison
                    # re-pushes authoritatively — documented limitation)
                    continue
                lhash = local_keys.get(key)
                if lhash == rhash:
                    continue
                if lhash is not None:
                    # content conflict: no comparable revision — surface,
                    # never guess (the liaison re-push is authoritative)
                    conflicts.append((kind, key))
                    continue
                try:
                    item = self.transport.call(
                        peer.addr,
                        TOPIC_SCHEMA_PULL,
                        {"kind": kind, "key": key},
                        timeout=10,
                    )["item"]
                except (TransportError, KeyError):
                    continue
                cls = schema_mod._KINDS[kind]
                self.registry._put(kind, schema_mod._from_jsonable(cls, item))
                pulled.append((kind, key))
        self.pulled += len(pulled)
        self.deleted += len(deleted)
        new_conflicts = set(conflicts) - self.conflicts
        self.conflicts |= set(conflicts)
        if new_conflicts:
            log.warning(
                "schema gossip: %d NEW content conflicts with %s: %s",
                len(new_conflicts),
                peer.name,
                sorted(new_conflicts)[:5],
            )
        return {"pulled": pulled, "deleted": deleted, "conflicts": conflicts}

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.run_once()
                except Exception:  # noqa: BLE001 - gossip must survive
                    log.exception("gossip round failed")

        self._thread = threading.Thread(
            target=loop, daemon=True, name="schema-gossip"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
