"""Epoch-versioned shard placement (docs/robustness.md "Elastic cluster").

Replaces the implicit round-robin shard->node mapping with an EXPLICIT,
persisted, epoch-fenced placement map (the reference's liaison placement
layer grown a version number):

- ``PlacementMap`` — one epoch + the replica chain per shard.  A fresh
  map (``initial``) carries NO explicit chains: every shard resolves
  through the round-robin closed form over the sorted node ring, so a
  brand-new cluster places exactly like the historical
  ``RoundRobinSelector`` — byte-identical routing, now with an epoch.
  A rebalance plan (cluster/rebalance.py) materializes explicit chains
  and bumps the epoch at cutover.
- ``PlacementSelector`` — the liaison's routing view: an addr book (the
  discovered ``NodeInfo`` set, which may include joined nodes that own
  no shards yet) resolved against the placement's chains.  Drop-in for
  the old selector surface (``nodes`` / ``replicas`` / ``replica_set`` /
  ``primary``).
- ``StaleEpoch`` — the write fence.  Every write/scatter envelope
  carries ``placement_epoch``; data nodes remember the highest epoch
  they have seen (persisted) and REJECT writes stamped with an older
  one (wire ``kind="stale_epoch"``, retryable — the sender is healthy
  but holds a superseded map and must refresh before retrying).  A
  mover and a straggling liaison can therefore never double-apply a
  write across a cutover.

The epoch changes ONLY at an explicit rebalance cutover.  Membership
changes alone (discovery file edits, node joins/leaves) never move
shards — see ``Liaison.refresh_nodes``.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional, Sequence

from banyandb_tpu.cluster.node import NodeInfo


class StaleEpoch(RuntimeError):
    """A data node rejecting a write whose ``placement_epoch`` is older
    than the node's remembered epoch.  Classified ``kind="stale_epoch"``
    on the wire: the sender is HEALTHY but routing on a superseded
    placement map — it must refresh (re-read the persisted map / learn
    the new epoch) and retry, never spool-and-replay the fenced copy."""


@dataclass(frozen=True)
class PlacementMap:
    """Shard -> replica-ordered node-name chains, versioned by epoch.

    ``chains`` lists explicit chains for shards ``0..len(chains)-1``;
    shards beyond (a group created after the last rebalance with a
    larger shard_num) fall back to the round-robin closed form over
    ``nodes`` — the same formula ``RoundRobinSelector`` used, so the
    fallback is deterministic and identical across every holder of the
    same map."""

    epoch: int
    nodes: tuple[str, ...]  # sorted ring for the round-robin fallback
    replicas: int
    chains: tuple[tuple[str, ...], ...] = field(default=())

    @classmethod
    def initial(cls, node_names: Sequence[str], replicas: int) -> "PlacementMap":
        """Epoch-1 map with no explicit chains: placement equals the
        historical round-robin for every shard count."""
        return cls(
            epoch=1,
            nodes=tuple(sorted(node_names)),
            replicas=int(replicas),
            chains=(),
        )

    def chain(self, shard: int) -> tuple[str, ...]:
        if 0 <= shard < len(self.chains):
            return self.chains[shard]
        n = len(self.nodes)
        if n == 0:
            return ()
        count = min(self.replicas + 1, n)
        return tuple(self.nodes[(shard + r) % n] for r in range(count))

    def to_json(self) -> dict:
        return {
            "epoch": self.epoch,
            "nodes": list(self.nodes),
            "replicas": self.replicas,
            "chains": [list(c) for c in self.chains],
        }

    @classmethod
    def from_json(cls, d: dict) -> "PlacementMap":
        return cls(
            epoch=int(d["epoch"]),
            nodes=tuple(d.get("nodes", ())),
            replicas=int(d.get("replicas", 0)),
            chains=tuple(tuple(c) for c in d.get("chains", ())),
        )

    # -- persistence (the liaison's placement store) -------------------------
    def save(self, path: str | Path) -> None:
        from banyandb_tpu.utils import fs

        fs.atomic_write_json(Path(path), self.to_json())

    @classmethod
    def load(cls, path: str | Path) -> Optional["PlacementMap"]:
        try:
            return cls.from_json(json.loads(Path(path).read_text()))
        except (OSError, ValueError, KeyError):
            return None


class PlacementSelector:
    """Routing view = addr book (NodeInfo set) x placement chains.

    The addr book may be WIDER than the placement (a joined node is
    reachable for schema sync and part shipping before any rebalance
    hands it shards) or narrower (a departed node's chains keep naming
    it until a plan cuts over — its legs simply show as down).  Same
    read surface as the old ``RoundRobinSelector``."""

    def __init__(self, nodes: list[NodeInfo], placement: PlacementMap):
        self.nodes = sorted(nodes, key=lambda n: n.name)
        self.placement = placement
        self.replicas = placement.replicas
        self._by_name = {n.name: n for n in self.nodes}

    def node_by_name(self, name: str) -> Optional[NodeInfo]:
        return self._by_name.get(name)

    def replica_set(self, shard: int) -> list[NodeInfo]:
        if not self.nodes:
            raise RuntimeError("no data nodes registered")
        return [
            self._by_name[nm]
            for nm in self.placement.chain(shard)
            if nm in self._by_name
        ]

    def primary(self, shard: int, alive: "set[str] | None" = None) -> NodeInfo:
        """First alive node in the shard's replica chain (failover walk,
        same contract as RoundRobinSelector.primary)."""
        for node in self.replica_set(shard):
            if alive is None or node.name in alive:
                return node
        raise RuntimeError(f"no alive replica for shard {shard}")


class EpochRecord:
    """A data node's persisted highest-seen placement epoch — the write
    fence's memory.  Epochs only ratchet UP: adopting a higher epoch
    (from a cutover broadcast or any fenced envelope) persists it, so a
    restarted node keeps rejecting writes from before the last cutover
    it witnessed."""

    def __init__(self, path: str | Path):
        self._path = Path(path)
        self._lock = threading.Lock()
        try:
            self._epoch = int(
                json.loads(self._path.read_text()).get("epoch", 0)
            )
        except (OSError, ValueError):
            self._epoch = 0

    @property
    def epoch(self) -> int:
        return self._epoch

    def observe(self, epoch: int, *, source: str = "") -> None:
        """Ratchet toward `epoch`; raise StaleEpoch when the envelope is
        BEHIND the node's remembered epoch (the double-apply fence)."""
        epoch = int(epoch)
        with self._lock:
            cur = self._epoch
            if epoch > cur:
                self._epoch = epoch
                from banyandb_tpu.utils import fs

                try:
                    fs.atomic_write_json(self._path, {"epoch": epoch})
                except OSError:
                    # a full disk must not fail the write that carried
                    # the fresher epoch; the fence just loses restart
                    # durability until the next successful persist
                    pass
                from banyandb_tpu.obs.metrics import global_meter

                global_meter().gauge_set("placement_epoch", float(epoch))
                return
        if epoch < cur:
            from banyandb_tpu.obs.metrics import global_meter

            global_meter().counter_add(
                "stale_epoch_rejected", 1.0, {"site": source or "write"}
            )
            raise StaleEpoch(
                f"placement epoch {epoch} is stale (node at {cur}); "
                "refresh the placement map and retry"
            )
