"""Deterministic fault-injection plane (docs/robustness.md).

Generalizes the test-local chunked-sync injector (reference:
queue.go:230 ChunkedSyncFailureInjector) into a first-class, seedable,
schedule-driven plane covering four boundaries:

- ``rpc``   — the transport call surface (cluster/rpc.py): inject a hard
              error, an UNAVAILABLE-shaped failure, a shed rejection
              (ServerBusy semantics) or a fixed delay before dispatch;
- ``sync``  — the chunked-sync stream (cluster/chunked_sync.py): cut the
              stream mid-flight, truncate a chunk, corrupt chunk bytes
              after the checksum was computed;
- ``disk``  — spool/part disk I/O (cluster/wqueue.py seal,
              cluster/handoff.py spool): ENOSPC before the write, or a
              short write that leaves a truncated artifact behind;
- ``kill``  — harness-driven process kills: the plane carries the
              schedule (which node dies at which chaos cycle), the
              harness (scripts/chaos.py) performs the kill.
- ``partition`` — an ASYMMETRIC rpc blackhole between named node pairs
              (``partition=blackhole:src=n0:dst=n1`` drops every call
              n0 makes TO n1; the n1->n0 direction stays up).  The
              process's own identity comes from ``set_local_node``
              (servers set it from --name at boot); ``dst`` matches a
              node name against the dialed address via the LocalTransport
              ``local:<name>`` form or an explicit
              ``register_node_addr(name, addr)`` mapping.  A standing
              filter: it fires on every matching call (bounded only by
              ``count``/``after``), unlike the seeded one-shot sites.
- ``join`` / ``leave`` — membership-change schedules carried exactly
              like ``kill`` (``join=n3:at=2;leave=n0:at=3``): the
              harness reads them via ``kills_for_cycle(cycle,
              site="join")`` / ``events_for_cycle`` and performs the
              discovery edit + rebalance itself, so elastic-cluster
              moves are chaos-testable under the same determinism.

Spec grammar (``BYDB_FAULTS`` env var or an explicit ``configure()``):

    spec   := clause (";" clause)*
    clause := "seed=" INT
            | SITE "=" KIND (":" key "=" value)*

    BYDB_FAULTS="seed=42;rpc=delay:p=0.2:ms=50;rpc=error:every=7;
                 sync=corrupt:every=3:count=2;disk=enospc:after=1:count=1;
                 kill=n0:at=1;kill=n1:at=2"

Per-rule keys: ``p`` (fire with probability p), ``every`` (fire each
Nth decision at the site), ``after`` (skip the first N decisions),
``count`` (fire at most N times), ``ms`` (delay duration, rpc=delay),
``match`` (substring filter on the decision detail, e.g. a topic name),
``at`` (kill: the chaos cycle index the kill belongs to).

Determinism contract (pinned by tests/test_faults.py): every site owns
a decision counter and a dedicated ``random.Random`` seeded from
``(seed, site)``.  Each decision draws exactly one uniform per
probabilistic rule of that site — in clause order, whether or not the
rule fires — so the decision-index -> fault mapping is a pure function
of (seed, schedule).  A fault's history entry records ``(site,
decision_seq, kind)``; replaying the same schedule against the same
decision sequence reproduces the same faults.  Which *request* lands on
which decision index depends on thread interleaving; the per-site fault
sequence does not.

Every fired fault also bumps ``fault_injected_total{site,kind}`` on the
process-global meter, so chaos artifacts can assert the schedule
actually ran.
"""

from __future__ import annotations

import errno
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

# fault kinds understood per boundary (free-form sites are allowed; the
# hooks below only act on the kinds they know)
RPC_KINDS = ("error", "unavailable", "shed", "delay")
SYNC_KINDS = ("cut", "truncate", "corrupt")
DISK_KINDS = ("enospc", "short")
PARTITION_KINDS = ("blackhole",)

# -- node identity (the partition site's "who am I" + addr book) -------------
# Process-global by design: production runs one node per process, and
# the harness/tests set the identity explicitly per scenario.
_LOCAL_NODE = ""
_NODE_ADDRS: dict[str, set[str]] = {}
_IDENT_LOCK = threading.Lock()


def set_local_node(name: str) -> None:
    """Declare this process's node identity for the ``partition`` site
    (servers call it with --name at boot; "" clears)."""
    global _LOCAL_NODE
    _LOCAL_NODE = name or ""


def local_node() -> str:
    return _LOCAL_NODE


def register_node_addr(name: str, addr: str) -> None:
    """Teach the partition matcher a node's transport address (the
    LocalTransport ``local:<name>`` form needs no registration)."""
    with _IDENT_LOCK:
        _NODE_ADDRS.setdefault(name, set()).add(addr)


def clear_node_addrs() -> None:
    with _IDENT_LOCK:
        _NODE_ADDRS.clear()


def _addr_is_node(name: str, addr: str) -> bool:
    if addr == name or addr == f"local:{name}":
        return True
    with _IDENT_LOCK:
        return addr in _NODE_ADDRS.get(name, ())


class DeadlineExceeded(RuntimeError):
    """A data node rejecting work whose liaison-propagated deadline is
    already exhausted.  Classified as kind="deadline" on the wire (the
    node is healthy — the query was simply too late), so the liaison
    degrades the response instead of evicting the node."""


@dataclass(frozen=True)
class FaultAction:
    """One decided fault: where, what, and the reproducible index."""

    site: str
    kind: str
    seq: int  # the site's decision index that produced this fault
    params: dict = field(default_factory=dict)


class _Rule:
    __slots__ = ("site", "kind", "p", "every", "after", "count", "params",
                 "fired")

    def __init__(self, site: str, kind: str, params: dict):
        self.site = site
        self.kind = kind
        self.p = float(params["p"]) if "p" in params else None
        self.every = int(params["every"]) if "every" in params else None
        self.after = int(params.get("after", 0))
        self.count = int(params["count"]) if "count" in params else None
        self.params = params
        self.fired = 0

    def spec(self) -> str:
        extra = ":".join(f"{k}={v}" for k, v in sorted(self.params.items()))
        return f"{self.site}={self.kind}" + (f":{extra}" if extra else "")


def _parse(spec: str) -> tuple[int, list[_Rule]]:
    seed = 0
    rules: list[_Rule] = []
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        head, _, tail = clause.partition(":")
        site, _, kind = head.partition("=")
        site, kind = site.strip(), kind.strip()
        if site == "seed":
            seed = int(kind)
            continue
        if not site or not kind:
            raise ValueError(f"bad BYDB_FAULTS clause {clause!r}")
        params: dict = {}
        if tail:
            for kv in tail.split(":"):
                k, _, v = kv.partition("=")
                if not k or not v:
                    raise ValueError(
                        f"bad BYDB_FAULTS param {kv!r} in {clause!r}"
                    )
                params[k.strip()] = v.strip()
        rules.append(_Rule(site, kind, params))
    return seed, rules


class FaultPlane:
    """Seeded decision engine over the parsed schedule.

    ``decide(site, detail)`` is the one entry point every boundary hook
    funnels through; it returns the fault to inject (or None) and
    advances that site's decision counter.
    """

    HISTORY_CAP = 4096

    def __init__(self, spec: str = ""):
        import random

        self.spec = spec
        self.seed, self._rules = _parse(spec)
        self._by_site: dict[str, list[_Rule]] = {}
        for r in self._rules:
            self._by_site.setdefault(r.site, []).append(r)
        self._counters: dict[str, int] = {}
        self._rngs: dict[str, object] = {
            site: random.Random(f"{self.seed}/{site}")
            for site in self._by_site
        }
        self.history: list[tuple[str, int, str]] = []
        self._lock = threading.Lock()

    # -- core ---------------------------------------------------------------
    def decide(self, site: str, detail: str = "") -> Optional[FaultAction]:
        """Advance `site`'s decision counter and return the fault the
        schedule assigns to this decision index, if any."""
        rules = self._by_site.get(site)
        if not rules:
            return None
        with self._lock:
            n = self._counters.get(site, 0)
            self._counters[site] = n + 1
            rng = self._rngs[site]
            hit: Optional[_Rule] = None
            for rule in rules:
                # one uniform per probabilistic rule per decision, drawn
                # unconditionally: the draw stream stays aligned with the
                # decision index whatever fires or filters
                draw = rng.random() if rule.p is not None else None
                if hit is not None:
                    continue
                if n < rule.after:
                    continue
                if rule.count is not None and rule.fired >= rule.count:
                    continue
                if rule.every is not None and (n - rule.after) % rule.every:
                    continue
                if draw is not None and draw >= rule.p:
                    continue
                if rule.params.get("match") and rule.params["match"] not in detail:
                    continue
                hit = rule
            if hit is None:
                return None
            hit.fired += 1
            if len(self.history) < self.HISTORY_CAP:
                self.history.append((site, n, hit.kind))
        from banyandb_tpu.obs.metrics import global_meter

        global_meter().counter_add(
            "fault_injected", 1.0, {"site": site, "kind": hit.kind}
        )
        return FaultAction(site, hit.kind, n, dict(hit.params))

    def counters(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counters)

    # -- boundary hooks -----------------------------------------------------
    def check_partition(self, local: str, addr: str, topic: str) -> None:
        """partition boundary: drop the call when an active rule names
        (local -> addr's node) — BEFORE the rpc site draws, so a
        blackholed call never consumes an rpc decision.  Asymmetric by
        construction: only the src->dst direction ever matches."""
        rules = self._by_site.get("partition")
        if not rules:
            return
        hit: Optional[_Rule] = None
        with self._lock:
            n = self._counters.get("partition", 0)
            for rule in rules:
                src = rule.params.get("src", "")
                dst = rule.params.get("dst", "")
                if src and src != local:
                    continue
                if dst and not _addr_is_node(dst, addr):
                    continue
                if rule.count is not None and rule.fired >= rule.count:
                    continue
                if n < rule.after:
                    continue
                hit = rule
                break
            # the decision counter advances only on MATCHING pairs: an
            # un-partitioned peer's traffic never perturbs the site
            if hit is not None:
                self._counters["partition"] = n + 1
                hit.fired += 1
                if len(self.history) < self.HISTORY_CAP:
                    self.history.append(("partition", n, hit.kind))
        if hit is None:
            return
        from banyandb_tpu.cluster.rpc import TransportError

        from banyandb_tpu.obs.metrics import global_meter

        global_meter().counter_add(
            "fault_injected", 1.0, {"site": "partition", "kind": hit.kind}
        )
        raise TransportError(
            f"rpc to {addr} blackholed: partition {local or '?'}->"
            f"{hit.params.get('dst', addr)} "
            f"[fault site=partition kind={hit.kind}]"
        )

    def fail_rpc(self, addr: str, topic: str) -> None:
        """rpc boundary: raise/delay per the schedule, before dispatch."""
        self.check_partition(_LOCAL_NODE, addr, topic)
        act = self.decide("rpc", topic)
        if act is None:
            return
        from banyandb_tpu.cluster.rpc import TransportError

        tag = f"[fault site=rpc seq={act.seq} kind={act.kind}]"
        if act.kind == "delay":
            time.sleep(float(act.params.get("ms", 50.0)) / 1000.0)
            return
        if act.kind == "shed":
            raise TransportError(
                f"ServerBusy: injected shed for {addr}/{topic} {tag}",
                kind="shed",
            )
        # "error" and "unavailable" both surface as hard transport
        # failures (the gRPC path maps UNAVAILABLE into the same class)
        raise TransportError(f"rpc to {addr} failed: injected {tag}")

    def check_disk(self, where: str) -> Optional[str]:
        """disk boundary: raise ENOSPC, or return "short" when the caller
        must simulate a torn write (write partial bytes, then raise)."""
        act = self.decide("disk", where)
        if act is None:
            return None
        if act.kind == "short":
            return "short"
        raise OSError(
            errno.ENOSPC,
            f"injected ENOSPC at {where} [fault site=disk seq={act.seq}]",
        )

    def sync_injector(self):
        """sync boundary: a chunked_sync-shaped injector driven by this
        plane (duck-typed: before_sync + mutate_request), or None when
        the schedule names no sync faults."""
        if "sync" not in self._by_site:
            return None
        return _PlaneSyncInjector(self)

    def kills_for_cycle(self, cycle: int, site: str = "kill") -> list[str]:
        """Node names the schedule kills at this chaos cycle
        (site=<site>, kind=<node>, at=<cycle>).  Consumed by the
        harness; the plane never kills anything itself.  ``site``
        selects the kill plane: ``kill`` = cluster data nodes,
        ``worker`` = shard-owning worker processes of the multi-process
        data plane (cluster/workers.py)."""
        out = []
        for rule in self._by_site.get(site, ()):
            if int(rule.params.get("at", 0)) == cycle:
                out.append(rule.kind)
        return out

    def events_for_cycle(
        self,
        cycle: int,
        sites: tuple[str, ...] = ("kill", "worker", "join", "leave"),
    ) -> dict[str, list[str]]:
        """Every scheduled membership/kill event for one chaos cycle:
        {site: [node, ...]}.  ``join``/``leave`` ride the same
        ``<site>=<node>:at=<cycle>`` grammar as kills — the harness
        performs the discovery edit and the rebalance plan/apply, the
        plane only carries the schedule (docs/robustness.md "Elastic
        cluster")."""
        return {site: self.kills_for_cycle(cycle, site=site) for site in sites}


class _PlaneSyncInjector:
    """Chunked-sync injector driven by the plane's sync schedule: one
    decision per outgoing chunk."""

    def __init__(self, plane: FaultPlane):
        self._plane = plane

    def before_sync(self, part_dirs):  # noqa: ARG002 - injector contract
        return (False, "")

    def mutate_request(self, req):
        act = self._plane.decide("sync", f"chunk:{req.chunk_index}")
        if act is None:
            return req
        tag = f"[fault site=sync seq={act.seq} kind={act.kind}]"
        if act.kind == "cut":
            from banyandb_tpu.cluster.rpc import TransportError

            raise TransportError(f"sync stream cut mid-flight {tag}")
        if req.chunk_data:
            if act.kind == "truncate":
                # drop the tail AFTER the checksum was computed: the
                # receiver's CRC catches the torn chunk
                req.chunk_data = req.chunk_data[: len(req.chunk_data) // 2]
            elif act.kind == "corrupt":
                req.chunk_data = (
                    bytes([req.chunk_data[0] ^ 0xFF]) + req.chunk_data[1:]
                )
        return req


# -- process-global plane ----------------------------------------------------
# One plane per process, parsed from BYDB_FAULTS at first use (or set
# explicitly by tests/harnesses via configure()).  `_ACTIVE` keeps the
# fault-free hot path to one module-global read.

_PLANE: Optional[FaultPlane] = None
_ACTIVE = False
_INIT = False
_GLOBAL_LOCK = threading.Lock()


def get_plane() -> Optional[FaultPlane]:
    global _PLANE, _ACTIVE, _INIT
    if not _INIT:
        with _GLOBAL_LOCK:
            if not _INIT:
                from banyandb_tpu.utils.envflag import env_str

                spec = env_str("BYDB_FAULTS").strip()
                _PLANE = FaultPlane(spec) if spec else None
                _ACTIVE = _PLANE is not None
                _INIT = True
    return _PLANE


def configure(spec: str) -> FaultPlane:
    """Install a fresh plane for `spec` (tests/harnesses); "" clears."""
    global _PLANE, _ACTIVE, _INIT
    with _GLOBAL_LOCK:
        _PLANE = FaultPlane(spec) if spec else None
        _ACTIVE = _PLANE is not None
        _INIT = True
    return _PLANE  # type: ignore[return-value]


def clear() -> None:
    configure("")


def active() -> bool:
    if not _INIT:
        get_plane()
    return _ACTIVE


def maybe_fail_rpc(addr: str, topic: str) -> None:
    """Transport hook: no-op unless a plane with rpc rules is active."""
    if _ACTIVE or not _INIT:
        plane = get_plane()
        if plane is not None:
            plane.fail_rpc(addr, topic)


def check_disk(where: str) -> Optional[str]:
    """Disk hook: None (proceed), "short" (caller tears the write), or
    raises OSError(ENOSPC)."""
    if _ACTIVE or not _INIT:
        plane = get_plane()
        if plane is not None:
            return plane.check_disk(where)
    return None


def plane_sync_injector():
    if _ACTIVE or not _INIT:
        plane = get_plane()
        if plane is not None:
            return plane.sync_injector()
    return None
