"""Liaison-side write queue: buffer -> sealed parts -> chunked sync.

Analog of the reference's wqueue architecture
(banyand/internal/wqueue/wqueue.go:75 + banyand/measure/syncer.go:69):
instead of fanning every row batch out synchronously, the liaison
buffers writes per (group, measure, shard) in columnar memtables, seals
them into real on-disk parts when a row threshold or flush interval
hits, and ships sealed parts to the shard's data node over the
streaming ChunkedSyncService (cluster/chunked_sync.py).  Data nodes
introduce shipped parts directly — the write path and the inter-tier
sync path are the same code.

Failure contract: a sealed part that fails to ship stays spooled on
disk and retries with bounded exponential backoff + jitter (the spool
is the liaison's handoff buffer for the part plane); seal+ship never
loses acknowledged rows — rows are acknowledged only after landing in
the spool-backed memtable of a seal group, and a liaison crash loses at
most the unsealed buffer (same window as the reference's liaison
wqueue).  The spool is bounded by BACKPRESSURE, not eviction: past the
high watermark (``max_spool_bytes``) new appends raise ServerBusy — a
retryable shed rejection on the wire (the reference's ServerBusy,
pub.go:301-387) — instead of buffering unboundedly while data nodes
are down.
"""

from __future__ import annotations

import random
import shutil
import threading
import time
import uuid
from pathlib import Path
from typing import Callable, Optional

from banyandb_tpu.api.model import WriteRequest
from banyandb_tpu.api.schema import SchemaRegistry
from banyandb_tpu.cluster import faults
from banyandb_tpu.storage.memtable import MemTable
from banyandb_tpu.storage.part import PartWriter
from banyandb_tpu.utils import hashing


def _dir_bytes(path: Path) -> int:
    total = 0
    try:
        for f in path.rglob("*"):
            if f.is_file():
                total += f.stat().st_size
    except OSError:
        pass
    return total


class WriteQueue:
    def __init__(
        self,
        registry: SchemaRegistry,
        spool_root: str | Path,
        shipper: Callable[[str, int, Path], None],
        *,
        max_rows: int = 65536,
        flush_interval_s: float = 1.0,
        max_spool_bytes: int = 256 << 20,
        retry_base_s: float = 0.05,
        retry_cap_s: float = 30.0,
    ):
        """shipper(group, shard_id, part_dir) ships one sealed part;
        raises on failure (the part stays spooled and retries with
        exponential backoff capped at ``retry_cap_s``)."""
        self.registry = registry
        self.spool = Path(spool_root)
        self.spool.mkdir(parents=True, exist_ok=True)
        self.shipper = shipper
        self.max_rows = max_rows
        self.flush_interval_s = flush_interval_s
        self.max_spool_bytes = max_spool_bytes
        self.retry_base_s = retry_base_s
        self.retry_cap_s = retry_cap_s
        # key: (catalog, group, resource, shard)
        self._buffers: dict[tuple[str, str, str, int], MemTable] = {}
        self._lock = threading.Lock()
        # ordered-tag sets per trace buffer (ride in sealed part meta)
        self._trace_meta: dict[tuple, tuple[str, ...]] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # per-part retry state: str(part_dir) -> (attempts, next_try
        # monotonic); jitter decorrelates a fleet of liaisons hammering
        # one recovering data node
        self._retry: dict[str, tuple[int, float]] = {}
        self._jitter = random.Random(0xBDB)
        # orphaned sealed parts from a previous process retry first
        self._pending: list[tuple[str, int, Path]] = self._recover_spool()
        # per-part byte sizes, measured ONCE (at seal/recovery) and
        # reused when the ship frees them
        self._part_bytes: dict[str, int] = {
            str(p): _dir_bytes(p.parent) for _g, _s, p in self._pending
        }
        self._spool_bytes = sum(self._part_bytes.values())

    # -- admission (spool high-watermark backpressure) ----------------------
    def _admit(self) -> None:
        """Reject new rows while the ship spool is past its high
        watermark: the caller gets a RETRYABLE shed rejection (ServerBusy
        serializes as kind="shed" on the transport, so clients back off
        and retry instead of treating the liaison as dead), and already-
        acked rows keep their bounded, eventually-shipped spool."""
        with self._lock:
            over = self._spool_bytes > self.max_spool_bytes
            spooled = self._spool_bytes
        if over:
            from banyandb_tpu.admin.protector import ServerBusy
            from banyandb_tpu.obs.metrics import global_meter

            global_meter().counter_add("wqueue_shed", 1.0)
            raise ServerBusy(
                f"write queue spool over high watermark "
                f"({spooled} > {self.max_spool_bytes} bytes); retry later"
            )

    # -- append path --------------------------------------------------------
    def append(self, req: WriteRequest) -> int:
        """Route points into per-(group, measure, shard) buffers; returns
        the accepted count.  Same shard routing as the synchronous path
        (entity hash -> seriesID -> shard).  The queue lock is held for
        the whole batch so a concurrent seal can never orphan a buffer
        between lookup and append (acknowledged rows must reach a seal)."""
        self._admit()
        m = self.registry.get_measure(req.group, req.name)
        shard_num = self.registry.get_group(req.group).resource_opts.shard_num
        tag_names = [t.name for t in m.tags]
        field_names = [f.name for f in m.fields]
        full = set()
        with self._lock:
            for p in req.points:
                entity = [req.name.encode()] + [
                    hashing.entity_bytes(p.tags[t]) for t in m.entity.tag_names
                ]
                sid = hashing.series_id(entity)
                shard = hashing.shard_id(sid, shard_num)
                key = ("measure", req.group, req.name, shard)
                buf = self._buffers.get(key)
                if buf is None:
                    buf = self._buffers[key] = MemTable(tag_names, field_names)
                tag_bytes = {
                    t: hashing.entity_bytes(p.tags[t])
                    if p.tags.get(t) is not None
                    else b""
                    for t in tag_names
                }
                fields = {f: float(p.fields.get(f, 0)) for f in field_names}
                version = p.version or int(time.time() * 1000)
                buf.append(p.ts_millis, sid, version, tag_bytes, fields)
                if len(buf) >= self.max_rows:
                    full.add(key)
        for key in full:
            self._seal(key)
        return len(req.points)

    def append_stream(self, group: str, name: str, elements) -> int:
        """Stream twin of append(): elements (models.stream.ElementValue)
        buffer per (group, stream, shard) with the element-id+body
        payload column, sealing into stream parts the data node
        introduces identically to its own flushes."""
        from banyandb_tpu.models.stream import encode_element_payload

        self._admit()
        st = self.registry.get_stream(group, name)
        shard_num = self.registry.get_group(group).resource_opts.shard_num
        tag_names = [t.name for t in st.tags]
        full = set()
        with self._lock:
            for e in elements:
                entity = [name.encode()] + [
                    hashing.entity_bytes(e.tags[t]) for t in st.entity
                ]
                sid = hashing.series_id(entity)
                shard = hashing.shard_id(sid, shard_num)
                key = ("stream", group, name, shard)
                buf = self._buffers.get(key)
                if buf is None:
                    buf = self._buffers[key] = MemTable(
                        tag_names, [], with_payload=True
                    )
                tag_bytes = {
                    t: hashing.entity_bytes(e.tags[t])
                    if e.tags.get(t) is not None
                    else b""
                    for t in tag_names
                }
                buf.append(
                    e.ts_millis,
                    sid,
                    0,
                    tag_bytes,
                    {},
                    payload=encode_element_payload(e.element_id, e.body),
                )
                if len(buf) >= self.max_rows:
                    full.add(key)
        for key in full:
            self._seal(key)
        return len(elements)

    def append_trace(self, group: str, name: str, spans, ordered_tags=()) -> int:
        """Trace twin of append(): spans (models.trace.SpanValue) buffer
        per (group, trace, shard) — trace routing hashes the TRACE ID
        (partition.TraceShardID), not the series — with the opaque span
        payload.  ordered_tags ride in part meta so the data node can
        rebuild sidx entries on install."""
        from banyandb_tpu.models.trace import trace_shard_id

        self._admit()
        t = self.registry.get_trace(group, name)
        shard_num = self.registry.get_group(group).resource_opts.shard_num
        tag_names = [x.name for x in t.tags]
        full = set()
        with self._lock:
            for sp in spans:
                trace_id = str(sp.tags[t.trace_id_tag])
                sid = hashing.series_id([name.encode(), trace_id.encode()])
                shard = trace_shard_id(trace_id, shard_num)
                key = ("trace", group, name, shard)
                buf = self._buffers.get(key)
                if buf is None:
                    buf = self._buffers[key] = MemTable(
                        tag_names, [], with_payload=True
                    )
                # union across calls: a later batch naming MORE ordered
                # tags must not be silently ignored for this buffer
                prev = self._trace_meta.get(key, ())
                self._trace_meta[key] = tuple(
                    dict.fromkeys((*prev, *ordered_tags))
                )
                tag_bytes = {
                    x: hashing.entity_bytes(sp.tags[x])
                    if sp.tags.get(x) is not None
                    else b""
                    for x in tag_names
                }
                buf.append(sp.ts_millis, sid, 0, tag_bytes, {}, payload=sp.span)
                if len(buf) >= self.max_rows:
                    full.add(key)
        for key in full:
            self._seal(key)
        return len(spans)

    # -- seal + ship --------------------------------------------------------
    def _seal(self, key: tuple[str, str, str, int]) -> None:
        """Swap the buffer out and write its rows as sealed parts in the
        spool — one part per storage segment (rows spanning a segment
        boundary must not land in one part: the receiver installs a part
        into a single segment, and rows outside it would be invisible to
        time-pruned queries).  On write failure the buffer is restored so
        acknowledged rows are never dropped."""
        catalog, group, resource, shard = key
        with self._lock:
            buf = self._buffers.pop(key, None)
        if buf is None or len(buf) == 0:
            return
        tmp_parents: list[Path] = []
        sealed: list[tuple[str, int, Path]] = []
        try:
            # disk-fault boundary (cluster/faults.py): ENOSPC raises here
            # (rows restored below); a "short" decision tears the first
            # staged write so the cleanup path is exercised too
            torn = faults.check_disk("wqueue-seal")
            cols = buf.snapshot_columns()
            iv = self.registry.get_group(group).resource_opts.segment_interval.millis
            seg_starts = cols.ts - (cols.ts % iv)
            import numpy as np

            # All segment-split parts are written under .tmp dirs first and
            # renamed only after EVERY one succeeds — a mid-seal failure
            # must not leave a recoverable orphan part while the same rows
            # are also restored to the buffer (double delivery).
            staged: list[tuple[Path, Path]] = []
            for start in np.unique(seg_starts).tolist():
                mask = seg_starts == start
                session = uuid.uuid4().hex
                final_parent = self.spool / f"{group}@{resource}@{shard}@{session}"
                tmp_parent = self.spool / f".tmp-{session}"
                tmp_parents.append(tmp_parent)
                payloads = None
                if cols.payloads is not None:
                    payloads = [p for p, k in zip(cols.payloads, mask) if k]
                extra_meta = {
                    catalog: resource,
                    "group": group,
                    "catalog": catalog,
                    # unique per seal: receiver-side dedup must distinguish
                    # re-delivery of THIS part from an independent later
                    # seal of byte-identical content (client retry batch)
                    "seal_session": session,
                    # row count stamped for the receiver's ingest-side
                    # consumers (the streamagg install hook short-
                    # circuits empty parts on it without a part read)
                    "rows": int(np.count_nonzero(mask)),
                }
                if catalog == "trace":
                    extra_meta["ordered_tags"] = list(
                        self._trace_meta.get(key, ())
                    )
                if torn:
                    import errno as _errno

                    tmp_parent.mkdir(parents=True, exist_ok=True)
                    (tmp_parent / "part-000000.torn").write_bytes(b"\0" * 8)
                    raise OSError(
                        _errno.EIO, "injected short write at wqueue seal"
                    )
                PartWriter.write(
                    tmp_parent / "part-000000",
                    ts=cols.ts[mask],
                    series=cols.series[mask],
                    version=cols.version[mask],
                    tag_codes={t: v[mask] for t, v in cols.tags.items()},
                    tag_dicts=dict(cols.dicts),
                    fields={f: v[mask] for f, v in cols.fields.items()},
                    extra_meta=extra_meta,
                    payloads=payloads,
                )
                staged.append((tmp_parent, final_parent))
            for tmp_parent, final_parent in staged:
                tmp_parent.rename(final_parent)
                sealed.append((group, shard, final_parent / "part-000000"))
            sizes = {
                str(p): _dir_bytes(p.parent) for _g, _s, p in sealed
            }
            with self._lock:
                self._pending.extend(sealed)
                self._part_bytes.update(sizes)
                self._spool_bytes += sum(sizes.values())
            from banyandb_tpu.obs.metrics import global_meter

            global_meter().counter_add(
                "wqueue_sealed_rows", float(len(buf))
            )
        except Exception:
            # undo everything (renamed-but-unregistered parts included):
            # the restored rows below are the single surviving copy
            for tmp_parent in tmp_parents:
                shutil.rmtree(tmp_parent, ignore_errors=True)
            for _g, _s, part_dir in sealed:
                shutil.rmtree(part_dir.parent, ignore_errors=True)
            # restore the rows: seal again next tick (merge into any new
            # buffer created meanwhile)
            with self._lock:
                cur = self._buffers.get(key)
                if cur is None or len(cur) == 0:
                    self._buffers[key] = buf
                else:
                    snap = buf.snapshot_columns()
                    cur.append_bulk(
                        snap.ts,
                        snap.series,
                        snap.version,
                        {
                            t: [snap.dicts[t][c] for c in snap.tags[t]]
                            for t in snap.tags
                        },
                        dict(snap.fields),
                        payloads=snap.payloads,
                    )
            raise

    def seal_all(self) -> None:
        with self._lock:
            keys = list(self._buffers.keys())
        errors = []
        for key in keys:
            try:
                self._seal(key)
            except Exception as e:  # noqa: BLE001 - other keys still seal
                errors.append(e)
        if errors:
            raise errors[0]

    def ship_pending(self, *, force: bool = False) -> tuple[int, int]:
        """Try to ship every sealed part that is DUE; -> (shipped,
        failed).  A part whose last attempt failed waits out its
        exponential backoff (base * 2^attempts, capped, +25% jitter)
        before the next try — deferred parts count as neither shipped
        nor failed.  ``force=True`` ignores the backoff clock (final
        flush at stop, post-recovery drains)."""
        from banyandb_tpu.obs.metrics import global_meter

        now = time.monotonic()
        with self._lock:
            pending, self._pending = self._pending, []
        shipped = failed = 0
        still: list[tuple[str, int, Path]] = []
        for group, shard, part_dir in pending:
            key = str(part_dir)
            attempts, next_try = self._retry.get(key, (0, 0.0))
            if not force and now < next_try:
                still.append((group, shard, part_dir))  # not due yet
                continue
            try:
                self.shipper(group, shard, part_dir)
                shutil.rmtree(part_dir.parent, ignore_errors=True)
                shipped += 1
                with self._lock:
                    self._retry.pop(key, None)
                    freed = self._part_bytes.pop(key, 0)
                    self._spool_bytes = max(0, self._spool_bytes - freed)
                global_meter().counter_add("wqueue_shipped", 1.0)
            except Exception:  # noqa: BLE001 - retried after backoff
                attempts += 1
                delay = min(
                    self.retry_cap_s,
                    self.retry_base_s * (2 ** (attempts - 1)),
                )
                delay *= 1.0 + 0.25 * self._jitter.random()
                with self._lock:
                    self._retry[key] = (attempts, time.monotonic() + delay)
                still.append((group, shard, part_dir))
                failed += 1
                global_meter().counter_add("wqueue_ship_retry", 1.0)
        with self._lock:
            self._pending.extend(still)
            global_meter().gauge_set("wqueue_spool_bytes", self._spool_bytes)
        return shipped, failed

    def flush(self, *, force: bool = False) -> tuple[int, int]:
        """Seal everything and attempt shipping (one tick, also the test
        hook)."""
        self.seal_all()
        return self.ship_pending(force=force)

    def pending_parts(self) -> int:
        with self._lock:
            return len(self._pending)

    def buffered_rows(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._buffers.values())

    def spool_bytes(self) -> int:
        with self._lock:
            return self._spool_bytes

    # -- lifecycle ----------------------------------------------------------
    def _recover_spool(self) -> list[tuple[str, int, Path]]:
        out = []
        for d in sorted(self.spool.iterdir()) if self.spool.exists() else []:
            if d.is_dir() and d.name.startswith(".tmp"):
                # crashed mid-seal: rows never left the (lost) buffer OR
                # were restored and resealed — either way this is garbage
                shutil.rmtree(d, ignore_errors=True)
                continue
            if not d.is_dir() or "@" not in d.name:
                continue
            try:
                group, _measure, shard, _session = d.name.split("@", 3)
                part_dir = d / "part-000000"
                if (part_dir / "metadata.json").exists():
                    out.append((group, int(shard), part_dir))
                else:  # crashed mid-write: the part is not durable yet
                    shutil.rmtree(d, ignore_errors=True)
            except (ValueError, OSError):
                continue
        return out

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        import logging

        log = logging.getLogger("banyandb.wqueue")

        def loop():
            while not self._stop.wait(self.flush_interval_s):
                try:
                    self.flush()
                except Exception:  # noqa: BLE001 - the loop must survive
                    log.exception("wqueue flush tick failed (rows retained)")

        self._thread = threading.Thread(target=loop, daemon=True, name="wqueue")
        self._thread.start()

    def stop(self, final_flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final_flush:
            # the last chance to drain before shutdown ignores backoff
            self.flush(force=True)
