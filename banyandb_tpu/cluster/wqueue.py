"""Liaison-side write queue: buffer -> sealed parts -> chunked sync.

Analog of the reference's wqueue architecture
(banyand/internal/wqueue/wqueue.go:75 + banyand/measure/syncer.go:69):
instead of fanning every row batch out synchronously, the liaison
buffers writes per (group, measure, shard) in columnar memtables, seals
them into real on-disk parts when a row threshold or flush interval
hits, and ships sealed parts to the shard's data node over the
streaming ChunkedSyncService (cluster/chunked_sync.py).  Data nodes
introduce shipped parts directly — the write path and the inter-tier
sync path are the same code.

Failure contract: a sealed part that fails to ship stays spooled on
disk and retries on the next tick (the spool is the liaison's handoff
buffer for the part plane); seal+ship never loses acknowledged rows —
rows are acknowledged only after landing in the spool-backed memtable
of a seal group, and a liaison crash loses at most the unsealed buffer
(same window as the reference's liaison wqueue).
"""

from __future__ import annotations

import shutil
import threading
import time
import uuid
from pathlib import Path
from typing import Callable, Optional

from banyandb_tpu.api.model import WriteRequest
from banyandb_tpu.api.schema import SchemaRegistry
from banyandb_tpu.storage.memtable import MemTable
from banyandb_tpu.storage.part import PartWriter
from banyandb_tpu.utils import hashing


class WriteQueue:
    def __init__(
        self,
        registry: SchemaRegistry,
        spool_root: str | Path,
        shipper: Callable[[str, int, Path], None],
        *,
        max_rows: int = 65536,
        flush_interval_s: float = 1.0,
    ):
        """shipper(group, shard_id, part_dir) ships one sealed part;
        raises on failure (the part stays spooled and retries)."""
        self.registry = registry
        self.spool = Path(spool_root)
        self.spool.mkdir(parents=True, exist_ok=True)
        self.shipper = shipper
        self.max_rows = max_rows
        self.flush_interval_s = flush_interval_s
        # key: (catalog, group, resource, shard)
        self._buffers: dict[tuple[str, str, str, int], MemTable] = {}
        self._lock = threading.Lock()
        # ordered-tag sets per trace buffer (ride in sealed part meta)
        self._trace_meta: dict[tuple, tuple[str, ...]] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # orphaned sealed parts from a previous process retry first
        self._pending: list[tuple[str, int, Path]] = self._recover_spool()

    # -- append path --------------------------------------------------------
    def append(self, req: WriteRequest) -> int:
        """Route points into per-(group, measure, shard) buffers; returns
        the accepted count.  Same shard routing as the synchronous path
        (entity hash -> seriesID -> shard).  The queue lock is held for
        the whole batch so a concurrent seal can never orphan a buffer
        between lookup and append (acknowledged rows must reach a seal)."""
        m = self.registry.get_measure(req.group, req.name)
        shard_num = self.registry.get_group(req.group).resource_opts.shard_num
        tag_names = [t.name for t in m.tags]
        field_names = [f.name for f in m.fields]
        full = set()
        with self._lock:
            for p in req.points:
                entity = [req.name.encode()] + [
                    hashing.entity_bytes(p.tags[t]) for t in m.entity.tag_names
                ]
                sid = hashing.series_id(entity)
                shard = hashing.shard_id(sid, shard_num)
                key = ("measure", req.group, req.name, shard)
                buf = self._buffers.get(key)
                if buf is None:
                    buf = self._buffers[key] = MemTable(tag_names, field_names)
                tag_bytes = {
                    t: hashing.entity_bytes(p.tags[t])
                    if p.tags.get(t) is not None
                    else b""
                    for t in tag_names
                }
                fields = {f: float(p.fields.get(f, 0)) for f in field_names}
                version = p.version or int(time.time() * 1000)
                buf.append(p.ts_millis, sid, version, tag_bytes, fields)
                if len(buf) >= self.max_rows:
                    full.add(key)
        for key in full:
            self._seal(key)
        return len(req.points)

    def append_stream(self, group: str, name: str, elements) -> int:
        """Stream twin of append(): elements (models.stream.ElementValue)
        buffer per (group, stream, shard) with the element-id+body
        payload column, sealing into stream parts the data node
        introduces identically to its own flushes."""
        from banyandb_tpu.models.stream import encode_element_payload

        st = self.registry.get_stream(group, name)
        shard_num = self.registry.get_group(group).resource_opts.shard_num
        tag_names = [t.name for t in st.tags]
        full = set()
        with self._lock:
            for e in elements:
                entity = [name.encode()] + [
                    hashing.entity_bytes(e.tags[t]) for t in st.entity
                ]
                sid = hashing.series_id(entity)
                shard = hashing.shard_id(sid, shard_num)
                key = ("stream", group, name, shard)
                buf = self._buffers.get(key)
                if buf is None:
                    buf = self._buffers[key] = MemTable(
                        tag_names, [], with_payload=True
                    )
                tag_bytes = {
                    t: hashing.entity_bytes(e.tags[t])
                    if e.tags.get(t) is not None
                    else b""
                    for t in tag_names
                }
                buf.append(
                    e.ts_millis,
                    sid,
                    0,
                    tag_bytes,
                    {},
                    payload=encode_element_payload(e.element_id, e.body),
                )
                if len(buf) >= self.max_rows:
                    full.add(key)
        for key in full:
            self._seal(key)
        return len(elements)

    def append_trace(self, group: str, name: str, spans, ordered_tags=()) -> int:
        """Trace twin of append(): spans (models.trace.SpanValue) buffer
        per (group, trace, shard) — trace routing hashes the TRACE ID
        (partition.TraceShardID), not the series — with the opaque span
        payload.  ordered_tags ride in part meta so the data node can
        rebuild sidx entries on install."""
        from banyandb_tpu.models.trace import trace_shard_id

        t = self.registry.get_trace(group, name)
        shard_num = self.registry.get_group(group).resource_opts.shard_num
        tag_names = [x.name for x in t.tags]
        full = set()
        with self._lock:
            for sp in spans:
                trace_id = str(sp.tags[t.trace_id_tag])
                sid = hashing.series_id([name.encode(), trace_id.encode()])
                shard = trace_shard_id(trace_id, shard_num)
                key = ("trace", group, name, shard)
                buf = self._buffers.get(key)
                if buf is None:
                    buf = self._buffers[key] = MemTable(
                        tag_names, [], with_payload=True
                    )
                # union across calls: a later batch naming MORE ordered
                # tags must not be silently ignored for this buffer
                prev = self._trace_meta.get(key, ())
                self._trace_meta[key] = tuple(
                    dict.fromkeys((*prev, *ordered_tags))
                )
                tag_bytes = {
                    x: hashing.entity_bytes(sp.tags[x])
                    if sp.tags.get(x) is not None
                    else b""
                    for x in tag_names
                }
                buf.append(sp.ts_millis, sid, 0, tag_bytes, {}, payload=sp.span)
                if len(buf) >= self.max_rows:
                    full.add(key)
        for key in full:
            self._seal(key)
        return len(spans)

    # -- seal + ship --------------------------------------------------------
    def _seal(self, key: tuple[str, str, str, int]) -> None:
        """Swap the buffer out and write its rows as sealed parts in the
        spool — one part per storage segment (rows spanning a segment
        boundary must not land in one part: the receiver installs a part
        into a single segment, and rows outside it would be invisible to
        time-pruned queries).  On write failure the buffer is restored so
        acknowledged rows are never dropped."""
        catalog, group, resource, shard = key
        with self._lock:
            buf = self._buffers.pop(key, None)
        if buf is None or len(buf) == 0:
            return
        tmp_parents: list[Path] = []
        sealed: list[tuple[str, int, Path]] = []
        try:
            cols = buf.snapshot_columns()
            iv = self.registry.get_group(group).resource_opts.segment_interval.millis
            seg_starts = cols.ts - (cols.ts % iv)
            import numpy as np

            # All segment-split parts are written under .tmp dirs first and
            # renamed only after EVERY one succeeds — a mid-seal failure
            # must not leave a recoverable orphan part while the same rows
            # are also restored to the buffer (double delivery).
            staged: list[tuple[Path, Path]] = []
            for start in np.unique(seg_starts).tolist():
                mask = seg_starts == start
                session = uuid.uuid4().hex
                final_parent = self.spool / f"{group}@{resource}@{shard}@{session}"
                tmp_parent = self.spool / f".tmp-{session}"
                tmp_parents.append(tmp_parent)
                payloads = None
                if cols.payloads is not None:
                    payloads = [p for p, k in zip(cols.payloads, mask) if k]
                extra_meta = {
                    catalog: resource,
                    "group": group,
                    "catalog": catalog,
                    # unique per seal: receiver-side dedup must distinguish
                    # re-delivery of THIS part from an independent later
                    # seal of byte-identical content (client retry batch)
                    "seal_session": session,
                }
                if catalog == "trace":
                    extra_meta["ordered_tags"] = list(
                        self._trace_meta.get(key, ())
                    )
                PartWriter.write(
                    tmp_parent / "part-000000",
                    ts=cols.ts[mask],
                    series=cols.series[mask],
                    version=cols.version[mask],
                    tag_codes={t: v[mask] for t, v in cols.tags.items()},
                    tag_dicts=dict(cols.dicts),
                    fields={f: v[mask] for f, v in cols.fields.items()},
                    extra_meta=extra_meta,
                    payloads=payloads,
                )
                staged.append((tmp_parent, final_parent))
            for tmp_parent, final_parent in staged:
                tmp_parent.rename(final_parent)
                sealed.append((group, shard, final_parent / "part-000000"))
            with self._lock:
                self._pending.extend(sealed)
        except Exception:
            # undo everything (renamed-but-unregistered parts included):
            # the restored rows below are the single surviving copy
            for tmp_parent in tmp_parents:
                shutil.rmtree(tmp_parent, ignore_errors=True)
            for _g, _s, part_dir in sealed:
                shutil.rmtree(part_dir.parent, ignore_errors=True)
            # restore the rows: seal again next tick (merge into any new
            # buffer created meanwhile)
            with self._lock:
                cur = self._buffers.get(key)
                if cur is None or len(cur) == 0:
                    self._buffers[key] = buf
                else:
                    snap = buf.snapshot_columns()
                    cur.append_bulk(
                        snap.ts,
                        snap.series,
                        snap.version,
                        {
                            t: [snap.dicts[t][c] for c in snap.tags[t]]
                            for t in snap.tags
                        },
                        dict(snap.fields),
                        payloads=snap.payloads,
                    )
            raise

    def seal_all(self) -> None:
        with self._lock:
            keys = list(self._buffers.keys())
        errors = []
        for key in keys:
            try:
                self._seal(key)
            except Exception as e:  # noqa: BLE001 - other keys still seal
                errors.append(e)
        if errors:
            raise errors[0]

    def ship_pending(self) -> tuple[int, int]:
        """Try to ship every sealed part; -> (shipped, failed)."""
        with self._lock:
            pending, self._pending = self._pending, []
        shipped = failed = 0
        still: list[tuple[str, int, Path]] = []
        for group, shard, part_dir in pending:
            try:
                self.shipper(group, shard, part_dir)
                shutil.rmtree(part_dir.parent, ignore_errors=True)
                shipped += 1
            except Exception:  # noqa: BLE001 - retried next tick
                still.append((group, shard, part_dir))
                failed += 1
        with self._lock:
            self._pending.extend(still)
        return shipped, failed

    def flush(self) -> tuple[int, int]:
        """Seal everything and attempt shipping (one tick, also the test
        hook)."""
        self.seal_all()
        return self.ship_pending()

    def pending_parts(self) -> int:
        with self._lock:
            return len(self._pending)

    def buffered_rows(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._buffers.values())

    # -- lifecycle ----------------------------------------------------------
    def _recover_spool(self) -> list[tuple[str, int, Path]]:
        out = []
        for d in sorted(self.spool.iterdir()) if self.spool.exists() else []:
            if d.is_dir() and d.name.startswith(".tmp"):
                # crashed mid-seal: rows never left the (lost) buffer OR
                # were restored and resealed — either way this is garbage
                shutil.rmtree(d, ignore_errors=True)
                continue
            if not d.is_dir() or "@" not in d.name:
                continue
            try:
                group, _measure, shard, _session = d.name.split("@", 3)
                part_dir = d / "part-000000"
                if (part_dir / "metadata.json").exists():
                    out.append((group, int(shard), part_dir))
                else:  # crashed mid-write: the part is not durable yet
                    shutil.rmtree(d, ignore_errors=True)
            except (ValueError, OSError):
                continue
        return out

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        import logging

        log = logging.getLogger("banyandb.wqueue")

        def loop():
            while not self._stop.wait(self.flush_interval_s):
                try:
                    self.flush()
                except Exception:  # noqa: BLE001 - the loop must survive
                    log.exception("wqueue flush tick failed (rows retained)")

        self._thread = threading.Thread(target=loop, daemon=True, name="wqueue")
        self._thread.start()

    def stop(self, final_flush: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if final_flush:
            self.flush()
