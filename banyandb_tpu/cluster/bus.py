"""Typed topic bus (pkg/bus analog).

Topics carry JSON-serializable envelopes; handlers are registered per
topic and return reply payloads.  The bus is the single dispatch surface
both transports target: LocalTransport calls handle() in-process, the
gRPC server calls the same handle() from its service method.
"""

from __future__ import annotations

import enum
import threading
from typing import Callable


class Topic(str, enum.Enum):
    # write plane (api/data/data.go topic registry analog)
    MEASURE_WRITE = "measure-write"
    # columnar write envelope: base64-packed ts/field arrays +
    # optionally dictionary-encoded tag columns — the wire shape of the
    # vectorized ingest path (10x less per-point JSON than MEASURE_WRITE)
    MEASURE_WRITE_COLUMNS = "measure-write-cols"
    STREAM_WRITE = "stream-write"
    TRACE_WRITE = "trace-write"
    PROPERTY_APPLY = "property-apply"
    # query plane
    MEASURE_QUERY_PARTIAL = "measure-query-partial"
    MEASURE_QUERY_RAW = "measure-query-raw"
    STREAM_QUERY = "stream-query"
    TRACE_QUERY_BY_ID = "trace-query-by-id"
    TRACE_QUERY_ORDERED = "trace-query-ordered"
    # full trace query surface: criteria/projection/order-by QueryRequest
    # scattered per shard set, span rows + sidx keys back
    TRACE_QUERY_EXEC = "trace-query-exec"
    PROPERTY_QUERY = "property-query"
    # schema + control plane
    SCHEMA_SYNC = "schema-sync"
    SCHEMA_GET = "schema-get"  # barrier verification: per-object hash
    HEALTH = "health"
    # chunked part sync (cluster/v1/rpc.proto SyncPart analog)
    SYNC_PART = "sync-part"


Handler = Callable[[dict], dict]


class LocalBus:
    """Topic -> handler registry with thread-safe dispatch."""

    def __init__(self):
        self._handlers: dict[str, Handler] = {}
        self._lock = threading.Lock()

    def subscribe(self, topic: "Topic | str", handler: Handler) -> None:
        with self._lock:
            key = topic.value if isinstance(topic, Topic) else topic
            self._handlers[key] = handler

    def handle(self, topic: str, envelope: dict) -> dict:
        h = self._handlers.get(topic)
        if h is None:
            raise KeyError(f"no handler for topic {topic}")
        return h(envelope)

    def topics(self) -> list[str]:
        """Registered topic names (HealthCheck's service inventory)."""
        with self._lock:
            return list(self._handlers)
