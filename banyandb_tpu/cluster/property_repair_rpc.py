"""Property repair + gossip over the wire.

The reference reconciles property replicas with a bidirectional
RepairService stream driven through a gossip scheduler
(banyand/property/db/repair.go, db/repair_gossip.go,
api/proto/banyandb/property/v1/repair.proto:113, gossip.proto:46,
docs/concept/property-repair.md).  This module serves the SAME proto
message shapes on the repo's GrpcBusServer:

  compare stage:  client TreeRoot        -> server RootCompare
                  client TreeSlots       -> server DifferTreeSummary
  repair stage:   client PropertyMissing -> server PropertySyncWithFrom
                  client PropertySync    -> server PropertySyncWithFrom

One deliberate simplification vs upstream: every repair-stage request
gets exactly one response (an empty PropertySyncWithFrom means "nothing
for you"), keeping the bidi exchange in lockstep — the reference
pipelines asynchronously.  Conflict resolution carries mod_revision in
a reserved "@mod" tag on the wire (the upstream Property message has no
revision field; it resolves by delete_time/updated_at instead) — higher
revision wins, and installs preserve the winner's revision verbatim so
both trees converge to identical SHAs.
"""

from __future__ import annotations

import queue
from typing import Callable

import grpc

from banyandb_tpu.api import pb
from banyandb_tpu.models import property_repair
from banyandb_tpu.models.property import Property

REPAIR_SERVICE = "banyandb.property.v1.RepairService"
REPAIR_METHOD = f"/{REPAIR_SERVICE}/Repair"
GOSSIP_SERVICE = "banyandb.property.v1.GossipService"
GOSSIP_METHOD = f"/{GOSSIP_SERVICE}/Propagation"

_MOD_TAG = "@mod"
_CREATE_TAG = "@create"


def _prop_to_pb(p: Property):
    rpb = pb.property_property_pb2
    out = rpb.Property()
    out.metadata.group = p.group
    out.metadata.name = p.name
    out.id = p.id
    for k, v in sorted(p.tags.items()):
        tag = out.tags.add(key=k)
        tag.value.str.value = str(v)
    mod = out.tags.add(key=_MOD_TAG)
    mod.value.str.value = str(p.mod_revision)
    cre = out.tags.add(key=_CREATE_TAG)
    cre.value.str.value = str(p.create_revision)
    return out


def _prop_from_pb(msg) -> Property:
    tags, mod, cre = {}, 0, 0
    for tag in msg.tags:
        if tag.key == _MOD_TAG:
            mod = int(tag.value.str.value or 0)
        elif tag.key == _CREATE_TAG:
            cre = int(tag.value.str.value or 0)
        else:
            tags[tag.key] = tag.value.str.value
    return Property(
        group=msg.metadata.group,
        name=msg.metadata.name,
        id=msg.id,
        tags=tags,
        mod_revision=mod,
        create_revision=cre,
    )


def _split_entity(entity: str) -> tuple[str, str]:
    name, _, pid = entity.partition("/")
    return name, pid


# -- server ------------------------------------------------------------------


def repair_behavior(engine) -> Callable:
    """Bidi handler bound to this node's PropertyEngine."""
    rpb = pb.property_repair_pb2

    def behavior(request_iterator, context):
        group = ""
        shard = 0
        tree: dict = {}
        installed = False
        for req in request_iterator:
            which = req.WhichOneof("data")
            if which == "tree_root":
                group = req.tree_root.group
                shard = int(req.tree_root.shard_id)
                tree = property_repair.build_shard_tree(engine, group, shard)
                yield rpb.RepairResponse(
                    root_compare=rpb.RootCompare(
                        tree_found=True,
                        root_sha_match=(
                            req.tree_root.root_sha == tree["root"]
                        ),
                    )
                )
            elif which == "tree_slots":
                client = {
                    str(s.slot): s.value for s in req.tree_slots.slot_sha
                }
                mine = tree.get("slots", {})
                differ = [
                    s
                    for s in set(client) | set(mine)
                    if client.get(s) != mine.get(s)
                ]
                nodes = []
                for s in sorted(differ, key=int):
                    mine_leaves = tree.get("leaves", {}).get(s, [])
                    if not mine_leaves:
                        nodes.append(
                            rpb.TreeLeafNode(slot_index=int(s), exists=False)
                        )
                        continue
                    for entity, sha in mine_leaves:
                        nodes.append(
                            rpb.TreeLeafNode(
                                slot_index=int(s),
                                exists=True,
                                entity=entity,
                                sha=sha,
                            )
                        )
                yield rpb.RepairResponse(
                    differ_tree_summary=rpb.DifferTreeSummary(nodes=nodes)
                )
            elif which == "wait_next_differ":
                yield rpb.RepairResponse(
                    differ_tree_summary=rpb.DifferTreeSummary(nodes=[])
                )
            elif which == "property_missing":
                name, pid = _split_entity(req.property_missing.entity)
                p = engine.get(group, name, pid)
                resp = rpb.PropertySyncWithFrom()
                if p is not None:
                    # 'from' is a Python keyword; protobuf exposes it via setattr
                    setattr(resp, "from", 1)  # MISSING: client lacks it
                    resp.property.id = req.property_missing.entity.encode()
                    resp.property.property.CopyFrom(_prop_to_pb(p))
                yield rpb.RepairResponse(property_sync=resp)
            elif which == "property_sync":
                theirs = _prop_from_pb(req.property_sync.property)
                mine = engine.get(theirs.group, theirs.name, theirs.id)
                resp = rpb.PropertySyncWithFrom()
                if mine is None or property_repair.wins(theirs, mine):
                    property_repair.install_verbatim(engine, theirs)
                    installed = True
                    # lockstep ack: from=MISSING with no property means
                    # "server took yours" (upstream pipelines these
                    # asynchronously and needs no ack)
                    setattr(resp, "from", 1)
                elif property_repair.wins(mine, theirs):
                    setattr(resp, "from", 2)  # SYNC: server side is newer
                    resp.property.id = (
                        f"{mine.name}/{mine.id}".encode()
                    )
                    resp.property.property.CopyFrom(_prop_to_pb(mine))
                yield rpb.RepairResponse(property_sync=resp)
            else:
                # lockstep invariant: EVERY request gets a response, even
                # one whose oneof we do not recognize — silence here
                # deadlocks the exchange
                yield rpb.RepairResponse(
                    differ_tree_summary=rpb.DifferTreeSummary(nodes=[])
                )
        # stream over: docs installed for the client must survive a
        # server restart (the client persists its own side in finally)
        if installed and group:
            engine.persist_group(group)

    return behavior


def generic_handler(engine):
    rpb = pb.property_repair_pb2
    return grpc.method_handlers_generic_handler(
        REPAIR_SERVICE,
        {
            "Repair": grpc.stream_stream_rpc_method_handler(
                repair_behavior(engine),
                request_deserializer=rpb.RepairRequest.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )
        },
    )


# -- client ------------------------------------------------------------------

_SENTINEL = object()


def repair_with_peer(channel, engine, group: str, shard: int) -> int:
    """Drive one full repair round against a peer; returns docs copied
    in either direction.  Raises on transport failure mid-round — the
    caller (gossip scheduler) retries; every exchange is idempotent."""
    rpb = pb.property_repair_pb2
    stub = channel.stream_stream(
        REPAIR_METHOD,
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=rpb.RepairResponse.FromString,
    )
    reqq: queue.Queue = queue.Queue()
    call = stub(iter(reqq.get, _SENTINEL))
    copied = 0
    try:
        tree = property_repair.build_shard_tree(engine, group, shard)
        req = rpb.RepairRequest()
        req.tree_root.group = group
        req.tree_root.shard_id = shard
        req.tree_root.root_sha = tree["root"]
        reqq.put(req)
        rc = next(call).root_compare
        if rc.root_sha_match:
            return 0

        req = rpb.RepairRequest()
        req.tree_slots.SetInParent()  # an EMPTY slot set must still set
        # the oneof, or the server sees a dataless request and the
        # lockstep exchange deadlocks
        for s, v in tree["slots"].items():
            req.tree_slots.slot_sha.add(slot=int(s), value=v)
        reqq.put(req)
        summary = next(call).differ_tree_summary

        # index the server's leaves by slot
        server_leaves: dict[int, dict[str, str]] = {}
        server_slots: set[int] = set()
        for n in summary.nodes:
            server_slots.add(n.slot_index)
            if n.exists:
                server_leaves.setdefault(n.slot_index, {})[n.entity] = n.sha

        my_leaves: dict[int, dict[str, str]] = {}
        for s, lst in tree["leaves"].items():
            my_leaves[int(s)] = {e: h for e, h in lst}

        # every slot the server called out, plus slots it lacks entirely
        for s in sorted(server_slots | set(my_leaves)):
            srv = server_leaves.get(s, {})
            if s not in server_slots:
                continue  # slot SHAs matched; nothing to reconcile
            mine = my_leaves.get(s, {})
            for entity in sorted(set(srv) | set(mine)):
                if srv.get(entity) == mine.get(entity):
                    continue
                if entity not in mine:
                    # case 1: client missing, server existing
                    req = rpb.RepairRequest()
                    req.property_missing.entity = entity
                    reqq.put(req)
                    resp = next(call).property_sync
                    if getattr(resp, "from") == 1 and resp.property.HasField("property"):
                        property_repair.install_verbatim(
                            engine, _prop_from_pb(resp.property.property)
                        )
                        copied += 1
                else:
                    # case 2/3: client existing, server missing or differs
                    name, pid = _split_entity(entity)
                    mine_p = engine.get(group, name, pid)
                    if mine_p is None:
                        continue
                    req = rpb.RepairRequest()
                    req.property_sync.id = entity.encode()
                    req.property_sync.property.CopyFrom(_prop_to_pb(mine_p))
                    reqq.put(req)
                    resp = next(call).property_sync
                    if getattr(resp, "from") == 2 and resp.property.HasField("property"):
                        property_repair.install_verbatim(
                            engine, _prop_from_pb(resp.property.property)
                        )
                        copied += 1  # pulled the server's newer doc
                    elif getattr(resp, "from") == 1:
                        copied += 1  # server took ours (install ack)
                    # from=0: nothing moved on either side
        return copied
    finally:
        reqq.put(_SENTINEL)
        try:
            call.cancel()
        except Exception:  # noqa: BLE001
            pass
        engine.persist_group(group)


# -- gossip scheduler --------------------------------------------------------


class PropertyGossip:
    """Propagation handler + initiator (repair_gossip.go analog).

    On Propagation(group, shard): repair with the NEXT node in
    context.nodes (ring order), then forward the request with
    current_propagation_count+1 until max_propagation_count.  Any node
    failure stops this round; the next scheduled round retries — rounds
    are idempotent.
    """

    def __init__(self, node_name: str, engine, channel_of: Callable[[str], object]):
        self.node_name = node_name
        self.engine = engine
        self.channel_of = channel_of  # node name -> grpc channel
        self.rounds = 0

    def behavior(self, request, context):
        gpb = pb.property_gossip_pb2
        ctx = request.context
        self._run(request, ctx)
        return gpb.PropagationResponse()

    def _run(self, request, ctx) -> None:
        if ctx.current_propagation_count >= ctx.max_propagation_count:
            return
        nodes = list(ctx.nodes)
        if self.node_name not in nodes:
            return
        nxt = nodes[(nodes.index(self.node_name) + 1) % len(nodes)]
        if nxt == self.node_name:
            return
        chan = self.channel_of(nxt)
        repair_with_peer(
            chan, self.engine, request.group, int(request.shard_id)
        )
        self.rounds += 1
        fwd = pb.property_gossip_pb2.PropagationRequest()
        fwd.CopyFrom(request)
        fwd.context.current_propagation_count = (
            ctx.current_propagation_count + 1
        )
        if fwd.context.current_propagation_count >= ctx.max_propagation_count:
            return
        stub = chan.unary_unary(
            GOSSIP_METHOD,
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=pb.property_gossip_pb2.PropagationResponse.FromString,
        )
        stub(fwd)

    def start_round(
        self, nodes: list[str], group: str, shard: int, max_hops: int = 0
    ) -> None:
        """Initiate a propagation round from this node."""
        gpb = pb.property_gossip_pb2
        req = gpb.PropagationRequest()
        req.context.nodes.extend(nodes)
        req.context.max_propagation_count = max_hops or len(nodes)
        req.context.current_propagation_count = 0
        req.context.origin_node = self.node_name
        req.group = group
        req.shard_id = shard
        self._run(req, req.context)

    def generic_handler(self):
        gpb = pb.property_gossip_pb2
        return grpc.method_handlers_generic_handler(
            GOSSIP_SERVICE,
            {
                "Propagation": grpc.unary_unary_rpc_method_handler(
                    self.behavior,
                    request_deserializer=gpb.PropagationRequest.FromString,
                    response_serializer=lambda m: m.SerializeToString(),
                )
            },
        )
