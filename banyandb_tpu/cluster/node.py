"""Node registry + replica placement (pkg/node/round_robin.go analog)."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class NodeInfo:
    name: str
    addr: str  # transport address ("local:<name>" or "host:port")
    roles: tuple[str, ...] = ("data",)
    # lifecycle stages this node serves (hot/warm/cold tier labels,
    # banyand/queue/pub/stage.go ResolveStage analog); empty = all stages
    stages: tuple[str, ...] = ()

    def serves_stage(self, stage: str) -> bool:
        return not self.stages or stage in self.stages


class RoundRobinSelector:
    """Deterministic shard -> replica-ordered node list.

    node for (shard, replica r) = nodes[(shard + r) % len(nodes)]
    (pkg/node/round_robin.go:219-248 contract): every node gets an equal
    share of primaries and replicas follow consecutively.
    """

    def __init__(self, nodes: list[NodeInfo], replicas: int = 0):
        self.nodes = sorted(nodes, key=lambda n: n.name)
        self.replicas = replicas

    def replica_set(self, shard: int) -> list[NodeInfo]:
        n = len(self.nodes)
        if n == 0:
            raise RuntimeError("no data nodes registered")
        count = min(self.replicas + 1, n)
        return [self.nodes[(shard + r) % n] for r in range(count)]

    def primary(self, shard: int, alive: set[str] | None = None) -> NodeInfo:
        """First alive node in the shard's replica order (failover walk)."""
        for node in self.replica_set(shard):
            if alive is None or node.name in alive:
                return node
        raise RuntimeError(f"no alive replica for shard {shard}")
