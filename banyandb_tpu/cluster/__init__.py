"""Cluster fabric: liaison/data roles over a pluggable message transport.

Analog of the reference's banyand/queue (pub/sub/local) + pkg/bus +
pkg/node + banyand/dquery: writes route by (group, shard) with
replica fan-out; queries scatter per-shard to primary-alive nodes and
reduce partial aggregates at the liaison (two rounds for percentile so
node histograms share a range).  Transports: in-process (standalone and
the reference's in-process multi-node test trick) and gRPC sockets.
"""

from banyandb_tpu.cluster.bus import Topic, LocalBus
from banyandb_tpu.cluster.node import NodeInfo, RoundRobinSelector
from banyandb_tpu.cluster.placement import (
    PlacementMap,
    PlacementSelector,
    StaleEpoch,
)
from banyandb_tpu.cluster.data_node import DataNode
from banyandb_tpu.cluster.liaison import Liaison
