"""Node discovery (banyand/metadata/discovery/{none,dns,file} analog).

- StaticDiscovery: fixed node list (discovery "none").
- FileDiscovery: watched JSON file of node records — the reference's
  file-based discovery AND its in-process cluster-test trick
  (pkg/test/setup NewDiscoveryFileWriter).  DNS SRV polling can plug in
  behind the same refresh() surface.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Callable, Optional

from banyandb_tpu.cluster.node import NodeInfo


class StaticDiscovery:
    def __init__(self, nodes: list[NodeInfo]):
        self._nodes = list(nodes)

    def nodes(self) -> list[NodeInfo]:
        return list(self._nodes)

    def refresh(self) -> bool:
        return False


class FileDiscovery:
    """Watched JSON file: [{"name": ..., "addr": ..., "roles": [...]}].

    refresh() re-reads when the mtime changed and returns True when the
    node set changed; callers (Liaison) rebuild their selector then.
    """

    def __init__(self, path: str | Path, on_change: Optional[Callable] = None):
        self.path = Path(path)
        self.on_change = None  # initial load is not a "change"
        self._mtime: tuple = (0, 0)
        self._nodes: list[NodeInfo] = []
        self.refresh()
        self.on_change = on_change

    @staticmethod
    def write(path: str | Path, nodes: list[NodeInfo]) -> None:
        """Test/ops helper: publish a node list (DiscoveryFileWriter)."""
        from banyandb_tpu.utils import fs

        fs.atomic_write_json(
            path,
            [
                {"name": n.name, "addr": n.addr, "roles": list(n.roles)}
                for n in nodes
            ],
        )

    def nodes(self) -> list[NodeInfo]:
        return list(self._nodes)

    def refresh(self) -> bool:
        try:
            st = self.path.stat()
            # ns mtime + size: whole-second mtime would miss rapid rewrites
            stamp = (st.st_mtime_ns, st.st_size)
        except FileNotFoundError:
            return False
        if stamp == self._mtime:
            return False
        self._mtime = stamp
        data = json.loads(self.path.read_text())
        new = [
            NodeInfo(d["name"], d["addr"], tuple(d.get("roles", ("data",))))
            for d in data
        ]
        changed = new != self._nodes
        self._nodes = new
        if changed and self.on_change:
            self.on_change(new)
        return changed
