"""Node discovery (banyand/metadata/discovery/{none,dns,file} analog).

- StaticDiscovery: fixed node list (discovery "none").
- FileDiscovery: watched JSON file of node records — the reference's
  file-based discovery AND its in-process cluster-test trick
  (pkg/test/setup NewDiscoveryFileWriter).
- DnsDiscovery: address-record polling of a service hostname (the
  headless-service shape of the reference's dns discovery; SRV-record
  ports can plug in behind the same resolver seam).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Callable, Optional

from banyandb_tpu.cluster.node import NodeInfo


class StaticDiscovery:
    def __init__(self, nodes: list[NodeInfo]):
        self._nodes = list(nodes)

    def nodes(self) -> list[NodeInfo]:
        return list(self._nodes)

    def refresh(self) -> bool:
        return False


class DnsDiscovery:
    """DNS-polling discovery (banyand/metadata/discovery/dns analog).

    Resolves a service hostname to its A/AAAA records each refresh();
    node names derive from the resolved IPs, the port is fixed.  The
    resolver is injectable (tests use a fake; production uses the
    default socket resolver).
    """

    def __init__(
        self,
        hostname: str,
        port: int,
        *,
        roles: tuple[str, ...] = ("data",),
        resolver: Optional[Callable[[str], list[str]]] = None,
        on_change: Optional[Callable] = None,
    ):
        self.hostname = hostname
        self.port = port
        self.roles = roles
        self._resolver = resolver or _default_resolver
        self.on_change = None
        self._nodes: list[NodeInfo] = []
        self.refresh()
        self.on_change = on_change

    def nodes(self) -> list[NodeInfo]:
        return list(self._nodes)

    def refresh(self) -> bool:
        try:
            ips = sorted(set(self._resolver(self.hostname)))
        except OSError:
            return False
        if not ips:
            # empty answer degrades exactly like a raising resolver: keep
            # the last-known node set (a transiently endpoint-less service
            # must not collapse the selector)
            return False
        new = [
            NodeInfo(
                f"{self.hostname}-{ip}", f"{_fmt_host(ip)}:{self.port}", self.roles
            )
            for ip in ips
        ]
        changed = new != self._nodes
        self._nodes = new
        if changed and self.on_change:
            self.on_change(new)
        return changed


def _fmt_host(ip: str) -> str:
    return f"[{ip}]" if ":" in ip else ip


def _default_resolver(hostname: str) -> list[str]:
    import socket

    return sorted(
        {
            info[4][0]
            for info in socket.getaddrinfo(
                hostname, None, type=socket.SOCK_STREAM
            )
        }
    )


class FileDiscovery:
    """Watched JSON file:
    [{"name": ..., "addr": ..., "roles": [...], "stages": [...]}]
    ("stages" optional; empty/absent = the node serves every tier).

    refresh() re-reads when the mtime changed and returns True when the
    node set changed; callers (Liaison) rebuild their selector then.
    """

    def __init__(self, path: str | Path, on_change: Optional[Callable] = None):
        self.path = Path(path)
        self.on_change = None  # initial load is not a "change"
        self._mtime: tuple = (0, 0)
        self._nodes: list[NodeInfo] = []
        self.refresh()
        self.on_change = on_change

    @staticmethod
    def write(path: str | Path, nodes: list[NodeInfo]) -> None:
        """Test/ops helper: publish a node list (DiscoveryFileWriter)."""
        from banyandb_tpu.utils import fs

        fs.atomic_write_json(
            path,
            [
                {
                    "name": n.name,
                    "addr": n.addr,
                    "roles": list(n.roles),
                    "stages": list(n.stages),
                }
                for n in nodes
            ],
        )

    def nodes(self) -> list[NodeInfo]:
        return list(self._nodes)

    def refresh(self) -> bool:
        try:
            st = self.path.stat()
            # ns mtime + size: whole-second mtime would miss rapid rewrites
            stamp = (st.st_mtime_ns, st.st_size)
        except FileNotFoundError:
            return False
        if stamp == self._mtime:
            return False
        self._mtime = stamp
        data = json.loads(self.path.read_text())
        new = [
            NodeInfo(
                d["name"],
                d["addr"],
                tuple(d.get("roles", ("data",))),
                tuple(d.get("stages", ())),
            )
            for d in data
        ]
        changed = new != self._nodes
        self._nodes = new
        if changed and self.on_change:
            self.on_change(new)
        return changed
